"""The jaxlint rule set: JL001–JL018 and JL022, the JAX hazards this
repo has actually paid for (docs/ROUND3.md, docs/ROUND5.md attribution
work, the serving layer's per-request-shape retrace class, the telemetry
layer's record-at-trace-time class, the serving pipeline's
blocking-read-in-dispatch-loop class, the startup phase's serial-warmup
class, the steady-state input pipeline's host-blocking-feed class, the
replica pool's per-replica-re-trace class, the fault-tolerance
layer's swallowed-dispatch-error class, the resilient trainer's
torn-file / uncadenced-checkpoint-write class, the elastic
runtime's unbounded-rendezvous / unsupervised-launch class, the
tail-latency layer's deadline-blind fixed-linger class, the fleet
tier's timeout-less blocking-network-read class, the host hot
path's float-list-JSON-in-a-serve-loop class, and the model
registry's weights-mutated-behind-the-registry class; JL019–JL021,
the concurrency pass, live in :mod:`.concurrency`).

Every rule is a heuristic over one module's AST — no type inference, no
cross-file call graph.  "Traced context" below means: a function that is
(a) decorated with a jax transform, (b) passed by name into a transform
call (``jax.jit(f)``, ``shard_map(f, ...)``, ``lax.scan(f, ...)`` …), or
(c) called (by name, same module) from another traced function, to a
fixpoint.  That per-module closure is what makes "``.item()`` somewhere
under ``fit``" findable without executing anything.

False positives are expected at the margin; the contract is that they are
cheap to waive (``# jaxlint: disable=RULE -- reason``) and the waiver is
visible in review.  See docs/ANALYSIS.md for the per-rule rationale.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .engine import Finding, ModuleContext, Rule, Severity

# ---------------------------------------------------------------------------
# Shared AST helpers


def dotted_name(node: ast.AST) -> str | None:
    """``jax.lax.scan`` for an Attribute chain rooted at a Name, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# Transform entry points whose function-valued arguments get traced.  Both
# fully-dotted and from-import spellings; the last segment alone is NOT
# matched (a user function named ``scan`` must not poison the analysis).
_TRANSFORM_CALLS = {
    "jax.jit", "jit", "pjit", "jax.pjit",
    "jax.pmap", "pmap",
    "jax.shard_map", "shard_map", "jax.experimental.shard_map.shard_map",
    "jax.grad", "grad", "jax.value_and_grad", "value_and_grad",
    "jax.vmap", "vmap",
    "jax.checkpoint", "jax.remat", "remat",
    "jax.lax.scan", "lax.scan",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.cond", "lax.cond",
    "jax.lax.switch", "lax.switch",
    "jax.lax.map", "lax.map",
    "jax.custom_jvp", "jax.custom_vjp",
    "jax.linearize", "jax.vjp", "jax.jvp",
}

# The subset that builds a *compiled callable with its own trace cache* —
# constructing one of these inside a loop is a retrace generator (JL004).
_JIT_CONSTRUCTORS = {
    "jax.jit", "jit", "pjit", "jax.pjit", "jax.pmap", "pmap",
}

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def iter_own_body(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a def/lambda body WITHOUT descending into nested scopes.

    Nested defs get their own traced-or-not classification (via the call
    graph), so descending here would double-report their findings under
    the wrong function.
    """
    if isinstance(fn, ast.Lambda):
        stack: list[ast.AST] = [fn.body]
    else:
        stack = list(getattr(fn, "body", []))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _SCOPE_NODES):
            stack.extend(ast.iter_child_nodes(node))


def iter_loop_body_nodes(loop: ast.AST) -> Iterator[ast.AST]:
    """Nodes executed by a loop's body, not descending into nested scopes.

    A function merely *defined* inside the loop runs elsewhere — flagging
    its body as per-iteration work would be a false positive (its own
    call sites get their own classification).
    """
    stack: list[ast.AST] = list(loop.body) + list(loop.orelse)
    while stack:
        node = stack.pop()
        if isinstance(node, _SCOPE_NODES):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def is_jit_value(value: ast.AST) -> bool:
    """Is this expression a jit-compiled callable?  True for the jit
    constructors (``jax.jit(...)``/``pjit``/``pmap``) and for
    ``RecompileSentinel(...)``, which wraps a jitted callable by contract
    (sentinel.py rejects anything else at runtime).

    The single source of truth for "is this name/attr a jit or launch
    target" — JL007/JL009/JL010/JL011/JL013/JL016 and the concurrency
    pass all resolve through here (it had drifted into three near-copies
    before PR 16).
    """
    if not isinstance(value, ast.Call):
        return False
    name = dotted_name(value.func)
    if name in _JIT_CONSTRUCTORS:
        return True
    return bool(name) and name.split(".")[-1] == "RecompileSentinel"


def module_jit_names(tree: ast.Module) -> set[str]:
    """Module-level names bound to jitted callables — visible inside
    every function (the ``predict = jax.jit(...)`` -> ``def serve(...)``
    shape)."""
    out: set[str] = set()
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and is_jit_value(node.value)):
            out.add(node.targets[0].id)
    return out


def jit_attr_names(tree: ast.Module) -> set[str]:
    """Attribute names bound to jitted callables anywhere in the module
    (``self._predict = RecompileSentinel(jax.jit(...))``)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and is_jit_value(node.value):
            for target in node.targets:
                if isinstance(target, ast.Attribute):
                    out.add(target.attr)
    return out


def is_jit_call(node: ast.AST, jit_names: set[str], jit_attrs: set[str]) -> bool:
    """Does this Call dispatch through a known jitted name or attr?"""
    if not isinstance(node, ast.Call):
        return False
    if isinstance(node.func, ast.Name) and node.func.id in jit_names:
        return True
    return isinstance(node.func, ast.Attribute) and node.func.attr in jit_attrs


def iter_scope_nodes(scope: ast.AST) -> list[ast.AST]:
    """All nodes of one scope, not descending into nested scopes: a
    module's top-level statements flattened (so ``if __name__`` guards
    and try/except import shims are transparent), or a def/lambda body
    via :func:`iter_own_body`."""
    if isinstance(scope, ast.Module):
        nodes: list[ast.AST] = []
        stack: list[ast.AST] = list(scope.body)
        while stack:
            node = stack.pop()
            nodes.append(node)
            if not isinstance(node, _SCOPE_NODES):
                stack.extend(ast.iter_child_nodes(node))
        return nodes
    return list(iter_own_body(scope))


def _decorator_is_transform(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name in _TRANSFORM_CALLS:
        return True
    if isinstance(dec, ast.Call):
        # @partial(jax.jit, ...) / @functools.partial(jit, ...)
        if dotted_name(dec.func) in {"partial", "functools.partial"}:
            return any(dotted_name(a) in _TRANSFORM_CALLS for a in dec.args)
        return dotted_name(dec.func) in _TRANSFORM_CALLS
    return False


class TraceAnalysis:
    """Which defs/lambdas in a module execute under a jax trace."""

    def __init__(self, tree: ast.Module):
        self.defs: list[ast.AST] = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
        ]
        by_name: dict[str, list[ast.AST]] = {}
        for d in self.defs:
            if not isinstance(d, ast.Lambda):
                by_name.setdefault(d.name, []).append(d)

        self.traced: set[ast.AST] = set()
        for d in self.defs:
            if any(_decorator_is_transform(dec)
                   for dec in getattr(d, "decorator_list", [])):
                self.traced.add(d)

        # Functions handed to a transform by name (or as a lambda literal).
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in _TRANSFORM_CALLS:
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    self.traced.update(by_name.get(arg.id, []))
                elif isinstance(arg, ast.Lambda):
                    self.traced.add(arg)

        # Same-module transitive closure: a call by bare name from a traced
        # body marks the callee traced ("fit-reachable" within the module).
        callees: dict[ast.AST, set[str]] = {}
        for d in self.defs:
            names = set()
            for node in iter_own_body(d):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    names.add(node.func.id)
            callees[d] = names
        changed = True
        while changed:
            changed = False
            for d in list(self.traced):
                for name in callees.get(d, ()):
                    for cand in by_name.get(name, []):
                        if cand not in self.traced:
                            self.traced.add(cand)
                            changed = True

    def traced_defs(self) -> list[ast.AST]:
        return [d for d in self.defs if d in self.traced]


def get_trace_analysis(ctx: ModuleContext) -> TraceAnalysis:
    cached = getattr(ctx, "_trace_analysis", None)
    if cached is None:
        cached = TraceAnalysis(ctx.tree)
        ctx._trace_analysis = cached  # type: ignore[attr-defined]
    return cached


def _fn_label(fn: ast.AST) -> str:
    return "<lambda>" if isinstance(fn, ast.Lambda) else fn.name


# ---------------------------------------------------------------------------
# JL001 — PRNG key reuse


_KEY_CONSUMERS = {
    "split", "normal", "uniform", "bernoulli", "randint", "permutation",
    "shuffle", "choice", "categorical", "gumbel", "truncated_normal",
    "dirichlet", "beta", "gamma", "poisson", "exponential", "laplace",
    "cauchy", "rademacher", "bits", "orthogonal", "t", "multivariate_normal",
    "loggamma", "ball", "maxwell", "binomial",
}
# fold_in / PRNGKey derive without consuming; they are deliberately absent.
_KEY_PREFIXES = ("jax.random.", "random.", "jr.", "jrandom.")

# Bare (from-import) spellings are only matched for names unambiguous
# enough that a collision with an ordinary local helper is implausible.
# Generic English words (`t`, `choice`, `shuffle`, `beta`, `normal`, ...)
# need the module prefix — JL001 is an ERROR, so precision wins.
_BARE_CONSUMERS = {
    "split", "bernoulli", "categorical", "gumbel", "dirichlet",
    "rademacher", "truncated_normal", "multivariate_normal", "loggamma",
}


def _consumer_call(node: ast.Call) -> str | None:
    """The sampler name if this call consumes a PRNG key, else None."""
    name = dotted_name(node.func)
    if name is None:
        return None
    if name in _BARE_CONSUMERS:  # from jax.random import split, bernoulli
        return name
    for prefix in _KEY_PREFIXES:
        if name.startswith(prefix) and name[len(prefix):] in _KEY_CONSUMERS:
            return name
    return None


class KeyReuseRule(Rule):
    """JL001: a PRNG key passed to a second sampler without a re-split.

    Reusing a key makes two "independent" draws identical — silently
    correlated dropout masks / init values, the kind of bug no test that
    only checks shapes ever catches.
    """

    rule_id = "JL001"
    severity = Severity.ERROR
    summary = "PRNG key reused after being consumed; split it instead"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        analysis = get_trace_analysis(ctx)
        reported: set[tuple[str, int]] = set()
        scopes: list[tuple[ast.AST, list[ast.stmt]]] = [(ctx.tree, ctx.tree.body)]
        for d in analysis.defs:
            if not isinstance(d, ast.Lambda):
                scopes.append((d, d.body))
        for _scope, body in scopes:
            state: dict[str, tuple[int, str]] = {}
            yield from self._scan_stmts(ctx, body, state, reported)

    # -- ordered scan ------------------------------------------------------

    def _scan_stmts(self, ctx, stmts, state, reported) -> Iterator[Finding]:
        for stmt in stmts:
            yield from self._scan_stmt(ctx, stmt, state, reported)

    def _scan_stmt(self, ctx, stmt, state, reported) -> Iterator[Finding]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            state.pop(stmt.name, None)
            return
        if isinstance(stmt, ast.Assign):
            yield from self._scan_expr(ctx, stmt.value, state, reported)
            for target in stmt.targets:
                self._reset_target(target, state)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                yield from self._scan_expr(ctx, stmt.value, state, reported)
            self._reset_target(stmt.target, state)
        elif isinstance(stmt, ast.If):
            yield from self._scan_expr(ctx, stmt.test, state, reported)
            snapshot = dict(state)
            yield from self._scan_stmts(ctx, stmt.body, state, reported)
            after_body = dict(state)
            state.clear()
            state.update(snapshot)
            yield from self._scan_stmts(ctx, stmt.orelse, state, reported)
            # Join: consumed on either branch counts as consumed after.
            state.update(after_body)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            yield from self._scan_expr(ctx, stmt.iter, state, reported)
            self._reset_target(stmt.target, state)
            # Two passes over the body: the second catches a key consumed in
            # iteration k and reused (not re-split) in iteration k+1.
            yield from self._scan_stmts(ctx, stmt.body, state, reported)
            self._reset_target(stmt.target, state)
            yield from self._scan_stmts(ctx, stmt.body, state, reported)
            yield from self._scan_stmts(ctx, stmt.orelse, state, reported)
        elif isinstance(stmt, ast.While):
            yield from self._scan_expr(ctx, stmt.test, state, reported)
            yield from self._scan_stmts(ctx, stmt.body, state, reported)
            yield from self._scan_stmts(ctx, stmt.body, state, reported)
            yield from self._scan_stmts(ctx, stmt.orelse, state, reported)
        elif isinstance(stmt, ast.With) or isinstance(stmt, ast.AsyncWith):
            for item in stmt.items:
                yield from self._scan_expr(ctx, item.context_expr, state, reported)
                if item.optional_vars is not None:
                    self._reset_target(item.optional_vars, state)
            yield from self._scan_stmts(ctx, stmt.body, state, reported)
        elif isinstance(stmt, ast.Try):
            yield from self._scan_stmts(ctx, stmt.body, state, reported)
            for handler in stmt.handlers:
                yield from self._scan_stmts(ctx, handler.body, state, reported)
            yield from self._scan_stmts(ctx, stmt.orelse, state, reported)
            yield from self._scan_stmts(ctx, stmt.finalbody, state, reported)
        else:
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    yield from self._scan_expr(ctx, child, state, reported)

    def _scan_expr(self, ctx, expr, state, reported) -> Iterator[Finding]:
        if isinstance(expr, ast.Lambda):
            return
        if isinstance(expr, ast.NamedExpr):
            yield from self._scan_expr(ctx, expr.value, state, reported)
            self._reset_target(expr.target, state)
            return
        if isinstance(expr, ast.Call):
            for child in ast.iter_child_nodes(expr):
                if isinstance(child, ast.expr) and child is not expr.func:
                    yield from self._scan_expr(ctx, child, state, reported)
            sampler = _consumer_call(expr)
            if sampler and expr.args and isinstance(expr.args[0], ast.Name):
                key_name = expr.args[0].id
                if key_name in state:
                    first_line, first_sampler = state[key_name]
                    mark = (key_name, expr.lineno)
                    if mark not in reported:
                        reported.add(mark)
                        yield self.finding(
                            ctx, expr,
                            f"PRNG key '{key_name}' reused by {sampler} but "
                            f"already consumed by {first_sampler} (line "
                            f"{first_line}); derive fresh keys with "
                            "jax.random.split/fold_in instead",
                        )
                else:
                    state[key_name] = (expr.lineno, sampler)
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                yield from self._scan_expr(ctx, child, state, reported)

    @staticmethod
    def _reset_target(target: ast.AST, state: dict) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                state.pop(node.id, None)


# ---------------------------------------------------------------------------
# JL002 — host-device sync inside traced code


_NP_HOST_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_SYNC_METHODS = {"item", "tolist", "to_py"}


class HostSyncRule(Rule):
    """JL002: ``.item()`` / ``float(tracer)`` / ``np.asarray`` under trace.

    Under ``jit`` these either fail at trace time (ConcretizationTypeError)
    or — worse, when the function sometimes runs untraced — silently force
    a device→host round trip that stalls the TPU pipeline every step.
    """

    rule_id = "JL002"
    severity = Severity.ERROR
    summary = "host-device synchronization inside a traced function"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        analysis = get_trace_analysis(ctx)
        for fn in analysis.traced_defs():
            label = _fn_label(fn)
            static_names = self._static_int_names(fn)
            for node in iter_own_body(fn):
                if isinstance(node, ast.Call):
                    yield from self._check_call(ctx, node, label, static_names)
                elif isinstance(node, (ast.If, ast.While)):
                    test = node.test
                    name = dotted_name(test.func) if isinstance(test, ast.Call) else None
                    if name and (name.startswith("jnp.") or name.startswith("jax.numpy.")):
                        yield self.finding(
                            ctx, test,
                            f"implicit bool() on a traced value in '{label}' "
                            f"({name}(...) used as a branch condition); use "
                            "jax.lax.cond/jnp.where for traced control flow",
                        )

    @staticmethod
    def _static_int_names(fn: ast.AST) -> set[str]:
        """Names bound from ``x.shape`` (un)packing in this body.

        Shape elements are static Python ints during tracing, so
        ``float(d)`` after ``b, t, h, d = q.shape`` is idiomatic JAX, not
        a host sync — exempt those names from the concretization check.
        """
        names: set[str] = set()
        for node in iter_own_body(fn):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            is_shape = (
                (isinstance(value, ast.Attribute) and value.attr == "shape")
                or (isinstance(value, ast.Subscript)
                    and isinstance(value.value, ast.Attribute)
                    and value.value.attr == "shape")
            )
            if not is_shape:
                continue
            for target in node.targets:
                for sub in ast.walk(target):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
        return names

    def _check_call(
        self, ctx, node: ast.Call, label: str, static_names: set[str]
    ) -> Iterator[Finding]:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _SYNC_METHODS:
            if not node.args and not node.keywords:
                yield self.finding(
                    ctx, node,
                    f".{func.attr}() inside traced function '{label}' forces "
                    "a device sync (or fails under jit); return the array "
                    "and read it on the host side",
                )
            return
        name = dotted_name(func)
        if name in _NP_HOST_CALLS:
            yield self.finding(
                ctx, node,
                f"{name}(...) inside traced function '{label}' pulls the "
                "value to host numpy; use jnp.* under trace and convert "
                "outside the jitted boundary",
            )
        elif name in {"jax.device_get", "device_get"}:
            yield self.finding(
                ctx, node,
                f"jax.device_get inside traced function '{label}'; device "
                "transfers belong outside the jitted boundary",
            )
        elif name in {"float", "int", "bool"} and len(node.args) == 1:
            arg = node.args[0]
            # Static under trace: literals, shape-derived ints, len() (a
            # traced len() already fails loudly at trace time), and
            # x.shape[i] / x.ndim attribute reads.
            if isinstance(arg, ast.Constant):
                return
            if isinstance(arg, ast.Name) and arg.id in static_names:
                return
            if isinstance(arg, ast.Call) and dotted_name(arg.func) == "len":
                return
            if isinstance(arg, ast.Attribute) and arg.attr in {"shape", "ndim"}:
                return
            if (isinstance(arg, ast.Subscript)
                    and isinstance(arg.value, ast.Attribute)
                    and arg.value.attr == "shape"):
                return
            yield self.finding(
                ctx, node,
                f"{name}(...) on a non-literal inside traced function "
                f"'{label}' concretizes a tracer (host sync or trace "
                "error); keep values as jnp arrays under trace",
            )


# ---------------------------------------------------------------------------
# JL003 — Python side effects under trace


_IMPURE_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "datetime.now", "datetime.datetime.now", "datetime.utcnow",
    "random.random", "random.randint", "random.shuffle", "random.choice",
    "np.random.rand", "np.random.randn", "np.random.randint",
    "np.random.normal", "np.random.uniform", "np.random.seed",
    "open", "input",
}
_MUTATING_METHODS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "clear", "discard",
}


class SideEffectRule(Rule):
    """JL003: effects that run at TRACE time, not at step time.

    A ``print``/``time.time()``/list-append under ``jit`` executes once
    per trace (usually once, period) — code that looks like per-step
    logging or accumulation silently does nothing after compilation.
    """

    rule_id = "JL003"
    severity = Severity.ERROR
    summary = "Python side effect inside a traced function"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        analysis = get_trace_analysis(ctx)
        for fn in analysis.traced_defs():
            label = _fn_label(fn)
            local_names = self._local_bindings(fn)
            for node in iter_own_body(fn):
                if not isinstance(node, (ast.Call, ast.Assign)):
                    continue
                if isinstance(node, ast.Assign):
                    for target in node.targets:
                        if (isinstance(target, ast.Subscript)
                                and isinstance(target.value, ast.Name)
                                and target.value.id not in local_names):
                            yield self.finding(
                                ctx, target,
                                f"assignment into closed-over '{target.value.id}' "
                                f"inside traced function '{label}' happens at "
                                "trace time only; thread values through the "
                                "function's returns instead",
                            )
                    continue
                name = dotted_name(node.func)
                if name == "print":
                    yield self.finding(
                        ctx, node,
                        f"print() inside traced function '{label}' runs at "
                        "trace time only (once, with tracers); use "
                        "jax.debug.print for runtime values",
                    )
                elif name in _IMPURE_CALLS:
                    yield self.finding(
                        ctx, node,
                        f"{name}() inside traced function '{label}' is "
                        "evaluated once at trace time and baked into the "
                        "program as a constant; compute it outside the "
                        "jitted boundary",
                    )
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATING_METHODS
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id not in local_names):
                    yield self.finding(
                        ctx, node,
                        f".{node.func.attr}() on closed-over "
                        f"'{node.func.value.id}' inside traced function "
                        f"'{label}' mutates at trace time only; carry state "
                        "through the traced function's inputs/outputs",
                    )

    @staticmethod
    def _binding_names(target: ast.AST):
        """Names BOUND by an assignment target.  A Subscript/Attribute
        target (``cache[k] = v``) binds nothing — collecting its base
        name would mark the closed-over container "local" and silence
        the very mutation this rule exists to catch."""
        if isinstance(target, ast.Name):
            yield target.id
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                yield from SideEffectRule._binding_names(elt)
        elif isinstance(target, ast.Starred):
            yield from SideEffectRule._binding_names(target.value)

    @staticmethod
    def _local_bindings(fn: ast.AST) -> set[str]:
        names: set[str] = set()
        args = getattr(fn, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                names.add(a.arg)
            for a in (args.vararg, args.kwarg):
                if a is not None:
                    names.add(a.arg)
        for node in iter_own_body(fn):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    names.update(SideEffectRule._binding_names(target))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                if isinstance(node.target, ast.Name):
                    names.add(node.target.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                names.update(SideEffectRule._binding_names(node.target))
            elif isinstance(node, ast.NamedExpr):
                if isinstance(node.target, ast.Name):
                    names.add(node.target.id)
        return names


# ---------------------------------------------------------------------------
# JL004 — retrace triggers


class RetraceRule(Rule):
    """JL004: program structure that forces avoidable recompiles.

    (a) building a jitted callable inside a loop — every iteration gets an
    empty trace cache, so every iteration pays a full trace+compile;
    (b) ``jnp.array([...])`` literals inside traced functions — a fresh
    constant materialized on every trace, the round-3 "mystery" constant
    uploads.
    """

    rule_id = "JL004"
    severity = Severity.WARNING
    summary = "avoidable retrace trigger"

    _JNP_CTORS = {"jnp.array", "jnp.asarray", "jax.numpy.array", "jax.numpy.asarray"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        analysis = get_trace_analysis(ctx)
        # (a) jit/pmap construction inside any loop body.
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for sub in iter_loop_body_nodes(loop):
                if (isinstance(sub, ast.Call)
                        and dotted_name(sub.func) in _JIT_CONSTRUCTORS):
                    yield self.finding(
                        ctx, sub,
                        f"{dotted_name(sub.func)}(...) constructed inside "
                        "a loop: each iteration builds a fresh callable "
                        "with an empty trace cache (compile every "
                        "iteration); hoist the jitted function out of "
                        "the loop",
                    )
        # (b) jnp.array literal construction under trace.  Only flagged
        # when every element is a compile-time constant: stacking traced
        # values (`jnp.array([x.sum(), y.sum()])`) is legitimate and NOT
        # hoistable.
        for fn in analysis.traced_defs():
            label = _fn_label(fn)
            for node in iter_own_body(fn):
                if (isinstance(node, ast.Call)
                        and dotted_name(node.func) in self._JNP_CTORS
                        and node.args
                        and self._is_const_literal(node.args[0])):
                    yield self.finding(
                        ctx, node,
                        f"{dotted_name(node.func)} of a Python literal inside "
                        f"traced function '{label}' materializes a fresh "
                        "constant every trace; hoist it to module scope or "
                        "close over a precomputed array",
                    )

    @staticmethod
    def _is_const_literal(node: ast.AST) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, (ast.List, ast.Tuple)):
            return bool(node.elts) and all(
                RetraceRule._is_const_literal(e) for e in node.elts
            )
        if isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.USub, ast.UAdd)
        ):
            return RetraceRule._is_const_literal(node.operand)
        return False


# ---------------------------------------------------------------------------
# JL005 — missing donation on state-carrying jitted steps


class DonationRule(Rule):
    """JL005: a jitted step whose arg 0 is a train/opt state, not donated.

    Without ``donate_argnums`` the old state's buffers stay live across
    the update, doubling optimizer-state HBM and costing a copy per step
    — exactly the class of waste the fused-path work (docs/PERF.md)
    hunted by hand.
    """

    rule_id = "JL005"
    severity = Severity.WARNING
    summary = "state-carrying jitted step without donate_argnums"

    _STATE_HINTS = ("state", "carry", "opt")
    _DONATE_KWARGS = {"donate_argnums", "donate_argnames"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        by_name: dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                by_name[node.name] = node

        # Resolution is PER SCOPE, nearest-preceding-assignment wins: the
        # repo's factories all bind a local ``sharded = jax.shard_map(...)``
        # before ``return jax.jit(sharded)``, and a module-global map would
        # resolve every one of them to whichever factory parsed last.
        scopes: list[ast.AST] = [ctx.tree] + [
            d for d in ast.walk(ctx.tree)
            if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            events: list[tuple[int, int, str, ast.AST]] = []
            if isinstance(scope, ast.Module):
                nodes: list[ast.AST] = []
                stack = list(scope.body)
                while stack:
                    node = stack.pop()
                    nodes.append(node)
                    if not isinstance(node, _SCOPE_NODES):
                        stack.extend(ast.iter_child_nodes(node))
            else:
                nodes = list(iter_own_body(scope))
            for node in nodes:
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and isinstance(node.value, ast.Call)):
                    events.append((node.lineno, node.col_offset, "assign", node))
                elif (isinstance(node, ast.Call)
                        and dotted_name(node.func) in {"jax.jit", "jit",
                                                       "pjit", "jax.pjit"}):
                    events.append((node.lineno, node.col_offset, "jit", node))
            assigns: dict[str, ast.Call] = {}
            for _, _, kind, node in sorted(events, key=lambda e: (e[0], e[1])):
                if kind == "assign":
                    assigns[node.targets[0].id] = node.value
                    continue
                yield from self._check_jit_call(ctx, node, by_name, assigns)

    def _check_jit_call(self, ctx, node, by_name, assigns) -> Iterator[Finding]:
        if any(kw.arg in self._DONATE_KWARGS for kw in node.keywords):
            return
        if not node.args:
            return
        target = self._resolve(node.args[0], by_name, assigns)
        if target is None:
            return
        first_param = self._first_param(target)
        if first_param is None:
            return
        if any(h in first_param.lower() for h in self._STATE_HINTS):
            yield self.finding(
                ctx, node,
                f"jax.jit of '{_fn_label(target)}' carries "
                f"'{first_param}' in arg 0 but has no donate_argnums; "
                "donate the state so the old buffers are reused instead "
                "of held live across the update",
            )

    def _resolve(self, arg, by_name, assigns, depth: int = 0):
        """Follow ``jit(name)`` where name is a def or ``shard_map(def, …)``."""
        if depth > 3 or not isinstance(arg, ast.Name):
            return None
        if arg.id in by_name:
            return by_name[arg.id]
        call = assigns.get(arg.id)
        if call is not None and dotted_name(call.func) in _TRANSFORM_CALLS:
            for inner in call.args:
                resolved = self._resolve(inner, by_name, assigns, depth + 1)
                if resolved is not None:
                    return resolved
        return None

    @staticmethod
    def _first_param(fn) -> str | None:
        args = getattr(fn, "args", None)
        if args is None:
            return None
        ordered = args.posonlyargs + args.args
        if not ordered:
            return None
        first = ordered[0].arg
        return None if first in {"self", "cls"} else first


# ---------------------------------------------------------------------------
# JL006 — device_get in hot loops


class DeviceGetLoopRule(Rule):
    """JL006: ``jax.device_get`` inside a Python loop.

    Each call is a blocking D2H transfer; in a per-batch loop it
    serializes the device pipeline every iteration (the round-2 "run_s
    parked in print" effect).  Batch the reads, or read once after the
    loop.
    """

    rule_id = "JL006"
    severity = Severity.WARNING
    summary = "blocking device_get inside a loop"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for sub in iter_loop_body_nodes(loop):
                if (isinstance(sub, ast.Call)
                        and dotted_name(sub.func) in {"jax.device_get",
                                                      "device_get"}):
                    yield self.finding(
                        ctx, sub,
                        "jax.device_get inside a loop blocks on a "
                        "device-to-host transfer every iteration; batch "
                        "the reads or move the transfer after the loop",
                    )


# ---------------------------------------------------------------------------
# JL008 — telemetry recorded at trace time


_TRACE_CLOCK_CALLS = {
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "time.time_ns", "time.perf_counter_ns", "time.monotonic_ns",
}
_METRIC_RECORD_METHODS = {"inc", "dec", "observe", "emit", "mark"}


class TelemetryUnderTraceRule(Rule):
    """JL008: clock reads / metrics-recording calls inside traced code.

    The observability-layer twin of JL003: a ``time.perf_counter()`` or
    ``counter.inc()`` under ``jit`` executes ONCE, at trace time, with
    tracers — the "latency" is the compile-time timestamp baked in as a
    constant, and the counter moves once per compile instead of once per
    step.  Telemetry that silently measures nothing is worse than none:
    the dashboard looks alive.  Record at the host boundary instead —
    around the jitted call (obs/spans.span, StepStats.mark), never
    inside it.

    Matched: the ``time`` module's clock calls, the obs recording
    methods (``.inc``/``.dec``/``.observe``/``.emit``/``.mark``), and
    any ``.record_*`` method (the ServingMetrics surface).  Clock reads
    overlap JL003's impure-call set deliberately — JL003 says "this is
    a side effect", this rule says what the broken telemetry will look
    like and where the recording belongs.
    """

    rule_id = "JL008"
    severity = Severity.WARNING
    summary = "telemetry (clock read / metric record) inside a traced function"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        analysis = get_trace_analysis(ctx)
        for fn in analysis.traced_defs():
            label = _fn_label(fn)
            for node in iter_own_body(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name in _TRACE_CLOCK_CALLS:
                    yield self.finding(
                        ctx, node,
                        f"{name}() inside traced function '{label}' reads "
                        "the clock once at trace time — the value is a "
                        "compile-time constant, so the timing records "
                        "nothing at runtime; time around the jitted call "
                        "at the host boundary (obs/spans.span, "
                        "StepStats.mark)",
                    )
                elif isinstance(node.func, ast.Attribute) and (
                    node.func.attr in _METRIC_RECORD_METHODS
                    or node.func.attr.startswith("record_")
                ):
                    yield self.finding(
                        ctx, node,
                        f".{node.func.attr}() inside traced function "
                        f"'{label}' records at trace time only (once per "
                        "compile, with tracers, not once per step); move "
                        "the recording outside the jitted boundary and "
                        "feed it values the function returns",
                    )


# ---------------------------------------------------------------------------
# JL007 — raw len()-dependent shapes fed to a jitted callable


class BucketShapeRule(Rule):
    """JL007: a jit-compiled callable fed ``len(batch)``-dependent data
    outside a bucket helper.

    The serving retrace class: ``predict(params, buf[:len(batch)])``
    compiles one executable per distinct request size — unbounded
    executables under real traffic, tens of seconds each on TPU.  The fix
    is shape bucketing (serving/buckets.py): quantize ``len(batch)`` to a
    fixed ladder and pad, so jit only ever sees bucket shapes.

    Heuristics (per scope, same resolution style as JL005): a name is
    "jitted" when bound from ``jax.jit``/``pjit``/``pmap`` (directly or
    through ``RecompileSentinel(...)``); an argument is "len-dependent"
    when it lexically contains ``len(...)`` or a name previously bound
    from a bare ``len(...)``.  Subtrees inside a call whose name mentions
    ``bucket`` (``bucket_for(len(batch))``, ``pad_to_bucket(...)``) are
    exempt — that is the sanctioned laundering point for raw sizes.
    """

    rule_id = "JL007"
    severity = Severity.WARNING
    summary = "jit-compiled call fed raw len()-dependent shapes; bucket them"

    # Kept as an alias: callers and fixtures address the shared helper
    # through the rule that introduced it.
    _is_jit_value = staticmethod(is_jit_value)

    @staticmethod
    def _is_bucket_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = dotted_name(node.func)
        return bool(name) and "bucket" in name.split(".")[-1].lower()

    @classmethod
    def _len_taint(cls, node: ast.AST, len_names: set[str]) -> ast.AST | None:
        """The first raw-len use inside ``node``, skipping bucket calls."""
        if cls._is_bucket_call(node):
            return None
        if isinstance(node, ast.Call) and dotted_name(node.func) == "len":
            return node
        if isinstance(node, ast.Name) and node.id in len_names:
            return node
        for child in ast.iter_child_nodes(node):
            hit = cls._len_taint(child, len_names)
            if hit is not None:
                return hit
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        module_jit = module_jit_names(ctx.tree)

        scopes: list[ast.AST] = [ctx.tree] + [
            d for d in ast.walk(ctx.tree)
            if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            label = "<module>" if isinstance(scope, ast.Module) else scope.name
            # Bucket/pad helpers are where raw sizes legitimately live.
            if any(tag in label.lower() for tag in ("bucket", "pad")):
                continue
            nodes = iter_scope_nodes(scope)
            nodes.sort(key=lambda n: (getattr(n, "lineno", 0),
                                      getattr(n, "col_offset", 0)))
            jit_names = set(module_jit)
            len_names: set[str] = set()
            for node in nodes:
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    target = node.targets[0].id
                    if self._is_jit_value(node.value):
                        jit_names.add(target)
                        continue
                    if (isinstance(node.value, ast.Call)
                            and dotted_name(node.value.func) == "len"):
                        len_names.add(target)
                    else:
                        len_names.discard(target)
                    continue
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in jit_names):
                    continue
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    hit = self._len_taint(arg, len_names)
                    if hit is not None:
                        yield self.finding(
                            ctx, node,
                            f"jitted '{node.func.id}' called with a raw "
                            "len()-dependent argument in "
                            f"'{label}': every distinct size compiles a new "
                            "executable; quantize to fixed shape buckets "
                            "and pad (serving/buckets.py: bucket_for + "
                            "pad_to_bucket)",
                        )
                        break


# ---------------------------------------------------------------------------
# JL009 — blocking host reads of jit outputs inside dispatch loops


_BLOCKING_READ_CALLS = _NP_HOST_CALLS | {"jax.device_get", "device_get"}


class BlockingReadLoopRule(Rule):
    """JL009: ``np.asarray`` / ``jax.device_get`` / ``.block_until_ready``
    on a jitted function's output inside the loop that dispatched it.

    The serving-pipeline hazard class (docs/SERVING.md): a dispatch loop
    that launches the jitted forward and immediately reads the result
    back serializes the whole chain — device compute, host padding, H2D
    and D2H never overlap, because jax's async dispatch is thrown away
    one call later by the blocking read.  The fix is to decouple
    completion from dispatch: launch inside the loop, hand the device
    array to a completion worker (or read once after the loop) so batch
    N+1's host work overlaps batch N's compute — the pipelined batcher's
    whole design.  A deliberate same-iteration read (a serial path, a
    benchmark timing one dispatch) is waived inline with a reason.

    Heuristics (per scope, same resolution style as JL007): a callable is
    "jitted" when bound from ``jax.jit``/``pjit``/``pmap`` — directly,
    through ``RecompileSentinel(...)``, or onto a ``self.attr`` (the
    engine shape); an expression is a "jit output" when it calls such a
    name, or names a variable assigned from one *inside the same loop
    body* (a handle produced before the loop is prefetched, not
    pipelined-away — reading it per iteration is not this hazard).
    """

    rule_id = "JL009"
    severity = Severity.WARNING
    summary = "blocking host read of a jit output inside its dispatch loop"

    # Aliases for the shared helpers (historical access path; the bodies
    # live at module level since PR 16's de-duplication sweep).
    _jit_attr_names = staticmethod(jit_attr_names)
    _is_jit_call = staticmethod(is_jit_call)

    @classmethod
    def _jit_output_taint(
        cls, node: ast.AST, jit_names, jit_attrs, out_names
    ) -> bool:
        """Does ``node`` lexically contain a jit call or a loop-local
        name bound from one?"""
        if cls._is_jit_call(node, jit_names, jit_attrs):
            return True
        if isinstance(node, ast.Name) and node.id in out_names:
            return True
        return any(
            cls._jit_output_taint(child, jit_names, jit_attrs, out_names)
            for child in ast.iter_child_nodes(node)
        )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        module_jit = module_jit_names(ctx.tree)
        jit_attrs = jit_attr_names(ctx.tree)

        scopes: list[ast.AST] = [ctx.tree] + [
            d for d in ast.walk(ctx.tree)
            if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            nodes = iter_scope_nodes(scope)
            jit_names = set(module_jit)
            for node in nodes:
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and is_jit_value(node.value)):
                    jit_names.add(node.targets[0].id)
            for node in nodes:
                if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    yield from self._check_loop(ctx, node, jit_names, jit_attrs)

    def _check_loop(self, ctx, loop, jit_names, jit_attrs) -> Iterator[Finding]:
        body = list(iter_loop_body_nodes(loop))
        # Names bound from a jit call WITHIN this loop body: reading one
        # of these in the same loop is the dispatch-then-stall shape.
        out_names: set[str] = set()
        for node in body:
            if isinstance(node, ast.Assign):
                if self._is_jit_call(node.value, jit_names, jit_attrs):
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            out_names.add(target.id)
        for node in body:
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name in _BLOCKING_READ_CALLS:
                if any(
                    self._jit_output_taint(a, jit_names, jit_attrs, out_names)
                    for a in node.args
                ):
                    yield self.finding(
                        ctx, node,
                        f"{name}(...) on a jit output inside its dispatch "
                        "loop blocks the loop on device compute + D2H every "
                        "iteration — async dispatch is wasted; hand the "
                        "device array to a completion worker or read after "
                        "the loop (serving/batcher.py)",
                    )
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "block_until_ready"
                    and self._jit_output_taint(
                        node.func.value, jit_names, jit_attrs, out_names)):
                yield self.finding(
                    ctx, node,
                    ".block_until_ready() on a jit output inside its "
                    "dispatch loop serializes the pipeline every iteration; "
                    "bound in-flight work with a window and complete "
                    "asynchronously instead (serving/batcher.py)",
                )


# ---------------------------------------------------------------------------
# JL010 — serial warmup of independent compile jobs


class SerialWarmupRule(Rule):
    """JL010: a loop that compiles one executable per iteration, serially.

    The startup-latency hazard class (docs/COMPILE.md): a warmup loop
    that calls a jitted function once per ladder rung — or runs
    ``.lower(...).compile()`` per iteration — pays trace+compile for N
    independent programs ONE AT A TIME on the calling thread, when XLA
    compilation releases the GIL and the jobs would happily build
    concurrently.  At TPU compile times (tens of seconds per program)
    a serial ladder turns seconds of startup into minutes.  Fan the
    jobs out over the background compile service instead
    (compile/service.py; the serving engine's warmup is the worked
    example).

    Heuristics (per scope, same jit-name resolution as JL009): a loop
    iteration is a *warmup* when it (a) calls a known-jitted callable as
    a bare expression statement — the result is discarded, so the call
    exists only for its compile/cache side effect — or (b) compiles
    explicitly via ``.lower(...).compile()`` (directly chained or
    through a loop-local name).  It is flagged only when the call's
    arguments depend on the loop variable (directly or through names
    derived from it): distinct per-iteration arguments mean distinct
    programs, i.e. independent jobs.  Re-running one program for
    burn-in (``for _ in range(3): f(x)``) compiles nothing after the
    first call and is exempt.  A deliberately serial ladder (debugging
    compile order) is waived inline with a reason.
    """

    rule_id = "JL010"
    severity = Severity.WARNING
    summary = "serial per-iteration warmup compile; fan out over the compile service"

    @staticmethod
    def _names_in(node: ast.AST) -> set[str]:
        return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}

    @classmethod
    def _args_tainted(cls, call: ast.Call, tainted: set[str]) -> bool:
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if cls._names_in(arg) & tainted:
                return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        module_jit = module_jit_names(ctx.tree)
        jit_attrs = jit_attr_names(ctx.tree)

        scopes: list[ast.AST] = [ctx.tree] + [
            d for d in ast.walk(ctx.tree)
            if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            nodes = iter_scope_nodes(scope)
            jit_names = set(module_jit)
            for node in nodes:
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and is_jit_value(node.value)):
                    jit_names.add(node.targets[0].id)
            for node in nodes:
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    yield from self._check_loop(ctx, node, jit_names, jit_attrs)

    def _check_loop(self, ctx, loop, jit_names, jit_attrs) -> Iterator[Finding]:
        body = sorted(
            iter_loop_body_nodes(loop),
            key=lambda n: (getattr(n, "lineno", 0), getattr(n, "col_offset", 0)),
        )
        # Loop-variable taint: the target itself plus names assigned from
        # expressions that reference a tainted name (x = np.zeros((b, ...))).
        tainted = self._names_in(loop.target)
        lower_names: set[str] = set()
        for node in body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            if self._names_in(node.value) & tainted:
                tainted.add(target.id)
                value = node.value
                if (isinstance(value, ast.Call)
                        and isinstance(value.func, ast.Attribute)
                        and value.func.attr == "lower"):
                    lower_names.add(target.id)
        for node in body:
            # (a) discarded jit call with per-iteration arguments.
            if (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)
                    and BlockingReadLoopRule._is_jit_call(
                        node.value, jit_names, jit_attrs)
                    and self._args_tainted(node.value, tainted)):
                yield self.finding(
                    ctx, node.value,
                    "jitted call discarded inside a loop with per-iteration "
                    "arguments: a serial warmup ladder that trace+compiles "
                    "one program per rung on this thread; submit the rungs "
                    "to the background compile service instead "
                    "(compile/service.py; serving/engine.py warmup)",
                )
                continue
            # (b) explicit .lower(...).compile() per iteration.
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "compile"):
                recv = node.func.value
                chained = (
                    isinstance(recv, ast.Call)
                    and isinstance(recv.func, ast.Attribute)
                    and recv.func.attr == "lower"
                    and self._args_tainted(recv, tainted)
                )
                via_name = isinstance(recv, ast.Name) and recv.id in lower_names
                if chained or via_name:
                    yield self.finding(
                        ctx, node,
                        ".lower(...).compile() inside a loop builds one "
                        "executable per iteration serially; the jobs are "
                        "independent — run them concurrently on the "
                        "background compile service (compile/service.py)",
                    )


# ---------------------------------------------------------------------------
# JL011 — host-blocking data feeds between jitted step calls


_FEED_CALLS = {"next"} | _NP_HOST_CALLS


class HostBlockingFeedRule(Rule):
    """JL011: the next batch materialized on the critical path between
    two jitted step calls, with no prefetch wrapper in scope.

    The steady-state input hazard class (docs/DATA.md): a training loop
    shaped ``x = np.asarray(next(it)); state = step(state, x)`` pays the
    whole assemble + H2D cost INSIDE the gap between step k's dispatch
    and step k+1's — the device idles exactly that long every iteration
    (BENCH_r05's missing third of wall clock).  The fix is a prefetch
    wrapper (data/prefetch.DevicePrefetcher, or DataLoader.epoch which
    wraps it): batch k+1 assembles and starts its transfer on a
    background thread while step k runs, so the loop's per-batch cost
    collapses to a buffer swap.

    Heuristics (per scope, same jit-name resolution as JL009/JL010): a
    loop iteration is a *blocking feed* when its body (a) calls a
    known-jitted callable AND (b) materializes host data via ``next(...)``
    or ``np.asarray``/``np.array`` whose result flows into that jitted
    call's arguments — directly, or through a name assigned in the same
    loop body.  Feeds whose source expression mentions a prefetch
    wrapper (any name containing ``prefetch``) are exempt: that is the
    sanctioned hand-off point, and ``next()`` on a prefetcher is a
    buffer swap, not a materialization.  ``np.asarray`` on a jit OUTPUT
    is JL009's territory, not this rule's (it only fires on the input
    side).  A deliberately serial feed (a benchmark timing the
    end-to-end chain) is waived inline with a reason.
    """

    rule_id = "JL011"
    severity = Severity.WARNING
    summary = "host-blocking data feed between jitted step calls; prefetch it"

    @staticmethod
    def _mentions_prefetch(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and "prefetch" in sub.id.lower():
                return True
            if isinstance(sub, ast.Attribute) and "prefetch" in sub.attr.lower():
                return True
        return False

    @classmethod
    def _feed_call(cls, node: ast.AST) -> ast.Call | None:
        """The first next()/np.asarray materialization inside ``node``,
        skipping prefetch-wrapped sources."""
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name in _FEED_CALLS and not cls._mentions_prefetch(node):
                return node
        for child in ast.iter_child_nodes(node):
            hit = cls._feed_call(child)
            if hit is not None:
                return hit
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        module_jit = module_jit_names(ctx.tree)
        jit_attrs = jit_attr_names(ctx.tree)

        scopes: list[ast.AST] = [ctx.tree] + [
            d for d in ast.walk(ctx.tree)
            if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            nodes = iter_scope_nodes(scope)
            jit_names = set(module_jit)
            for node in nodes:
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        and is_jit_value(node.value)):
                    jit_names.add(node.targets[0].id)
            for node in nodes:
                if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    yield from self._check_loop(ctx, node, jit_names, jit_attrs)

    def _check_loop(self, ctx, loop, jit_names, jit_attrs) -> Iterator[Finding]:
        body = sorted(
            iter_loop_body_nodes(loop),
            key=lambda n: (getattr(n, "lineno", 0), getattr(n, "col_offset", 0)),
        )
        jit_calls = [
            n for n in body
            if BlockingReadLoopRule._is_jit_call(n, jit_names, jit_attrs)
        ]
        if not jit_calls:
            return
        # Names bound in this loop body from a materializing feed call,
        # with the feed node kept as the finding's anchor.
        feed_names: dict[str, ast.Call] = {}
        for node in body:
            if not isinstance(node, ast.Assign):
                continue
            feed = self._feed_call(node.value)
            if feed is None:
                continue
            for target in node.targets:
                for sub in ast.walk(target):
                    if isinstance(sub, ast.Name):
                        feed_names[sub.id] = feed
        reported: set[int] = set()
        for call in jit_calls:
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                feed = self._feed_call(arg)
                if feed is None:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name) and sub.id in feed_names:
                            feed = feed_names[sub.id]
                            break
                if feed is None:
                    continue
                anchor = getattr(feed, "lineno", 0)
                if anchor in reported:
                    continue
                reported.add(anchor)
                yield self.finding(
                    ctx, feed,
                    f"{dotted_name(feed.func)}(...) materializes the next "
                    "batch on the critical path between jitted step calls: "
                    "the device idles through the whole assemble+transfer "
                    "every iteration; wrap the iterator in a prefetcher "
                    "(data/prefetch.DevicePrefetcher) so batch k+1 stages "
                    "while step k runs",
                )
                break


# ---------------------------------------------------------------------------
# JL012 — per-replica engine construction without shared warm state


# Call names that build a serving engine (and with it a full bucket
# ladder of compiled executables): the constructor and its classmethod
# surfaces.  Matched on the trailing segments so both
# `InferenceEngine(...)` and `serving.InferenceEngine.from_seed(...)`
# resolve.
_ENGINE_CTOR_TAIL = "InferenceEngine"
_ENGINE_FACTORY_METHODS = {"from_seed", "from_checkpoint"}

# Keyword arguments that make a per-iteration engine construction the
# sanctioned pool idiom instead of a re-trace generator: a shared AOT
# store and/or an explicit device/mesh pin (serving/pool.py passes both).
_ENGINE_SHARING_KWARGS = {"aot_cache", "mesh", "device", "devices"}


class EngineLoopRule(Rule):
    """JL012: an InferenceEngine built inside a loop without a shared
    AOT cache or an explicit device/mesh pin.

    The replica-pool hazard class (docs/SERVING.md scale-out): a loop
    that constructs one engine per device/replica builds one FULL bucket
    ladder of executables per iteration.  Without ``aot_cache=`` (the
    shared ExecutableStore) every replica re-traces and re-compiles the
    whole dtype x bucket grid from scratch — N x the startup cost the
    compile subsystem exists to remove — and without ``mesh=`` /
    ``device=`` every "replica" lands on whatever jax defaults to,
    usually the SAME device, so the loop multiplies compile cost without
    multiplying capacity.  The fix is the pool idiom
    (serving/pool.py: EnginePool): pin each engine to its device via an
    explicit mesh and share one ExecutableStore so replica warmups are
    deserializations, not traces.  (Bare ``jax.jit`` construction inside
    a loop is the same smell one level down — that is JL004's existing
    territory; this rule covers the engine-shaped version JL004 cannot
    see through the constructor call.)

    Heuristic: any loop-body call whose dotted name ends in
    ``InferenceEngine`` (or ``InferenceEngine.from_seed`` /
    ``.from_checkpoint``) with NONE of the sharing kwargs
    (``aot_cache``/``mesh``/``device``/``devices``) present.  A
    deliberately cache-less loop (a compile benchmark) is waived inline
    with a reason.
    """

    rule_id = "JL012"
    severity = Severity.WARNING
    summary = "per-loop InferenceEngine without shared AOT cache or device pin"

    @staticmethod
    def _engine_call(node: ast.AST) -> str | None:
        if not isinstance(node, ast.Call):
            return None
        name = dotted_name(node.func)
        if name is None:
            return None
        parts = name.split(".")
        if parts[-1] == _ENGINE_CTOR_TAIL:
            return name
        if (len(parts) >= 2
                and parts[-1] in _ENGINE_FACTORY_METHODS
                and parts[-2] == _ENGINE_CTOR_TAIL):
            return name
        return None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for sub in iter_loop_body_nodes(loop):
                name = self._engine_call(sub)
                if name is None:
                    continue
                kwargs = {kw.arg for kw in sub.keywords if kw.arg}
                if kwargs & _ENGINE_SHARING_KWARGS:
                    continue
                yield self.finding(
                    ctx, sub,
                    f"{name}(...) constructed inside a loop with neither "
                    "a shared AOT cache nor an explicit device pin: each "
                    "iteration re-traces and re-compiles a full bucket "
                    "ladder (and every replica lands on the default "
                    "device); pass aot_cache= (one shared "
                    "ExecutableStore) and mesh=/device= per replica, or "
                    "use the pool (serving/pool.py EnginePool)",
                )


# ---------------------------------------------------------------------------
# JL013 — swallowed dispatch errors in an unbounded retry loop


# Exception names whose handlers count as catch-everything.  A handler
# for a SPECIFIC error type (RejectedError, ValueError...) is a decision
# about one failure mode, not the silent-poison idiom.
_BROAD_EXCEPTS = {"Exception", "BaseException"}

# A handler that calls one of these is backing off, not spinning: the
# retry has a pacing mechanism, which is half of what the rule demands.
_BACKOFF_HINTS = ("sleep", "backoff", "wait")


class SwallowedDispatchErrorRule(Rule):
    """JL013: a bare ``except:`` / ``except Exception`` swallowing errors
    around a jitted call (or ``engine.launch``) inside an unbounded
    dispatch/retry loop — no re-raise, no bounded retry count, no
    backoff.

    The silent-poison hazard class the serving supervisor exists to
    replace (docs/ROBUSTNESS.md): a dispatch loop shaped ``while True:
    try: engine.launch(...) except Exception: continue`` turns a dead
    replica into an infinite hot loop that eats every request, counts
    nothing, heals nothing, and keeps the replica in rotation forever.
    The repo's sanctioned shapes all do one of three things instead:
    surface the error to every waiter and KEEP SERVING under metrics
    (the batcher's dispatch worker, which re-raises nothing but
    completes waiters and feeds ``on_failure`` → the circuit breaker),
    retry a BOUNDED number of times on the remaining deadline budget
    (the HTTP handler's ``for attempt in range(2)``), or hand the
    replica to the supervisor (quarantine → backoff restart → eject).

    Heuristics: fires when (a) the loop is unbounded — any ``while``, or
    a ``for`` over something other than a literal ``range(...)`` (a
    range-bounded retry loop IS the bounded-retry idiom); (b) a ``try``
    executed by the loop body contains a call to a known-jitted name
    (same resolution as JL009: ``jax.jit`` values, ``RecompileSentinel``
    wraps, ``self.attr`` bindings) or any ``*.launch(...)`` attribute
    call; and (c) a catch-all handler (bare / ``Exception`` /
    ``BaseException``) contains none of ``raise`` / ``break`` /
    ``return`` and no call whose name mentions sleep/backoff/wait.
    A deliberate swallow (a chaos driver, a best-effort prober) is
    waived inline with a reason.
    """

    rule_id = "JL013"
    severity = Severity.WARNING
    summary = "catch-all swallows dispatch errors in an unbounded retry loop"

    @staticmethod
    def _is_bounded_for(loop: ast.AST) -> bool:
        return (
            isinstance(loop, ast.For)
            and isinstance(loop.iter, ast.Call)
            and dotted_name(loop.iter.func) in {"range", "builtins.range"}
        )

    @staticmethod
    def _contains_dispatch(node: ast.AST, jit_names, jit_attrs) -> bool:
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, _SCOPE_NODES):
                continue
            if isinstance(sub, ast.Call):
                if BlockingReadLoopRule._is_jit_call(sub, jit_names, jit_attrs):
                    return True
                if (isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "launch"):
                    return True
            stack.extend(ast.iter_child_nodes(sub))
        return False

    @classmethod
    def _handler_swallows(cls, handler: ast.ExceptHandler) -> bool:
        if handler.type is not None:
            names = (
                [dotted_name(t) for t in handler.type.elts]
                if isinstance(handler.type, ast.Tuple)
                else [dotted_name(handler.type)]
            )
            last = {str(n).split(".")[-1] for n in names if n}
            if not last & _BROAD_EXCEPTS:
                return False
        stack: list[ast.AST] = list(handler.body)
        while stack:
            node = stack.pop()
            if isinstance(node, _SCOPE_NODES):
                continue
            if isinstance(node, (ast.Raise, ast.Break, ast.Return)):
                return False
            if isinstance(node, ast.Call):
                name = (dotted_name(node.func) or "").lower()
                if any(hint in name for hint in _BACKOFF_HINTS):
                    return False
            stack.extend(ast.iter_child_nodes(node))
        return True

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        module_jit = module_jit_names(ctx.tree)
        jit_attrs = jit_attr_names(ctx.tree)
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            if self._is_bounded_for(loop):
                continue  # the bounded-retry idiom (HTTP handler shape)
            for node in iter_loop_body_nodes(loop):
                if not isinstance(node, ast.Try):
                    continue
                if not self._contains_dispatch(
                    ast.Module(body=node.body, type_ignores=[]),
                    module_jit, jit_attrs,
                ):
                    continue
                for handler in node.handlers:
                    if self._handler_swallows(handler):
                        yield self.finding(
                            ctx, handler,
                            "catch-all around a jitted dispatch inside an "
                            "unbounded loop with no re-raise, bound, or "
                            "backoff: a dead replica becomes a silent "
                            "hot loop that poisons every request; surface "
                            "the error to its waiters and feed a failure "
                            "hook (serving/batcher.py), bound the retry "
                            "(for attempt in range(n)), or let the "
                            "supervisor quarantine the replica "
                            "(serving/pool.py)",
                        )


# ---------------------------------------------------------------------------
# JL014 — non-atomic / uncadenced checkpoint writes


# Tensor-checkpoint writers and, for each, the index of the argument
# that names the DESTINATION (np.save(path, arr) vs torch.save(obj,
# path) vs pickle.dump(obj, file)).  Matched on dotted names so a local
# helper named `save` never trips the rule.
_CKPT_WRITERS = {
    "np.save": 0, "numpy.save": 0,
    "np.savez": 0, "numpy.savez": 0,
    "np.savez_compressed": 0, "numpy.savez_compressed": 0,
    "jnp.save": 0, "jax.numpy.save": 0,
    "torch.save": 1,
    "pickle.dump": 1,
}

# The repo's sanctioned checkpoint helpers (utils/checkpoint.py): every
# one routes through the mkstemp+fsync+atomic-replace discipline, so a
# call to them is never a torn-file hazard — but INSIDE a step loop it
# still needs a cadence guard (matched by trailing segment so
# `checkpoint.save_train_state(...)` resolves too).
_CKPT_HELPER_TAILS = {
    "save_train_state", "save_state_dict", "save_params_tree",
}

# An If-test that counts as a cadence guard: a modulus (`step % N == 0`),
# a call to a `due()`-style gate (resilience/checkpoint.py
# MidEpochCheckpointer.due), or a comparison against an
# every/interval/cadence-named value.
_CADENCE_GATE_CALLS = {"due", "should_checkpoint", "should_save"}
_CADENCE_NAME_HINTS = ("every", "interval", "cadence")


class CheckpointWriteRule(Rule):
    """JL014: a checkpoint write that is torn-file-unsafe or uncadenced.

    The durability hazard class (docs/ROBUSTNESS.md): the whole
    preemption-safety story rests on two disciplines, and both are
    invisible to tests that never kill the writer.  (a) **Atomicity**: a
    raw ``np.savez``/``torch.save``/``pickle.dump`` straight onto its
    final path dies mid-write as a TORN file that the next load explodes
    on — every state write must route through utils/checkpoint.py's
    helpers (mkstemp + fsync + atomic replace; a reader only ever sees
    absent or complete files).  (b) **Cadence**: a save inside the step
    loop without a ``step % N``/``due(step)`` gate serializes a full
    device_get + disk write into EVERY step — the accidental
    10-100x slowdown class, usually introduced as a debugging aid and
    shipped.

    Heuristics: (a) fires on a raw-writer call whose destination
    argument is a string constant, f-string, or ``os.path.join(...)``
    call — writing DIRECTLY to a named final path.  A Name destination
    stays silent: the atomic helpers themselves write to mkstemp/BytesIO
    bindings, and the rule cannot see provenance through a variable.
    (b) fires on any checkpoint write (raw writer or helper) executed by
    a loop body with no enclosing cadence-shaped If (``%`` in the test,
    a ``due()``-style call, or an every/interval/cadence-named operand).
    A deliberate bare write (a one-shot export script) is waived inline
    with a reason.
    """

    rule_id = "JL014"
    severity = Severity.WARNING
    summary = "checkpoint write bypasses the atomic helper or lacks a cadence guard"

    @staticmethod
    def _writer_call(node: ast.AST):
        """(dotted name, destination arg node) for a raw-writer call."""
        if not isinstance(node, ast.Call):
            return None
        name = dotted_name(node.func)
        if name is None:
            return None
        idx = _CKPT_WRITERS.get(name)
        if idx is None or len(node.args) <= idx:
            return None
        return name, node.args[idx]

    @staticmethod
    def _helper_call(node: ast.AST) -> str | None:
        if not isinstance(node, ast.Call):
            return None
        name = dotted_name(node.func)
        if name is None:
            return None
        if name.split(".")[-1] in _CKPT_HELPER_TAILS:
            return name
        return None

    @staticmethod
    def _is_direct_path(dest: ast.AST) -> bool:
        """A destination the writer will open as its FINAL path: a
        literal, an f-string, or an os.path.join(...) — not a Name
        (could be a mkstemp temp or an in-memory buffer)."""
        if isinstance(dest, ast.Constant) and isinstance(dest.value, str):
            return True
        if isinstance(dest, ast.JoinedStr):
            return True
        if isinstance(dest, ast.Call):
            name = dotted_name(dest.func) or ""
            return name in {"os.path.join", "path.join"}
        return False

    @classmethod
    def _is_cadence_test(cls, test: ast.AST) -> bool:
        for node in ast.walk(test):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
                return True
            if isinstance(node, ast.Call):
                name = (dotted_name(node.func) or "").split(".")[-1]
                if name in _CADENCE_GATE_CALLS:
                    return True
            if isinstance(node, (ast.Name, ast.Attribute)):
                label = (dotted_name(node) or "").lower()
                if any(h in label for h in _CADENCE_NAME_HINTS):
                    return True
        return False

    @classmethod
    def _unguarded_loop_nodes(cls, loop: ast.AST) -> Iterator[ast.AST]:
        """Loop-body nodes NOT under a cadence-shaped If (and not in a
        nested scope — same rationale as iter_loop_body_nodes)."""
        stack: list[ast.AST] = list(loop.body) + list(loop.orelse)
        while stack:
            node = stack.pop()
            if isinstance(node, _SCOPE_NODES):
                continue
            if isinstance(node, ast.If) and cls._is_cadence_test(node.test):
                # The guarded branch is sanctioned; the else branch is
                # still per-iteration work.
                stack.extend(node.orelse)
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        # (a) raw writer straight onto a named final path, anywhere.
        for node in ast.walk(ctx.tree):
            hit = self._writer_call(node)
            if hit is None:
                continue
            name, dest = hit
            if self._is_direct_path(dest):
                yield self.finding(
                    ctx, node,
                    f"{name}(...) writes a checkpoint directly to its "
                    "final path: a writer killed mid-write leaves a TORN "
                    "file the next load explodes on; route through "
                    "utils/checkpoint.py (save_train_state / "
                    "save_state_dict / _atomic_write: mkstemp + fsync + "
                    "atomic replace)",
                )
        # (b) any checkpoint write in a loop with no cadence guard.
        flagged: set[ast.AST] = set()
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            for node in self._unguarded_loop_nodes(loop):
                if node in flagged:
                    continue
                name = self._helper_call(node)
                if name is None:
                    hit = self._writer_call(node)
                    name = hit[0] if hit else None
                if name is None:
                    continue
                flagged.add(node)
                yield self.finding(
                    ctx, node,
                    f"{name}(...) runs on EVERY iteration of this loop: "
                    "an unguarded in-loop checkpoint write serializes a "
                    "full state materialization + disk write into each "
                    "step; gate it on a cadence (`if step % N == 0:` / "
                    "`if checkpointer.due(step):` — "
                    "resilience/checkpoint.py) or move it out of the loop",
                )


# ---------------------------------------------------------------------------
# JL015 — unbounded rendezvous / unsupervised training-script launches


# Spellings of the multi-process world-formation entry point.  Matched on
# the dotted tail so both `jax.distributed.initialize(...)` and a
# `from jax import distributed; distributed.initialize(...)` resolve.
_RDZV_TAILS = ("distributed.initialize",)

# Launch calls the rule polices: the blocking and the supervisable
# spawn.  `subprocess.run` is deliberately absent — the repo's bench
# probes use it for short-lived device checks with their own timeouts,
# which is not the launcher shape.
_LAUNCH_CALLS = {"subprocess.call", "subprocess.Popen", "Popen"}


class ElasticLaunchRule(Rule):
    """JL015: a world-formation or process-launch call with no failure
    story — the two hazards the elastic runtime exists to remove
    (docs/ROBUSTNESS.md elastic section).

    (a) **Unbounded rendezvous**: a bare ``jax.distributed.initialize(...)``
    with no ``initialization_timeout`` argument and no surrounding
    bounded-retry shape (a ``for ... in range(...)`` loop) inherits
    jax's 300-second near-hang — one dead or late rank wedges the whole
    gang with zero diagnostics.  Route through
    ``parallel/distributed.initialize_with_retry`` (bounded attempts
    inside ``--rdzv-timeout-s``, a who-is-missing error) or at least
    pass the timeout.

    (b) **Unsupervised launch**: a ``subprocess.call``/``Popen`` of a
    Python script (``sys.executable`` or a ``*.py`` argument) in a
    module with NO signal handling anywhere (no ``signal`` usage at
    all).  A SIGTERM to such a launcher orphans the child — silently
    defeating the trainer's ``--preempt-grace-s`` emergency save — and
    a dead child is never detected, restarted, or even reported.
    Launcher-shaped modules must forward signals and supervise
    (``parallel/elastic.GangSupervisor``); one-shot probe drivers that
    deliberately fire-and-collect are waived inline with a reason.

    Heuristics: (a) fires on any call whose dotted name ends with
    ``distributed.initialize``, lacking an ``initialization_timeout``
    keyword, unless a lexically enclosing ``for`` iterates a literal
    ``range(...)`` (the bounded-retry idiom).  (b) fires on a
    ``subprocess.call``/``subprocess.Popen``/``Popen`` call whose first
    argument is a list containing ``sys.executable`` or a string
    constant ending ``.py``, in a module that never references the name
    ``signal`` (import, attribute, or call) — referencing it at all is
    taken as "this module thought about signals".
    """

    rule_id = "JL015"
    severity = Severity.WARNING
    summary = "unbounded rendezvous or unsupervised training-script launch"

    @staticmethod
    def _is_initialize(node: ast.Call) -> bool:
        name = dotted_name(node.func)
        return name is not None and any(
            name == tail or name.endswith("." + tail) for tail in _RDZV_TAILS
        )

    @staticmethod
    def _in_bounded_retry(node: ast.AST, parents: dict) -> bool:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.For) and (
                isinstance(cur.iter, ast.Call)
                and dotted_name(cur.iter.func) in {"range", "builtins.range"}
            ):
                return True
            cur = parents.get(cur)
        return False

    @staticmethod
    def _is_script_cmd(cmd: ast.AST, script_names: set[str]) -> bool:
        if isinstance(cmd, ast.Name) and cmd.id in script_names:
            return True  # cmd = [sys.executable, ...] assembled earlier
        elements: list[ast.AST] = []
        if isinstance(cmd, (ast.List, ast.Tuple)):
            elements = list(cmd.elts)
            for el in cmd.elts:
                if isinstance(el, ast.Starred):
                    elements.append(el.value)
        else:
            elements = [cmd]
        for el in elements:
            if dotted_name(el) == "sys.executable":
                return True
            if (isinstance(el, ast.Constant) and isinstance(el.value, str)
                    and el.value.endswith(".py")):
                return True
        return False

    @classmethod
    def _script_cmd_names(cls, tree: ast.Module) -> set[str]:
        """Names bound (anywhere in the module) to a list/tuple literal
        containing ``sys.executable`` or a ``*.py`` constant — the
        ``cmd = [sys.executable, script, ...]`` idiom the original
        unsupervised launcher used."""
        names: set[str] = set()
        for node in ast.walk(tree):
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, (ast.List, ast.Tuple))
                    and cls._is_script_cmd(node.value, set())):
                names.add(node.targets[0].id)
        return names

    @classmethod
    def _is_script_launch(cls, node: ast.Call, script_names: set[str]) -> bool:
        name = dotted_name(node.func)
        if name not in _LAUNCH_CALLS:
            return False
        if not node.args:
            return False
        return cls._is_script_cmd(node.args[0], script_names)

    @staticmethod
    def _module_handles_signals(tree: ast.Module) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, ast.Name) and node.id in (
                "signal", "GangSupervisor",
            ):
                # Referencing `signal` means "this module thought about
                # signals"; referencing GangSupervisor means the spawns
                # are routed through the supervised launcher, which
                # forwards signals by construction.
                return True
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                names = [a.name for a in node.names]
                if "signal" in names or getattr(node, "module", None) == "signal":
                    return True
                if "GangSupervisor" in names:
                    return True
            if isinstance(node, ast.Attribute) and node.attr in (
                "send_signal", "install_signals",
            ):
                return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        signal_aware = self._module_handles_signals(ctx.tree)
        script_names = self._script_cmd_names(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if self._is_initialize(node):
                has_timeout = any(
                    kw.arg == "initialization_timeout" for kw in node.keywords
                )
                if not has_timeout and not self._in_bounded_retry(
                    node, parents
                ):
                    yield self.finding(
                        ctx, node,
                        "jax.distributed.initialize(...) with no "
                        "initialization_timeout and no bounded-retry shape: "
                        "one dead or late rank hangs the gang for jax's "
                        "default 300 s with zero diagnostics; route through "
                        "parallel/distributed.initialize_with_retry "
                        "(bounded attempts inside --rdzv-timeout-s, a "
                        "who-is-missing error) or pass the timeout",
                    )
            elif self._is_script_launch(node, script_names) and not signal_aware:
                yield self.finding(
                    ctx, node,
                    f"{dotted_name(node.func)}(...) launches a Python "
                    "script from a module with no signal handling: SIGTERM "
                    "to this launcher orphans the child (silently defeating "
                    "the trainer's emergency-save path) and a dead child is "
                    "never detected or restarted; forward signals and "
                    "supervise (parallel/elastic.GangSupervisor, "
                    "parallel/launch.py)",
                )


# ---------------------------------------------------------------------------
# JL016 — deadline-blind fixed linger in a dispatch loop


# Loop-body identifiers that count as "this loop consults request
# deadlines": the deadline itself, a remaining-budget computation, an
# expiry check, or a due()-style gate.  Any ONE of them anywhere in the
# loop body is taken as deadline-awareness (the taught idiom computes a
# close deadline from the oldest member's budget and sleeps THAT).
_DEADLINE_HINTS = ("deadline", "remaining", "budget", "expire", "due")

_SLEEP_CALLS = {"time.sleep", "sleep"}


class FixedLingerDispatchRule(Rule):
    """JL016: a dispatch loop that sleeps a FIXED linger, blind to
    request deadlines — the tail-latency hazard class the deadline-aware
    batch close exists to remove (docs/SERVING.md).

    The shape ``while True: batch = drain(queue); time.sleep(LINGER);
    engine.launch(batch)`` treats the linger as a constant of nature:
    every request pays it, including the one whose deadline budget is
    nearly spent — which then expires in the batch (a wasted device
    slot) or answers at p99 instead of p50.  The taught idiom
    (serving/batcher.py ``_close_at``) computes the batch close from
    ``min(global linger, oldest member's deadline - service estimate)``
    and waits THAT, so the sleep is never longer than the tightest
    budget aboard allows.

    Heuristics: fires on a ``time.sleep(X)`` where (a) the enclosing
    loop is unbounded (any ``while``, or a ``for`` over something other
    than a literal ``range(...)``); (b) the same loop body dispatches —
    a known-jitted call (JL009's resolution: ``jax.jit`` values,
    ``RecompileSentinel`` wraps, ``self.attr`` bindings) or any
    ``*.launch(...)`` attribute call; (c) ``X`` is a numeric constant or
    a linger-named value; and (d) NOTHING in the loop body mentions a
    deadline-shaped name (deadline/remaining/budget/expire/due) — one
    mention anywhere is taken as deadline-awareness.  A deliberately
    fixed cadence (a metronome-style replay driver) is waived inline
    with a reason.
    """

    rule_id = "JL016"
    severity = Severity.WARNING
    summary = "dispatch loop sleeps a fixed linger, blind to request deadlines"

    @staticmethod
    def _fixed_sleep(node: ast.AST) -> bool:
        """``time.sleep(<const>)`` or ``time.sleep(<linger-named>)``."""
        if not isinstance(node, ast.Call):
            return False
        if dotted_name(node.func) not in _SLEEP_CALLS:
            return False
        if not node.args:
            return False
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(
            arg.value, (int, float)
        ):
            return True
        label = (dotted_name(arg) or "").lower()
        return "linger" in label

    @staticmethod
    def _mentions_deadline(body_nodes: list[ast.AST]) -> bool:
        for node in body_nodes:
            label = ""
            if isinstance(node, ast.Attribute):
                label = (dotted_name(node) or node.attr).lower()
            elif isinstance(node, ast.Name):
                label = node.id.lower()
            if label and any(h in label for h in _DEADLINE_HINTS):
                return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        module_jit = module_jit_names(ctx.tree)
        jit_attrs = jit_attr_names(ctx.tree)
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            if SwallowedDispatchErrorRule._is_bounded_for(loop):
                continue  # a bounded replay/retry is not a dispatch loop
            body_nodes = list(iter_loop_body_nodes(loop))
            dispatches = any(
                isinstance(n, ast.Call)
                and (
                    is_jit_call(n, module_jit, jit_attrs)
                    or (isinstance(n.func, ast.Attribute)
                        and n.func.attr == "launch")
                )
                for n in body_nodes
            )
            if not dispatches:
                continue
            if self._mentions_deadline(body_nodes):
                continue
            for node in body_nodes:
                if self._fixed_sleep(node):
                    yield self.finding(
                        ctx, node,
                        "fixed linger sleep inside a dispatch loop that "
                        "never consults request deadlines: every request "
                        "pays the full linger, and one whose budget is "
                        "nearly spent expires in the batch or answers at "
                        "p99; close the batch from the oldest member's "
                        "remaining deadline budget instead "
                        "(serving/batcher.py _close_at — "
                        "min(linger, deadline - service estimate))",
                    )


# ---------------------------------------------------------------------------
# JL017 — blocking network read without a timeout in an unbounded loop


# Calls with a ``timeout`` PARAMETER the author left unset.  Value:
# (dotted-name spellings, positional index of ``timeout``) — a call
# covering the index positionally has set it.
_TIMEOUT_PARAM_CALLS = (
    ({"urlopen", "urllib.request.urlopen", "request.urlopen"}, 2),
    ({"create_connection", "socket.create_connection"}, 1),
)

# Raw reads with NO timeout parameter of their own: the deadline lives
# on the socket (``settimeout``) or in the loop's own budget math, so
# these only fire when the loop body shows neither.
_RAW_READ_ATTRS = {"recv", "recv_into", "getresponse", "accept"}

_NET_DEADLINE_HINTS = (
    "deadline", "remaining", "budget", "timeout", "expire", "due",
)


class BlockingNetReadLoopRule(Rule):
    """JL017: a blocking socket/HTTP read without a timeout inside an
    unbounded control-plane or dispatch loop.

    The fleet tier's hazard class (docs/SERVING.md): a supervisor,
    poller, or proxy loop that calls ``urlopen(url)`` (no timeout),
    ``socket.create_connection(addr)`` (no timeout), or a raw
    ``sock.recv()`` / ``conn.getresponse()`` with no socket deadline
    anywhere in the loop hangs FOREVER the first time the peer wedges —
    and in a control plane, the hung loop is the component whose whole
    job was to detect exactly that wedge.  The taught idiom is the
    fleet front's per-attempt deadline (serving/fleet.py
    ``Backend.request``): every attempt carries ``timeout_s``, computed
    from the request's remaining budget.

    Heuristics: fires inside an unbounded loop (any ``while``, or a
    ``for`` over something other than a literal ``range(...)`` — JL016's
    resolution) on (a) a timeout-parameterized call (``urlopen``,
    ``create_connection``) whose ``timeout`` is neither a keyword nor
    covered positionally — these fire regardless of loop context,
    because the fix is one argument; and (b) a raw read
    (``.recv``/``.recv_into``/``.getresponse``/``.accept``) when
    NOTHING in the loop body mentions a deadline-shaped name
    (deadline/remaining/budget/timeout/expire/due — a ``settimeout`` or
    budget computation anywhere in the loop is taken as awareness).  A
    deliberately blocking accept loop (a test fixture server) is waived
    inline with a reason.
    """

    rule_id = "JL017"
    severity = Severity.WARNING
    summary = (
        "blocking network read without a timeout in an unbounded loop"
    )

    @staticmethod
    def _missing_timeout_call(node: ast.AST) -> bool:
        """A timeout-parameterized net call that leaves timeout unset."""
        if not isinstance(node, ast.Call):
            return False
        name = dotted_name(node.func)
        for spellings, timeout_pos in _TIMEOUT_PARAM_CALLS:
            if name in spellings:
                if any(kw.arg == "timeout" for kw in node.keywords):
                    return False
                if any(kw.arg is None for kw in node.keywords):
                    return False  # **kwargs may carry it; benefit of doubt
                return len(node.args) <= timeout_pos
        return False

    @staticmethod
    def _raw_read_call(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _RAW_READ_ATTRS
        )

    @staticmethod
    def _mentions_net_deadline(body_nodes: list[ast.AST]) -> bool:
        for node in body_nodes:
            label = ""
            if isinstance(node, ast.Attribute):
                label = (dotted_name(node) or node.attr).lower()
            elif isinstance(node, ast.Name):
                label = node.id.lower()
            elif isinstance(node, ast.keyword) and node.arg:
                label = node.arg.lower()
            if label and any(h in label for h in _NET_DEADLINE_HINTS):
                return True
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            if SwallowedDispatchErrorRule._is_bounded_for(loop):
                continue  # a bounded replay/retry is not a control loop
            body_nodes = list(iter_loop_body_nodes(loop))
            deadline_aware = self._mentions_net_deadline(body_nodes)
            for node in body_nodes:
                if self._missing_timeout_call(node):
                    yield self.finding(
                        ctx, node,
                        "network call with its timeout parameter unset "
                        "inside an unbounded loop: the first wedged peer "
                        "hangs this loop forever — and a control-plane "
                        "loop is usually the thing that was supposed to "
                        "DETECT the wedge; pass timeout= (the fleet "
                        "tier's per-attempt deadline, serving/fleet.py "
                        "Backend.request)",
                    )
                elif not deadline_aware and self._raw_read_call(node):
                    yield self.finding(
                        ctx, node,
                        "raw blocking read (.recv/.getresponse/.accept) "
                        "in an unbounded loop that never touches a "
                        "timeout or deadline: set a socket timeout "
                        "(settimeout) or compute a per-attempt deadline "
                        "from the remaining budget (serving/fleet.py "
                        "Backend.request)",
                    )


# ---------------------------------------------------------------------------
# JL018 — float-list JSON serialization in an unbounded dispatch/serve loop


# json-render spellings (the serializer half of the pattern).
_JSON_DUMP_CALLS = {"json.dumps", "dumps", "json.dump"}


class FloatListJSONLoopRule(Rule):
    """JL018: ``json.dumps`` of ``.tolist()``'d array data inside an
    unbounded dispatch/serve loop.

    The host hot path's hazard class (docs/SERVING.md wire protocol):
    rendering an array as a JSON float list costs ~1 µs per element on
    the way out and the same again at the peer's parse — for a 784-pixel
    MNIST row batch that is MILLISECONDS of pure text work per request,
    paid on every iteration of a loop that never ends.  The committed
    sweeps showed this exact cost class as the serving ceiling
    ("host-bound on 2 cores").  The taught idiom is the binary wire
    path (serving/wire.py): a fixed header plus ``tobytes()`` raw
    float32, parsed by the peer with one zero-copy ``np.frombuffer`` —
    and for one-shot reports/artifacts (bounded work), float-list JSON
    is fine and this rule stays silent.

    Heuristics: fires on a ``json.dumps``/``json.dump`` call whose
    argument subtree contains a ``.tolist()`` call (the array-shaped
    giveaway — ``tolist`` is the numpy/jax array spelling, so the value
    is known array data) inside an unbounded loop (any ``while``, or a
    ``for`` over a non-``range`` iterable — JL016's resolution; bounded
    literal replays are not serve loops).  A deliberately-JSON streamer
    (a debug endpoint, a compatibility shim) is waived inline with a
    reason.
    """

    rule_id = "JL018"
    severity = Severity.WARNING
    summary = (
        "float-list JSON serialization of array data in an unbounded "
        "dispatch/serve loop"
    )

    @staticmethod
    def _tolist_inside(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "tolist"):
                return True
        return False

    def _dumps_of_tolist(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        if dotted_name(node.func) not in _JSON_DUMP_CALLS:
            return False
        return any(self._tolist_inside(arg) for arg in node.args) or any(
            kw.value is not None and self._tolist_inside(kw.value)
            for kw in node.keywords
        )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            if SwallowedDispatchErrorRule._is_bounded_for(loop):
                continue  # a bounded replay/report pass is not a serve loop
            for node in iter_loop_body_nodes(loop):
                if self._dumps_of_tolist(node):
                    yield self.finding(
                        ctx, node,
                        "array data rendered as a JSON float list inside "
                        "an unbounded loop: every iteration pays "
                        "per-element text encode (and the peer pays the "
                        "matching parse) — milliseconds per request of "
                        "pure host work, the measured serving ceiling; "
                        "send raw bytes instead (serving/wire.py: fixed "
                        "header + tobytes(), parsed with one zero-copy "
                        "np.frombuffer)",
                    )


# ---------------------------------------------------------------------------
# JL022 — weights loaded or mutated behind the registry's back (serving)


# Checkpoint-load spellings whose return value is a live weight tree.
# Matched by last segment too (`checkpoint.load_state_dict(...)` and the
# bare from-import both fire): unlike the transform table, a serving
# module has no legitimate same-named local helper.
_WEIGHT_LOAD_CALLS = {
    "load_inference_variables", "load_state_dict", "load_variables",
}

# Attributes that ARE the serving weight surface: reassigning them on a
# foreign object is a weight swap that skips digest verification, cache
# invalidation, and the registry manifest.
_WEIGHT_SURFACE_ATTRS = {"variables", "weights_digest"}

# Modules that legitimately own the weight surface.  registry.py is the
# taught idiom itself; rollout.py drives it; engine.py implements the
# publish/install primitives the registry calls; checkpoint helpers and
# tests are out of scope by the serving/ path gate.
_REGISTRY_SURFACE_MODULES = {"registry.py", "rollout.py", "engine.py"}


class RegistryBypassRule(Rule):
    """JL022: a serving module loads checkpoint weights or mutates the
    engine weight surface directly instead of going through the model
    registry.

    The model registry's hazard class (docs/SERVING.md): once
    ``ModelRegistry`` owns (model, version) → (checkpoint, digest,
    Program grid), any serving-side code that calls
    ``load_inference_variables(path)`` itself — or reassigns
    ``engine.variables`` / ``engine.weights_digest`` from outside the
    engine — creates a weight state the registry cannot see: the served
    digest no longer matches the manifest, the response cache keeps
    answering from the OLD weights (its keys embed the digest the
    registry last published), and a later swap/rollback restores a
    version the operator never knew had been displaced.  The taught
    idiom is the registry surface (serving/registry.py):
    ``ModelRegistry.resolve()`` + ``load()`` to get verified weights,
    ``publish()`` to admit a checkpoint, and
    ``RolloutController.swap()`` / ``engine.publish_weights()`` for a
    live cutover — digest-checked, cache-invalidating, on the record.

    Heuristics: applies only to modules under a ``serving/`` path
    component, excluding the registry surface itself (``registry.py``,
    ``rollout.py``, ``engine.py``).  Fires on (a) any call whose name's
    last segment is a checkpoint-load spelling
    (``load_inference_variables`` / ``load_state_dict`` /
    ``load_variables``), and (b) any assignment whose target is
    ``<non-self>.variables`` or ``<non-self>.weights_digest``
    (``self.variables = ...`` in a module's own constructor is that
    module's own state, not a foreign engine's).  A pre-registry CLI
    path (``--checkpoint`` without ``--registry``) is waived inline
    with a reason.
    """

    rule_id = "JL022"
    severity = Severity.WARNING
    summary = (
        "checkpoint weights loaded or engine weight surface mutated "
        "outside the model registry in a serving module"
    )

    @staticmethod
    def _in_scope(ctx: ModuleContext) -> bool:
        parts = ctx.path.replace("\\", "/").split("/")
        if "serving" not in parts[:-1]:
            return False
        return parts[-1] not in _REGISTRY_SURFACE_MODULES

    @staticmethod
    def _load_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = dotted_name(node.func)
        if not name:
            return False
        return name.rsplit(".", 1)[-1] in _WEIGHT_LOAD_CALLS

    @staticmethod
    def _foreign_weight_target(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr in _WEIGHT_SURFACE_ATTRS
            and not (isinstance(node.value, ast.Name)
                     and node.value.id == "self")
        )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not self._in_scope(ctx):
            return
        for node in ast.walk(ctx.tree):
            if self._load_call(node):
                yield self.finding(
                    ctx, node,
                    "checkpoint weights loaded directly in a serving "
                    "module: the registry cannot see this weight state "
                    "— the served digest diverges from the manifest and "
                    "the response cache keys stay pinned to the last "
                    "published digest; resolve through the registry "
                    "surface instead (serving/registry.py "
                    "ModelRegistry.resolve()/load(), publish() to admit "
                    "a new checkpoint)",
                )
                continue
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for target in targets:
                if self._foreign_weight_target(target):
                    yield self.finding(
                        ctx, node,
                        "engine weight surface mutated from outside the "
                        "engine: reassigning .variables/.weights_digest "
                        "behind the registry skips digest verification "
                        "and cache invalidation — a torn or invisible "
                        "swap; use engine.publish_weights() via "
                        "RolloutController.swap() "
                        "(serving/rollout.py) so the cutover is "
                        "digest-checked, cache-invalidating, and on "
                        "the record",
                    )


# ---------------------------------------------------------------------------
# JL023 — per-item pow2 padding inside a dispatch loop (packed batching)


# The bucket-math helpers whose presence marks a pad as pow2-ladder
# padding (serving/buckets.py owns all of them).  Matched by last
# segment so `buckets.next_power_of_two(...)` and the bare from-import
# both fire.
_POW2_PAD_HELPERS = {
    "pad_to_bucket", "next_power_of_two", "bucket_for", "pow2_buckets",
}

# Raw pad spellings that, fed a bucket-derived width, reimplement
# pad_to_bucket inline.
_RAW_PAD_CALLS = {"np.pad", "numpy.pad", "jnp.pad", "jax.numpy.pad"}


class Pow2PadDispatchRule(Rule):
    """JL023: per-item pow2/bucket padding inside an unbounded dispatch
    loop outside the bucket helper module.

    The device hot-path waste class packed batching retired (PR 19,
    docs/SERVING.md): padding each request (or each forming batch) up to
    its pow2 bucket inside the dispatch loop burns device rows on
    padding — mean fill ~0.3 at MNIST request sizes — and re-grows the
    per-bucket executable ladder the packed rows-capacity path
    deliberately collapsed.  Padding is a *formation* decision, made
    once, behind the serving surface: the bucketed path owns it in
    ``serving/buckets.py`` (``StagingPool`` + ``pad_to_bucket``), and
    the packed path replaces it with segment-id concatenation
    (``segment_ids``) so the only padding left is the single buffer
    tail.  A dispatch loop that calls ``pad_to_bucket`` — or
    reimplements it inline as ``np.pad``/``jnp.pad`` fed
    ``next_power_of_two``/``bucket_for`` widths — is hiding ladder
    waste where the fill metrics and the SLO gate's ratcheted
    ``min_mean_fill_ratio`` cannot see it coming.

    Heuristics: fires inside unbounded loops (``while``/non-replay
    ``for``, same boundedness test as JL013/JL018) on (a) any call
    whose name's last segment is ``pad_to_bucket``, and (b) any
    ``np.pad``/``jnp.pad`` call with a bucket-math helper call
    (``next_power_of_two``/``bucket_for``/``pow2_buckets``) anywhere in
    its arguments.  ``serving/buckets.py`` itself is exempt — it IS the
    sanctioned home of the pow2 ladder.
    """

    rule_id = "JL023"
    severity = Severity.WARNING
    summary = (
        "per-item pow2/bucket padding inside a dispatch loop; let the "
        "batcher form batches (packed, or StagingPool-bucketed) instead"
    )

    @staticmethod
    def _in_scope(ctx: ModuleContext) -> bool:
        parts = ctx.path.replace("\\", "/").split("/")
        return not (
            parts[-1] == "buckets.py" and "serving" in parts[:-1]
        )

    @staticmethod
    def _helper_call(node: ast.AST, names: set[str]) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = dotted_name(node.func)
        return bool(name) and name.rsplit(".", 1)[-1] in names

    @classmethod
    def _pow2_pad(cls, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        if cls._helper_call(node, {"pad_to_bucket"}):
            return True
        if dotted_name(node.func) not in _RAW_PAD_CALLS:
            return False
        in_args = list(node.args) + [
            kw.value for kw in node.keywords if kw.value is not None
        ]
        return any(
            cls._helper_call(sub, _POW2_PAD_HELPERS)
            for arg in in_args
            for sub in ast.walk(arg)
        )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not self._in_scope(ctx):
            return
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            if SwallowedDispatchErrorRule._is_bounded_for(loop):
                continue  # a bounded replay/report pass is not a serve loop
            for node in iter_loop_body_nodes(loop):
                if self._pow2_pad(node):
                    yield self.finding(
                        ctx, node,
                        "pow2/bucket padding inside an unbounded dispatch "
                        "loop: every iteration pays padding rows the "
                        "device computes and throws away, and each "
                        "distinct bucket shape grows the executable "
                        "ladder — the waste packed batching deletes "
                        "(serving/batcher.py packed mode: requests "
                        "concatenate into one rows-capacity buffer + "
                        "segment ids, padding only the single buffer "
                        "tail); form batches behind the serving surface "
                        "instead of padding per item here",
                    )


# ---------------------------------------------------------------------------
# JL024 — sharded predict-step built over an inline mesh inside a loop


# Mesh constructors (parallel/mesh.py owns all but `Mesh` itself).
# Matched by last segment so both `mesh.replica_mesh(...)` and the bare
# from-import spelling fire.
_MESH_BUILDER_CALLS = {
    "Mesh", "make_mesh", "make_2d_mesh", "make_nd_mesh",
    "single_device_mesh", "replica_mesh",
}


class ShardedStepMeshLoopRule(Rule):
    """JL024: sharded predict-step construction closing over a mesh
    built inside the same dispatch/warmup loop.

    The predict-step builders (``make_tp_predict_step``,
    ``make_ep_predict_step``, ``make_pp_predict_step``, ...) close over
    a concrete ``Mesh``: the mesh's device tuple is part of the trace
    and of every AOT cache key (compile/program.py ``predict_config``).
    Building a *fresh* mesh each loop iteration — even over the same
    devices — hands the builder a new closure identity per pass, so
    every iteration re-traces, the ExecutableStore never hits, and the
    RecompileSentinel budget burns down on shapes that were already
    compiled.  The sanctioned pattern threads ONE mesh in from outside
    the loop (serving/pool.py plans replica meshes once, at
    construction) or uses a module-level mesh.

    Heuristics: fires on any call whose name's last segment looks like
    ``make_*predict_step`` inside any loop body when its mesh argument
    (first positional, or ``mesh=``) is (a) an inline mesh-builder call
    (``Mesh``/``make_mesh``/``make_2d_mesh``/``make_nd_mesh``/
    ``single_device_mesh``/``replica_mesh``), or (b) a name assigned
    from one of those inside the same loop body.  A mesh threaded in as
    a parameter or built at module level is exempt — that is the fix.
    Bounded loops are NOT exempt here (unlike JL013/JL018/JL023): a
    per-iteration mesh re-traces in a bounded warmup sweep exactly as
    it does in a serve loop.
    """

    rule_id = "JL024"
    severity = Severity.WARNING
    summary = (
        "sharded predict-step built over a mesh created inside the "
        "loop; build the mesh once outside and thread it in"
    )

    @staticmethod
    def _mesh_builder_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = dotted_name(node.func)
        return bool(name) and name.rsplit(".", 1)[-1] in _MESH_BUILDER_CALLS

    @staticmethod
    def _step_builder_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        name = dotted_name(node.func)
        if not name:
            return False
        last = name.rsplit(".", 1)[-1]
        return last.startswith("make_") and last.endswith("predict_step")

    @staticmethod
    def _mesh_arg(call: ast.Call) -> ast.AST | None:
        for kw in call.keywords:
            if kw.arg == "mesh":
                return kw.value
        return call.args[0] if call.args else None

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            body = list(iter_loop_body_nodes(loop))
            # Names bound to a fresh mesh within THIS loop body: their
            # use as a mesh arg is the two-line spelling of the inline
            # builder call.
            loop_meshes: set[str] = set()
            for node in body:
                targets: list[ast.AST] = []
                if isinstance(node, ast.Assign):
                    targets, value = list(node.targets), node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                else:
                    continue
                if self._mesh_builder_call(value):
                    loop_meshes.update(
                        t.id for t in targets if isinstance(t, ast.Name)
                    )
            for node in body:
                if not self._step_builder_call(node):
                    continue
                mesh = self._mesh_arg(node)
                if mesh is None:
                    continue
                if self._mesh_builder_call(mesh) or (
                    isinstance(mesh, ast.Name) and mesh.id in loop_meshes
                ):
                    yield self.finding(
                        ctx, node,
                        "predict-step builder closing over a mesh created "
                        "inside the loop: the mesh is part of the trace "
                        "and AOT cache identity, so every iteration "
                        "re-traces and the executable store never hits — "
                        "build the replica mesh ONCE outside the loop "
                        "(serving/pool.py plans meshes at construction) "
                        "and thread it in via mesh=",
                    )


ALL_RULES: tuple[Rule, ...] = (
    KeyReuseRule(),
    HostSyncRule(),
    SideEffectRule(),
    RetraceRule(),
    DonationRule(),
    DeviceGetLoopRule(),
    BucketShapeRule(),
    TelemetryUnderTraceRule(),
    BlockingReadLoopRule(),
    SerialWarmupRule(),
    HostBlockingFeedRule(),
    EngineLoopRule(),
    SwallowedDispatchErrorRule(),
    CheckpointWriteRule(),
    ElasticLaunchRule(),
    FixedLingerDispatchRule(),
    BlockingNetReadLoopRule(),
    FloatListJSONLoopRule(),
    RegistryBypassRule(),
    Pow2PadDispatchRule(),
    ShardedStepMeshLoopRule(),
)


def rule_by_id(rule_id: str) -> Rule:
    for rule in ALL_RULES:
        if rule.rule_id == rule_id.upper():
            return rule
    raise KeyError(f"unknown rule id {rule_id!r}")
