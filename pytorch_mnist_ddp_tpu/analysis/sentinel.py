"""Runtime recompile sentinel: fail loudly when a jitted function
retraces more often than its caller expects.

Static analysis (JL004) catches the *structural* retrace generators;
this catches the behavioral ones — a dtype that flips per batch, a shape
that wobbles on the last partial batch, a Python scalar in the arg list
— by watching the real trace cache of a ``jax.jit`` callable.  A train
step that silently compiles 40 times instead of once is invisible in
test assertions (the numbers are right!) and cost the round-3 bench
investigation hours; wrapped in a sentinel, the second unexpected trace
is a test failure with a pointed message.

Usage::

    step = RecompileSentinel(make_train_step(mesh), max_traces=1)
    for batch in loader:
        state, loss = step(state, *batch)   # raises RecompileError on trace 2

The trace count is read from the jit callable's own cache
(``_cache_size``), so the sentinel adds no tracing hooks and zero
per-call device work.
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Callable


class RecompileError(AssertionError):
    """A jitted function exceeded its expected trace count.

    Subclasses ``AssertionError`` so pytest renders it as a plain test
    failure (with the sentinel's diagnosis) rather than an error.
    """


class RecompileSentinel:
    """Wrap a jitted callable and bound its number of traces.

    Parameters
    ----------
    fn:
        The ``jax.jit`` (or ``pjit``) callable to guard.  Must expose a
        trace-cache size (every ``jax.jit`` result does); wrapping a
        plain Python function is a usage error and raises ``TypeError``
        immediately rather than silently never failing.
    max_traces:
        The number of distinct traces the caller considers legitimate.
        1 for a fixed-shape hot loop; 2 when e.g. a final partial batch
        legitimately compiles a second program.
    name:
        Label used in error messages; defaults to the wrapped function's.
    registry:
        Optional obs registry (duck-typed: anything with ``.counter(name,
        help=..., **labels)``) — every observed trace increments
        ``jax_compiles_total{fn=name}``, so retraces become a scrapeable
        counter (serving exposes it via ``GET /metrics``) instead of a
        number that only surfaces when the budget is already blown.
        Kept duck-typed so this module stays importable with zero
        package dependencies (analysis/engine.py contract).
    """

    def __init__(
        self,
        fn: Callable[..., Any],
        max_traces: int = 1,
        name: str | None = None,
        registry=None,
    ):
        cache_size = getattr(fn, "_cache_size", None)
        if not callable(cache_size):
            raise TypeError(
                "RecompileSentinel needs a jax.jit-compiled callable (got "
                f"{fn!r} with no trace cache); jit the function first"
            )
        if max_traces < 1:
            raise ValueError(f"max_traces must be >= 1, got {max_traces}")
        self._fn = fn
        self.max_traces = max_traces
        self.name = name or getattr(fn, "__name__", repr(fn))
        self.calls = 0
        # Parallel warmup (compile/service.py) calls the sentinel from
        # several threads at once; the call counter and the reported-trace
        # high-water mark are read-modify-write state, so both go under a
        # lock or jax_compiles_total over-counts on concurrent completions.
        self._lock = threading.Lock()
        self._compile_counter = (
            registry.counter(
                "jax_compiles_total",
                help="distinct traces of sentinel-guarded jitted functions",
                fn=self.name,
            )
            if registry is not None
            else None
        )
        self._reported_traces = 0
        functools.update_wrapper(self, fn, updated=())

    def trace_count(self) -> int:
        """Distinct traces the wrapped function has accumulated so far."""
        return int(self._fn._cache_size())

    def _report_compiles(self, traces: int) -> None:
        # Registry reporting happens BEFORE the bound check, so the
        # over-budget trace is on the counter even when check() raises —
        # the scrape shows what actually compiled, not what was allowed.
        if self._compile_counter is None:
            return
        with self._lock:
            delta = traces - self._reported_traces
            if delta > 0:
                self._compile_counter.inc(delta)
                self._reported_traces = traces

    def check(self) -> None:
        """Assert the trace bound now (also runs after every call)."""
        traces = self.trace_count()
        self._report_compiles(traces)
        if traces > self.max_traces:
            with self._lock:
                calls = self.calls
            raise RecompileError(
                f"{self.name} retraced: {traces} traces after {calls} "
                f"calls (expected <= {self.max_traces}). Something in the "
                "call signature is unstable — look for changing shapes/"
                "dtypes (last partial batch?), Python scalars that vary per "
                "call (pass jnp scalars), or fresh non-array objects in "
                "the args."
            )

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        out = self._fn(*args, **kwargs)
        with self._lock:
            self.calls += 1
        self.check()
        return out

    def __repr__(self) -> str:  # pragma: no cover - debugging sugar
        with self._lock:
            calls = self.calls
        return (
            f"RecompileSentinel({self.name}, traces={self.trace_count()}/"
            f"{self.max_traces}, calls={calls})"
        )
