"""Epoch driver: train loop, eval loop, checkpoint tail (replaces the
``train()``/``test()``/``main()`` bodies the reference duplicates across
mnist.py and mnist_ddp.py; SURVEY.md §2a #5-#8).

One driver serves both CLIs — single-device is simply a 1-device mesh, the
exact analogue of the reference's "Not using distributed mode" degradation
(reference mnist_ddp.py:25-28).
"""

from __future__ import annotations

import contextlib
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from .data.loader import DataLoader
from .data.mnist import MNIST
from .models.net import init_params, init_variables
from .ops.schedule import step_lr
from .parallel.ddp import (
    TrainState,
    eval_variables,
    make_eval_step,
    make_train_state,
    make_train_step,
    replicate_params,
)
from .parallel.distributed import DistState
from .parallel.mesh import DATA_AXIS, make_mesh
from .utils.checkpoint import load_variables, model_state_dict, save_state_dict
from .utils.logging import test_summary_lines, train_log_line
from .utils.rng import root_key, split_streams


def _assert_digest_consistent(digest: bytes, path: str, what: str) -> None:
    """Multi-controller guard: allgather an 8-byte digest prefix across
    processes and refuse divergent per-host copies — replicate_params
    assumes local copies are identical by construction.  No-op in a
    single-process world."""
    if jax.process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    digests = multihost_utils.process_allgather(
        np.frombuffer(digest[:8], dtype=np.uint8)
    )
    if not bool(np.all(digests == digests[0])):
        raise ValueError(
            f"{what} {path!r} differs across processes (per-host copies "
            "are not identical); distribute one consistent file to every "
            "host before resuming"
        )


def _assert_checkpoint_consistent(path: str) -> None:
    """Cross-check a digest of a resume file's raw bytes over all
    processes (see _assert_digest_consistent)."""
    if jax.process_count() <= 1:
        return
    import hashlib

    with open(path, "rb") as f:
        digest = hashlib.sha256(f.read()).digest()
    _assert_digest_consistent(digest, path, "resume file")


def _load_resume_variables(path: str, syncbn: bool, init_key) -> tuple:
    """Load a ``--resume`` checkpoint and return ``(params, bn_stats,
    step0)`` shaped for the CURRENT model configuration.

    The reference checkpoint format stores only the model (SURVEY.md
    §3.5), so the optimizer restarts fresh — torch-faithful, since the
    reference has no resume at all.  ``step0`` seeds ``TrainState.step``
    from the checkpoint's ``num_batches_tracked`` (BN checkpoints only;
    0 otherwise), so a resumed-then-saved ``--syncbn`` checkpoint keeps
    torch's CUMULATIVE batch counter rather than restarting it.

    The checkpoint's architecture must match the requested one: resuming
    a BN-bearing checkpoint without ``--syncbn`` (or vice versa) fails
    fast here, before any device work, instead of as a missing-param
    apply error mid-run.  A BN checkpoint saved without running stats
    (params only) starts the running averages from BN's init values.

    Multi-controller worlds load the file independently on every process
    (``--save-model`` wrote it chief-only — a non-shared filesystem fails
    loudly with FileNotFoundError on the other hosts), and a digest of
    the raw tensors is cross-checked over all processes: differing local
    copies at PATH would otherwise assemble silently divergent replicas
    through ``replicate_params``'s identical-by-construction contract."""
    import hashlib

    from .utils.checkpoint import load_state_dict, variables_from_state_dict

    flat = load_state_dict(path)
    if jax.process_count() > 1:
        # Digest the PARSED tensors (not file bytes): .pt archives admit
        # byte-level differences (pickle protocol, zip metadata) that do
        # not change the tensors, and those must not fail the guard.
        digest = hashlib.sha256()
        for key in sorted(flat):
            digest.update(key.encode())
            digest.update(np.ascontiguousarray(flat[key]).tobytes())
        _assert_digest_consistent(
            digest.digest(), path, "--resume checkpoint"
        )
    variables = variables_from_state_dict(flat)
    params = variables["params"]
    has_bn = "bn1" in params
    if syncbn and not has_bn:
        raise ValueError(
            f"--resume checkpoint {path!r} has no BatchNorm parameters; "
            "drop --syncbn or resume a checkpoint saved by a --syncbn run"
        )
    if has_bn and not syncbn:
        raise ValueError(
            f"--resume checkpoint {path!r} carries BatchNorm parameters; "
            "add --syncbn (a mnist_ddp.py flag) to resume it"
        )
    step0 = 0
    for key, value in flat.items():
        if key.split(".")[-1] == "num_batches_tracked":
            step0 = max(step0, int(np.asarray(value).ravel()[0]))
    if not syncbn:
        return params, (), step0
    bn_stats = variables.get("batch_stats")
    if bn_stats is None:
        bn_stats = init_variables(init_key, use_bn=True)["batch_stats"]
    return params, bn_stats, step0


def train_one_epoch(
    step_fn,
    state: TrainState,
    loader: DataLoader,
    epoch: int,
    dropout_key: jax.Array,
    lr: float,
    dist: DistState,
    log_interval: int = 10,
    dry_run: bool = False,
    per_rank_batch: int | None = None,
    step_stats=None,
    telemetry=None,
    runtime=None,
    start_batch: int = 0,
) -> TrainState:
    """One training epoch (reference train(), mnist_ddp.py:65-86).

    Logging preserves the reference's exact semantics: chief-only, every
    ``log_interval`` batches, global sample counter
    ``world_size * batch_idx * per_rank_batch`` (mnist_ddp.py:78), and the
    logged loss is the FIRST replica's local loss — fetched from device
    only on log steps, so there is no per-step sync stall (SURVEY.md §3.2).

    ``telemetry`` (obs.Telemetry, --telemetry-dir) records per-step loss,
    step latency, and samples into the registry and the JSONL sink.  Like
    --step-stats, it blocks on each step's output to timestamp it — one
    device sync per step, the accepted trade for an opt-in diagnostic;
    the default path is untouched.

    ``runtime`` (resilience.ResilientRuntime, PR 9) routes each step
    through the guarded attempt (fault sites, LossGuard rollback,
    watchdog beat) and each step boundary through cadence checkpoints +
    preemption polling; ``start_batch`` resumes a mid-epoch archive at
    its exact batch cursor (batch numbering, log lines, and sampler
    position all continue as if never interrupted).  Both default to
    the flagless no-op.
    """
    lr_arr = jnp.float32(lr)
    num_batches = len(loader)
    if per_rank_batch is None:
        per_rank_batch = loader.global_batch // max(dist.world_size, 1)
    if step_stats is not None:
        step_stats.start()
    step_counter = sample_counter = latency_hist = None
    steps_recorded = samples_recorded = 0
    if telemetry is not None:
        step_counter = telemetry.registry.counter(
            "train_steps_total", help="optimizer steps executed"
        )
        sample_counter = telemetry.registry.counter(
            "train_samples_total", help="global training samples consumed"
        )
        latency_hist = telemetry.registry.histogram(
            "train_step_latency_seconds",
            help="host-observed per-step latency (blocking read)",
        )
        epoch_t0 = step_t0 = time.perf_counter()
    if runtime is not None:
        runtime.begin_train()
    try:
        for batch_idx, (x, y, w) in enumerate(
            loader.epoch(epoch, start_batch=start_batch), start=start_batch
        ):
            loss_host = None
            if runtime is not None:
                state, losses, loss_host = runtime.run_step(
                    step_fn, state, x, y, w, dropout_key, lr_arr,
                    epoch=epoch, batch_idx=batch_idx,
                )
            else:
                state, losses = step_fn(state, x, y, w, dropout_key, lr_arr)
            loss0 = None if loss_host is None else float(loss_host[0])
            if step_stats is not None:
                # The runtime's guarded read already synced this step;
                # a second block would double-count the sync cost.
                step_stats.mark(losses if loss_host is None else None)
            if telemetry is not None:
                if loss0 is None:
                    jax.block_until_ready(losses)
                now = time.perf_counter()
                if loss0 is None:
                    # The chief's own first local replica, same local-shard
                    # read (and same no-collective rationale) as the log
                    # path below.
                    loss0 = float(
                        np.asarray(losses.addressable_shards[0].data)[0]
                    )
                global_batch = per_rank_batch * (
                    dist.world_size if dist.distributed else 1
                )
                step_counter.inc()
                sample_counter.inc(global_batch)
                steps_recorded += 1
                samples_recorded += global_batch
                latency_hist.observe(now - step_t0)
                telemetry.events.emit(
                    "step",
                    epoch=epoch,
                    step=batch_idx,
                    loss=loss0,
                    latency_s=now - step_t0,
                    samples=global_batch,
                )
                step_t0 = time.perf_counter()
            if dist.is_chief and batch_idx % log_interval == 0:
                samples = dist.world_size * batch_idx * per_rank_batch
                if not dist.distributed:
                    samples = batch_idx * per_rank_batch
                # The chief's OWN first local replica — read from its local
                # shard, never via `losses[0]`: indexing a globally-sharded
                # array compiles a gather over the whole mesh, and a
                # chief-only collective deadlocks/corrupts multi-process runs
                # (every process must enqueue the same programs in order).
                # (Reused from the telemetry block when it already read it.)
                if loss0 is None:
                    loss0 = float(
                        np.asarray(losses.addressable_shards[0].data)[0]
                    )
                print(
                    train_log_line(
                        epoch,
                        samples,
                        loader.dataset_len,
                        batch_idx,
                        num_batches,
                        loss0,
                    )
                )
            if runtime is not None:
                # Step boundary: cadence checkpoint + preemption poll.
                # May raise SystemExit (emergency save already written).
                runtime.after_step(state, epoch=epoch, batch_idx=batch_idx)
            if dry_run:
                break
    finally:
        if runtime is not None:
            runtime.end_train()
    if telemetry is not None:
        duration = time.perf_counter() - epoch_t0
        sps = samples_recorded / duration if duration > 0 else 0.0
        telemetry.registry.gauge(
            "train_samples_per_second",
            help="throughput of the most recent epoch",
        ).set(sps)
        telemetry.events.emit(
            "epoch_train_end",
            epoch=epoch,
            steps=steps_recorded,
            samples=samples_recorded,
            duration_s=duration,
            samples_per_s=sps,
        )
    return state


def evaluate(
    eval_fn,
    params,
    loader: DataLoader,
    dist: DistState,
    telemetry=None,
) -> tuple[float, int]:
    """Distributed eval (reference test(), mnist_ddp.py:89-105): sums NLL
    and correct counts over the full test set, psum'd across the mesh, and
    prints the reference's summary on the chief.  Returns (avg_loss,
    correct).  With ``telemetry``, the pass runs inside an ``evaluate``
    span (duration event + span_duration_seconds histogram)."""
    eval_span = (
        telemetry.span("evaluate")
        if telemetry is not None
        else contextlib.nullcontext()
    )
    loss_sum = 0.0
    correct = 0.0
    with eval_span:
        for x, y, w in loader.epoch(0):
            # np.asarray on the fully-replicated psum output reads the
            # local copy — no traced indexing, safe on every process of a
            # multi-controller world.
            totals = np.asarray(eval_fn(params, x, y, w))
            loss_sum += float(totals[0])
            correct += float(totals[1])
    n = loader.dataset_len
    avg = loss_sum / n
    if dist.is_chief:
        print(test_summary_lines(avg, int(correct), n))
    return avg, int(correct)


def fit(
    args,
    dist: DistState,
    save_path: str | None = None,
    timings: dict | None = None,
) -> TrainState:
    """Full training run: data, model, optimizer, epoch loop, final save —
    the body of the reference's main() (mnist_ddp.py:108-197).

    Opt-in observability beyond the reference (SURVEY.md §5): ``--profile
    DIR`` wraps the run in a ``jax.profiler`` trace; ``--step-stats``
    prints per-epoch host-side step-latency summaries (per-batch path).
    When ``timings`` is a dict, the fused path records wall-clock
    attribution into it: ``data_s`` (device_put + sharding of the already-
    loaded dataset arrays), ``compile_s`` (trace + compile, or persistent-
    cache load, of the fused program), and ``run_s`` (execution of the
    compiled multi-epoch run through to host-materialized loss/eval
    outputs — D2H included, because through the remote-accelerator tunnel
    ``block_until_ready`` alone can return early) — the host-vs-device
    split bench.py reports.  Both paths also record
    ``epoch1_test_accuracy`` / ``final_test_accuracy`` (fractions), so the
    recorded benchmark carries the >=99% accuracy target of BASELINE.json
    alongside the wall clock.

    ``--telemetry-dir DIR`` (obs package, docs/OBSERVABILITY.md) opts the
    run into structured telemetry: JSONL step/epoch/eval events plus a
    Prometheus exposition (``metrics.prom``) written at end of run.  The
    run-duration event carries a correctly-labeled ``wall_seconds`` field
    — the stdout ``Total cost time:... ms`` line keeps its byte-matched
    label quirk, the telemetry surface does not inherit it.  Default
    (flagless) stdout is byte-identical to the reference either way."""
    from .utils.profiling import trace

    telemetry = None
    telemetry_dir = getattr(args, "telemetry_dir", None)
    if telemetry_dir:
        from .obs import Telemetry

        telemetry = Telemetry(
            telemetry_dir,
            rank=dist.process_rank,
            distributed=dist.distributed,
        )
        attempts = int(getattr(dist, "rendezvous_attempts", 0) or 0)
        if attempts:
            # The world-formation receipt (parallel/distributed.py
            # initialize_with_retry): how many bounded attempts this
            # process's rendezvous took.  >1 means a retry healed a
            # late peer — the rendezvous_retry events carry the trail.
            telemetry.registry.counter(
                "rendezvous_attempts_total",
                help="bounded jax.distributed.initialize attempts this "
                "process took to form the world",
            ).inc(attempts)
    t0 = time.perf_counter()
    try:
        with trace(getattr(args, "profile", None)):
            if telemetry is None:
                return _fit_body(args, dist, save_path, timings)
            with telemetry.span("run"):
                state = _fit_body(args, dist, save_path, timings, telemetry)
        telemetry.events.emit(
            "run_complete", wall_seconds=time.perf_counter() - t0
        )
        telemetry.write_exposition()
        return state
    finally:
        if telemetry is not None:
            telemetry.close()


def _fit_body(
    args,
    dist: DistState,
    save_path: str | None,
    timings: dict | None = None,
    telemetry=None,
) -> TrainState:
    # Model-axis modes (beyond reference parity): --tp N tensor-shards the
    # dense head over a (data, model) mesh; --pp pipelines the two stages
    # over the same axis.  Both ride the common per-batch epoch loop.
    tp_degree = int(getattr(args, "tp", 1) or 1)
    pp_on = bool(getattr(args, "pp", False))
    if tp_degree > 1 and pp_on:
        raise ValueError("--tp and --pp both claim the model axis; pick one")
    num_model = tp_degree if tp_degree > 1 else (2 if pp_on else 1)
    if num_model > 1 and bool(getattr(args, "fused", False)):
        raise ValueError("--fused is data-parallel only; drop it for --tp/--pp")
    if num_model > 1 and bool(getattr(args, "pallas_opt", False)):
        raise ValueError(
            "--pallas-opt is implemented for the DP paths; drop --tp/--pp"
        )
    if num_model > 1 and not dist.distributed:
        raise ValueError("--tp/--pp need a multi-device mesh (use the launcher)")
    # --syncbn (cross-replica BatchNorm, the torch.nn.SyncBatchNorm
    # equivalent) rides the DP paths, per-batch and fused.
    syncbn = bool(getattr(args, "syncbn", False))
    if syncbn and num_model > 1:
        raise ValueError("--syncbn rides the DP paths; drop --tp/--pp")
    # --zero (ZeRO-1: Adadelta state sharded over the data axis,
    # parallel/zero.py) rides the DP paths — per-batch AND the fused
    # whole-run (the epoch scan carries each shard's local accumulator
    # slice; parallel/fused.py).  Composes with --syncbn, --bf16, and
    # --pregather; excludes the model-axis modes and --pallas-opt (the
    # kernel's persistent layout is a different sharding of the same
    # state — one flat-layout owner per run).
    zero = bool(getattr(args, "zero", False))
    if zero and num_model > 1:
        raise ValueError("--zero rides the DP paths; drop --tp/--pp")
    if zero and bool(getattr(args, "pallas_opt", False)):
        raise ValueError("--zero and --pallas-opt both re-lay-out the "
                         "Adadelta state; pick one")
    # --conv-impl (models/net.py CONV_IMPLS): the GEMM-lowered conv
    # variants ride every DP path (per-batch and fused); the tp/pp raw-lax
    # forwards pin the native conv, so reject the combination loudly.
    conv_impl = str(getattr(args, "conv_impl", None) or "conv")
    if conv_impl != "conv" and num_model > 1:
        raise ValueError("--conv-impl rides the DP paths; drop --tp/--pp")
    # --pregather (the pre-permuted-epoch input path, parallel/fused.py)
    # exists only inside the fused whole-run; validated here so every
    # caller (both CLIs, bench.py) fails loudly instead of silently
    # running the per-step-gather path while claiming otherwise.
    if bool(getattr(args, "pregather", False)) and not bool(
        getattr(args, "fused", False)
    ):
        raise ValueError("--pregather is the fused input path; add --fused")
    # --serve-prewarm (the train-to-serve handoff, compile/program.py):
    # validated here so every caller fails loudly before any device work.
    if bool(getattr(args, "serve_prewarm", False)):
        if not getattr(args, "aot_cache", None):
            raise ValueError(
                "--serve-prewarm persists the serving predict grid as "
                "serialized AOT executables; add --aot-cache DIR"
            )
        if bool(getattr(args, "fused", False)):
            raise ValueError(
                "--serve-prewarm rides the per-batch step loop; drop --fused"
            )
        if num_model > 1:
            raise ValueError(
                "--serve-prewarm rides the DP paths; drop --tp/--pp"
            )
    # Full-state continuation (--save-state / --resume-state): the whole
    # TrainState travels, so the continued run is bit-identical to an
    # uninterrupted one (utils/checkpoint.save_train_state).
    resume_state_path = getattr(args, "resume_state", None)
    save_state_path = getattr(args, "save_state", None)
    if resume_state_path and getattr(args, "resume", None):
        raise ValueError(
            "--resume (model-only checkpoint) and --resume-state (full "
            "training state) are mutually exclusive"
        )
    if (resume_state_path or save_state_path) and num_model > 1:
        raise ValueError(
            "--save-state/--resume-state ride the DP paths; drop --tp/--pp"
        )
    # Resilient-runtime flags (resilience/, docs/ROBUSTNESS.md): validated
    # here so every caller fails loudly before any device work.  They ride
    # the per-batch DP paths — the fused whole-run is ONE device call with
    # no step boundary to checkpoint, guard, or time — and are
    # single-controller (a rollback/emergency-save decision taken from
    # per-host loss shards could diverge across processes).
    ckpt_every = int(getattr(args, "checkpoint_every_steps", 0) or 0)
    loss_guard_on = bool(getattr(args, "loss_guard", False))
    step_timeout_s = float(getattr(args, "step_timeout_s", 0) or 0.0)
    resilience_flags = ckpt_every > 0 or loss_guard_on or step_timeout_s > 0
    from .serving import faults as _faults

    if bool(getattr(args, "fused", False)) and (
        _faults.active_sites() & set(_faults.TRAINER_SITES)
    ):
        # An armed trainer-site clause can NEVER fire on the fused path
        # (one device call, no step/data_next/ckpt_save events); letting
        # the run proceed would be a vacuous green chaos run — exactly
        # what the grammar's parse-time guards exist to prevent.
        raise ValueError(
            "--chaos clauses at trainer sites (step/data_next/ckpt_save) "
            "need the per-batch step loop; drop --fused"
        )
    if resilience_flags:
        if bool(getattr(args, "fused", False)):
            raise ValueError(
                "--checkpoint-every-steps/--loss-guard/--step-timeout-s "
                "need the per-batch step loop; drop --fused"
            )
        if num_model > 1:
            raise ValueError(
                "the resilient runtime rides the DP paths; drop --tp/--pp"
            )
        if loss_guard_on and dist.process_count > 1:
            # Checkpointing and the watchdog are multi-rank coherent
            # (ISSUE 10): cadence decisions are deterministic and
            # identical per rank, writes are chief-gated, and a
            # watchdog abort is just a rank death the supervising
            # launcher gang-restarts.  The LossGuard is NOT: it
            # classifies per-host loss shards, so rank 0 could roll
            # back a step rank 1 committed — silent divergence.
            raise ValueError(
                "--loss-guard is single-controller (a rollback decision "
                "taken from per-host loss shards could diverge across "
                "ranks); drop it on multi-process runs"
            )
    if ckpt_every > 0 and not save_state_path:
        raise ValueError(
            "--checkpoint-every-steps writes mid-epoch archives to the "
            "--save-state path; add --save-state PATH"
        )
    epoch0 = 0
    loaded_state = None
    resume_extras: dict = {}
    # Elastic restart contract (parallel/elastic.py, ISSUE 10): a child
    # relaunched by the supervising gang launcher (ELASTIC_RESTART_COUNT
    # exported) — or any run opting in with --elastic — resumes from its
    # OWN --save-state archive when one exists, with --epochs read as
    # the TOTAL epoch target rather than "more epochs".  The launcher
    # re-executes the original command verbatim and needs zero knowledge
    # of the trainer's flag surface; this is where the resume happens.
    elastic_resumed = False
    elastic_on = bool(getattr(args, "elastic", False)) or int(
        os.environ.get("ELASTIC_RESTART_COUNT", "0") or 0
    ) > 0
    if elastic_on and save_state_path and not resume_state_path:
        from .utils.checkpoint import PREV_SUFFIX

        if os.path.exists(save_state_path) or os.path.exists(
            save_state_path + PREV_SUFFIX
        ):
            resume_state_path = save_state_path
            elastic_resumed = True
    if resume_state_path:
        from .ops.pallas_adadelta import ensure_opt_layout
        from .utils.checkpoint import load_latest_train_state

        # load_latest_train_state falls back to the rotated
        # <path>.prev ONLY when <path> is missing or torn (a trainer
        # killed inside the checkpoint rotation window) — a final
        # archive resumes through the identical code path as before.
        loaded_state, epoch0, resume_extras, resume_used_path = (
            load_latest_train_state(resume_state_path)
        )
        # Same silent-divergence hazard as --resume (see
        # _assert_checkpoint_consistent): per-host archive copies must be
        # identical before replicate_params trusts them.  Checked on the
        # RESOLVED path so a host that fell back to the rotation while
        # another did not fails loudly here.
        _assert_checkpoint_consistent(resume_used_path)
        # The archive's optimizer layout follows the SAVING run's backend/
        # flags; convert to what THIS run executes (a flat TPU archive
        # must not drag a CPU resume into interpret-mode kernels).
        loaded_state = loaded_state._replace(
            opt=ensure_opt_layout(
                loaded_state.opt, loaded_state.params,
                bool(getattr(args, "pallas_opt", False)),
            )
        )
        if bool(loaded_state.batch_stats) != syncbn:
            raise ValueError(
                f"--resume-state {resume_state_path!r} was saved "
                f"{'with' if loaded_state.batch_stats else 'without'} "
                "BatchNorm state; "
                + ("add" if loaded_state.batch_stats else "drop")
                + " --syncbn to match"
            )
        if elastic_resumed:
            # Epochs-as-total: a gang restart reruns the SAME command,
            # so "train 2 epochs" must mean "finish the 2-epoch run",
            # not "train 2 more" — the arithmetic tools/train_chaos.py
            # does by hand for explicit --resume-state.
            args.epochs = max(int(args.epochs) - epoch0, 0)

    if dist.distributed:
        # Multi-host: the mesh spans every device in the world (JAX's global
        # view); single-host: the (possibly --nproc_per_node-capped) locals.
        devs = jax.devices() if dist.process_count > 1 else dist.devices
        mesh = make_mesh(num_model=num_model, devices=devs)
    else:
        mesh = make_mesh(num_data=1, devices=dist.devices or jax.devices()[:1])
    n_shards = mesh.shape[DATA_AXIS]

    train_set = MNIST(root=getattr(args, "data_root", "./data"), train=True)
    test_set = MNIST(root=getattr(args, "data_root", "./data"), train=False)
    # Smoke-only truncation (bench.py --train-limit): the fused whole-run
    # program is O(dataset x epochs), and XLA:CPU's weak conv-in-scan code
    # makes the full 60k set impractical to drive end-to-end off-TPU; a
    # capped run exercises the identical program shape in seconds.  Never
    # part of a recorded headline (bench.py refuses to snapshot it).
    limit = int(getattr(args, "train_limit", 0) or 0)
    if limit:
        train_set.images = train_set.images[:limit]
        train_set.labels = train_set.labels[:limit]
        test_set.images = test_set.images[:limit]
        test_set.labels = test_set.labels[:limit]
    if timings is not None:
        timings["dataset"] = train_set.source
        # Actual sizes, so bench.py's throughput/MFU math follows any
        # truncation instead of assuming the 60k/10k protocol.
        timings["train_size"] = len(train_set)
        timings["test_size"] = len(test_set)

    keys = split_streams(root_key(args.seed))

    global_batch = args.batch_size * n_shards
    eval_batch = -(-args.test_batch_size // n_shards) * n_shards
    lr_fn = step_lr(args.lr, args.gamma, step_size=1)
    # Fused path: the ENTIRE multi-epoch run as one device call over an
    # HBM-resident dataset (parallel/fused.py:make_fused_run).  Identical
    # printed output, emitted after the run completes rather than live.
    # dry-run stays on the per-batch loop (it IS the per-batch smoke test).
    fused = bool(getattr(args, "fused", False)) and not args.dry_run
    # Mid-epoch archive (resilience/checkpoint.py meta.* extras): the
    # resumed run re-enters epoch epoch0+1 at the saved batch cursor and
    # consumes the exact remaining batches.  A final archive carries no
    # extras and keeps its historical resume semantics untouched.
    resume_cursor = 0
    resume_in_progress = int(resume_extras.get("epoch_in_progress", 0))
    if resume_in_progress:
        if fused:
            raise ValueError(
                f"--resume-state {resume_state_path!r} is a MID-EPOCH "
                "archive; finishing the epoch needs the per-batch step "
                "loop — drop --fused (the next end-of-run archive can "
                "resume fused again)"
            )
        if resume_in_progress != epoch0 + 1:
            raise ValueError(
                f"--resume-state {resume_state_path!r} is inconsistent: "
                f"epoch_in_progress={resume_in_progress} but "
                f"epochs_completed={epoch0}"
            )
        resume_cursor = int(resume_extras.get("batch_cursor", 0))
        saved_seed = resume_extras.get("seed")
        if saved_seed is not None and int(saved_seed) != int(args.seed):
            raise ValueError(
                f"--resume-state {resume_state_path!r} was saved mid-epoch "
                f"under --seed {int(saved_seed)}; resuming with --seed "
                f"{int(args.seed)} would replay a DIFFERENT permutation "
                "from the saved batch cursor — pass the original seed"
            )
        saved_gb = resume_extras.get("global_batch")
        if saved_gb is not None and int(saved_gb) != int(global_batch):
            raise ValueError(
                f"--resume-state {resume_state_path!r} was saved mid-epoch "
                f"at global batch {int(saved_gb)}; this run's "
                f"{int(global_batch)} re-chunks the epoch and the saved "
                "batch cursor no longer addresses the same samples — "
                "match --batch-size and the device count"
            )
        saved_ws = resume_extras.get("world_size")
        if saved_ws is not None and int(saved_ws) != int(n_shards):
            # The world fingerprint's last leg (ISSUE 10).  With the
            # same seed and global batch a different data-parallel
            # degree consumes the SAME global batches (each epoch batch
            # is the same slab of the global permutation whatever the
            # rank striping — parallel/sampler.py), so a re-shard is a
            # correct, sample-exact continuation; but the new striping
            # re-partitions each batch across devices, reductions
            # re-associate, and bit-exactness is gone — and silently
            # resuming into a different world is how a fat-fingered
            # launch flag corrupts a run.  Say it out loud.
            if not bool(getattr(args, "resume_reshard", False)):
                raise ValueError(
                    f"--resume-state {resume_state_path!r} was saved "
                    f"mid-epoch at world size {int(saved_ws)}; this run's "
                    f"world size is {int(n_shards)}.  Matching seed and "
                    "global batch make a re-shard consume the exact same "
                    "global batches (sampler contract; reductions "
                    "re-associate, so expect FP-level drift, not "
                    "bit-equality) — pass --resume-reshard to accept it, "
                    "or relaunch at the original world size"
                )
    use_pallas = bool(getattr(args, "pallas_opt", False))
    # --bf16: activations/matmuls at the MXU's native width; params, the
    # Adadelta state, and the log_softmax/NLL tail stay fp32 (models/net.py).
    # Rides every path — DP (per-batch and fused), ZeRO, TP (half-width
    # logits psum), and PP (half-width stage-boundary ppermute payloads).
    compute_dtype = jnp.bfloat16 if getattr(args, "bf16", False) else jnp.float32

    if fused:
        import time as _time

        from .compile import (
            CompileService,
            ExecutableStore,
            Program,
            StartupTasks,
            train_config,
        )
        from .parallel.fused import device_put_dataset, make_fused_run

        if (
            dist.is_chief
            and mesh.devices.flat[0].platform == "cpu"
            and len(train_set) > 10000
        ):
            # XLA:CPU emits poor code for convs inside the scan bodies the
            # fused path is built from (~25x the eager per-step cost at
            # benchmark shapes); the per-batch path has no such cliff.
            import sys as _sys

            print(
                "warning: --fused on the CPU backend is much slower than "
                "the per-batch path at this dataset size (XLA:CPU lowers "
                "convolutions inside scan bodies poorly); drop --fused",
                file=_sys.stderr,
            )

        resume_path = getattr(args, "resume", None)
        from_key = resume_path is None and loaded_state is None
        _t0 = _time.perf_counter()
        tr_x, tr_y = device_put_dataset(train_set.images, train_set.labels, mesh)
        te_x, te_y = device_put_dataset(test_set.images, test_set.labels, mesh)
        # device_put is async: the H2D transfer proceeds while the program
        # below is built (or AOT-deserialized) in the background — data_s
        # is the dispatch cost plus the transfer-tail rendezvous, most of
        # which hides under the concurrent compile.
        _data_dispatch = _time.perf_counter() - _t0
        # from_key: param init happens inside the compiled run — a cold
        # process reaches the hot loop in ONE device dispatch, with no
        # separate init program (same RNG stream as init_params, so the
        # result is bit-identical to the per-epoch path).  A --resume run
        # instead feeds the checkpoint's state in as the carry (the
        # from_key=False variant, whose leading argument is the state).
        run_fn, num_batches = make_fused_run(
            mesh, len(train_set), len(test_set), global_batch, eval_batch,
            args.epochs, compute_dtype=compute_dtype, use_pallas=use_pallas,
            from_key=from_key,
            use_bn=syncbn, start_epoch=epoch0 + 1,
            pregather=getattr(args, "pregather", False),
            conv_impl=conv_impl, zero=zero,
        )

        def _make_lead():
            """The program's leading argument: the init key (from_key) or
            the restored state.  Runs as a background startup task so the
            checkpoint's file IO + device placement overlap the compile
            job and the dataset H2D."""
            if loaded_state is not None:
                if zero:
                    # Archives are per-leaf (portable); convert to the flat
                    # sharded accumulator layout on placement.
                    from .parallel.zero import shard_zero_state

                    return shard_zero_state(loaded_state, mesh)
                return replicate_params(loaded_state, mesh)
            if resume_path is None:
                return keys["init"]
            if zero:
                from .parallel.zero import make_zero_train_state

                r_params, r_stats, r_step = _load_resume_variables(
                    resume_path, syncbn, keys["init"]
                )
                return make_zero_train_state(
                    r_params, mesh, r_stats, step0=r_step
                )
            r_params, r_stats, r_step = _load_resume_variables(
                resume_path, syncbn, keys["init"]
            )
            return replicate_params(
                make_train_state(
                    r_params, r_stats, use_pallas=use_pallas
                )._replace(step=jnp.int32(r_step)),
                mesh,
            )

        # Host-computed StepLR values: bit-identical to the per-epoch
        # paths; a continuation picks the schedule up at epoch0+1.
        lrs = jnp.asarray(
            [lr_fn(e) for e in range(epoch0 + 1, epoch0 + args.epochs + 1)],
            jnp.float32,
        )
        _registry = telemetry.registry if telemetry is not None else None
        _sink = telemetry.events if telemetry is not None else None
        aot_dir = getattr(args, "aot_cache", None)
        startup_span = (
            telemetry.span("startup")
            if telemetry is not None
            else contextlib.nullcontext()
        )
        # Startup overlap (docs/COMPILE.md): dataset H2D, program
        # build/load, and checkpoint restore proceed concurrently and
        # rendezvous here, before step 0.
        with startup_span, CompileService(registry=_registry, sink=_sink) as svc:
            tasks = StartupTasks(svc, registry=_registry, sink=_sink)
            tasks.add("restore", _make_lead)

            def _example_args():
                # A from_key run lowers against the (instantly available)
                # init key, so trace+compile never waits on anything; a
                # resume run rendezvous on the restored state first — its
                # shapes and optimizer layout parameterize the program.
                lead_in = keys["init"] if from_key else tasks.result("restore")
                return (
                    lead_in, tr_x, tr_y, te_x, te_y,
                    keys["shuffle"], keys["dropout"], lrs,
                )

            # The whole-run program as ONE Program artifact (compile/
            # program.py): jit fn + deferred example args + AOT key.
            # With --aot-cache a warm start deserializes the serialized
            # executable — zero tracing — behind a gate that falls back
            # to a fresh compile on any config/source/environment
            # mismatch; without it, build() is a plain lower+compile.
            # Dispatch below is Program.call, the executable fast path.
            store = (
                ExecutableStore(aot_dir, registry=_registry, sink=_sink)
                if aot_dir else None
            )
            program = Program(
                "fused_run",
                run_fn,
                example_args=_example_args,
                config=train_config(
                    mesh, "fused_run",
                    train_size=len(train_set),
                    test_size=len(test_set),
                    global_batch=global_batch,
                    eval_batch=eval_batch,
                    epochs=args.epochs,
                    compute_dtype=jnp.dtype(compute_dtype).name,
                    use_pallas=bool(use_pallas),
                    from_key=from_key,
                    use_bn=syncbn,
                    start_epoch=epoch0 + 1,
                    pregather=bool(getattr(args, "pregather", False)),
                    conv_impl=conv_impl,
                    zero=zero,
                ),
                store=store,
            )
            tasks.add("fused_run", program.build, kind="compile")
            # The H2D transfer tail as its own measured rendezvous leg.
            tasks.add(
                "data",
                lambda: jax.block_until_ready((tr_x, tr_y, te_x, te_y)),
            )
            lead = tasks.result("restore")
            aot_outcome = tasks.result("fused_run")
            overlap_ratio = tasks.rendezvous()
        run_args = (
            lead, tr_x, tr_y, te_x, te_y,
            keys["shuffle"], keys["dropout"], lrs,
        )
        if timings is not None:
            # Startup attribution: compile_s is the time to OBTAIN the
            # executable (trace+compile, or AOT/persistent-cache load);
            # data_s the dispatch plus the transfer-tail task.  The legs
            # ran concurrently, so their sum can exceed startup wall —
            # startup_overlap_ratio is the fraction the overlap hid.
            timings["compile_s"] = tasks.duration("fused_run") or 0.0
            timings["data_s"] = _data_dispatch + (tasks.duration("data") or 0.0)
            timings["startup_overlap_ratio"] = overlap_ratio
            if aot_outcome is not None:
                timings["aot_executable"] = aot_outcome
            _t1 = _time.perf_counter()
            state, losses, evals = program.call(*run_args)
            # Materialize the outputs on host INSIDE the timed window:
            # through the remote-accelerator tunnel, block_until_ready can
            # return while device work is still in flight, which would park
            # the whole run's device time in whichever later call first
            # touches the values (measured round 2: run_s ~0 with ~6 s
            # landing in the chief's print section).  A D2H read cannot
            # return early, so run_s is dispatch -> host-visible results.
            losses_np = np.asarray(losses)
            evals_np = np.asarray(evals)
            timings["run_s"] = _time.perf_counter() - _t1
            timings["epoch1_test_accuracy"] = float(evals_np[0, 1]) / len(test_set)
            timings["final_test_accuracy"] = float(evals_np[-1, 1]) / len(test_set)
        else:
            state, losses, evals = program.call(*run_args)
            losses_np = evals_np = None
        if dist.is_chief:
            # One transfer for the whole run, then the reference's exact
            # interleaved output — train lines + test summary per epoch.
            # (np.asarray reads replicated outputs locally; slicing happens
            # on host so no chief-only device program is enqueued.)
            losses_host = (np.asarray(losses) if losses_np is None else losses_np)[:, :, 0]
            evals_host = np.asarray(evals) if evals_np is None else evals_np
            for epoch in range(epoch0 + 1, epoch0 + args.epochs + 1):
                row = epoch - epoch0 - 1
                for batch_idx in range(0, num_batches, args.log_interval):
                    samples = dist.world_size * batch_idx * args.batch_size
                    if not dist.distributed:
                        samples = batch_idx * args.batch_size
                    print(
                        train_log_line(
                            epoch, samples, len(train_set), batch_idx,
                            num_batches, float(losses_host[row, batch_idx]),
                        )
                    )
                print(
                    test_summary_lines(
                        float(evals_host[row, 0]) / len(test_set),
                        int(evals_host[row, 1]),
                        len(test_set),
                    )
                )
            if telemetry is not None:
                # The fused run is ONE device call — there is no per-step
                # host boundary to time, so the telemetry records the
                # per-epoch curve from the host-materialized outputs
                # (chief-side, where they land anyway).
                telemetry.registry.counter(
                    "train_steps_total", help="optimizer steps executed"
                ).inc(num_batches * args.epochs)
                telemetry.registry.counter(
                    "train_samples_total",
                    help="global training samples consumed",
                ).inc(num_batches * args.epochs * global_batch)
                acc_gauge = telemetry.registry.gauge(
                    "test_accuracy", help="accuracy of the latest eval pass"
                )
                for epoch in range(epoch0 + 1, epoch0 + args.epochs + 1):
                    row = epoch - epoch0 - 1
                    acc = float(evals_host[row, 1]) / len(test_set)
                    acc_gauge.set(acc)
                    telemetry.events.emit(
                        "eval",
                        epoch=epoch,
                        avg_loss=float(evals_host[row, 0]) / len(test_set),
                        correct=int(evals_host[row, 1]),
                        accuracy=acc,
                    )
    else:
        resume_path = getattr(args, "resume", None)
        resume_step = 0
        if loaded_state is not None:
            params, bn_stats = None, None  # full state replaces init below
        elif resume_path is not None:
            params, bn_stats, resume_step = _load_resume_variables(
                resume_path, syncbn, keys["init"]
            )
        elif syncbn:
            variables = init_variables(keys["init"], use_bn=True)
            params = variables["params"]
            bn_stats = variables["batch_stats"]
        else:
            params = init_params(keys["init"])
            bn_stats = ()
        if tp_degree > 1:
            from .parallel.tp import make_tp_eval_step, make_tp_train_step, shard_state

            state = shard_state(make_train_state(params), mesh)
        elif zero:
            from .parallel.zero import make_zero_train_state, shard_zero_state

            if loaded_state is not None:
                # The archive's per-leaf accumulators (ensure_opt_layout
                # above) convert to the flat sharded layout on placement.
                state = shard_zero_state(loaded_state, mesh)
            else:
                state = make_zero_train_state(
                    params, mesh, bn_stats, step0=resume_step
                )
        elif loaded_state is not None:
            state = replicate_params(loaded_state, mesh)
        else:
            state = replicate_params(
                make_train_state(
                    params, bn_stats, use_pallas=use_pallas
                )._replace(step=jnp.int32(resume_step)),
                mesh,
            )
        # Steady-state input pipeline (data/prefetch.py): keep
        # --prefetch-depth placed batches in flight ahead of the step
        # loop (0 = synchronous serial baseline; batches bit-identical
        # either way — the A/B pin of docs/DATA.md).  With telemetry on,
        # the loaders record data_wait_seconds/prefetch_buffer_occupancy
        # and emit per-epoch prefetch_epoch events.
        prefetch_depth = int(getattr(args, "prefetch_depth", 2) or 0)
        obs_registry = telemetry.registry if telemetry is not None else None
        obs_sink = telemetry.events if telemetry is not None else None
        train_loader = DataLoader(
            train_set.images,
            train_set.labels,
            global_batch,
            mesh=mesh,
            shuffle=True,
            seed=args.seed,
            process_rank=dist.process_rank,
            process_count=dist.process_count,
            prefetch_depth=prefetch_depth,
            registry=obs_registry,
            sink=obs_sink,
            pipeline="train",
        )
        test_loader = DataLoader(
            test_set.images,
            test_set.labels,
            eval_batch,
            mesh=mesh,
            shuffle=False,
            process_rank=dist.process_rank,
            process_count=dist.process_count,
            # Count every test sample exactly once in the psum'd totals,
            # even when the sampler pads ranks to equal length (multi-host).
            mask_padding=True,
            prefetch_depth=prefetch_depth,
            registry=obs_registry,
            sink=obs_sink,
            pipeline="eval",
        )
        from .utils.profiling import StepStats

        if tp_degree > 1:
            step_fn = make_tp_train_step(mesh, compute_dtype=compute_dtype)
            eval_fn = make_tp_eval_step(mesh, compute_dtype=compute_dtype)
        elif pp_on:
            from .parallel.pp import make_pp_train_step

            step_fn = make_pp_train_step(
                mesh, num_micro=int(getattr(args, "pp_microbatches", 2)),
                compute_dtype=compute_dtype,
            )
            eval_fn = make_eval_step(mesh, compute_dtype=compute_dtype)
        elif zero:
            from .parallel.zero import make_zero_train_step

            # --zero and plain DP share one eval (constructed below):
            # params are replicated either way; only the train step and
            # the optimizer-state layout differ.
            step_fn = make_zero_train_step(
                mesh, compute_dtype=compute_dtype, use_bn=syncbn,
                conv_impl=conv_impl,
            )
            eval_fn = None
        else:
            step_fn = make_train_step(
                mesh, compute_dtype=compute_dtype, use_pallas=use_pallas,
                use_bn=syncbn, conv_impl=conv_impl,
            )
            eval_fn = None
        if eval_fn is None:
            eval_fn = make_eval_step(
                mesh, compute_dtype=compute_dtype, use_bn=syncbn,
                conv_impl=conv_impl,
            )
        # Unified Program artifact (compile/program.py, docs/COMPILE.md):
        # the DP-family train and eval steps become Programs built
        # CONCURRENTLY through the compile-service fan-out — the eval
        # program no longer compiles serially at the first eval pass —
        # and the step loop dispatches through Program.call, the bound
        # executable's C++ fast path (per-call host overhead pinned at
        # the direct-jit level in tests/test_program.py).  Shapes are
        # static by the loader's pad-to-batch contract, so ONE lowered
        # signature serves the whole run; numerics are the same
        # executable jit would have cached, so stdout and params stay
        # byte-identical (pinned).  With --aot-cache the programs
        # persist as serialized executables (warm trainer restart =
        # pure deserialize), and --serve-prewarm additionally builds
        # the serving engine's f32 predict grid through the SAME
        # canonical config composition — the train-to-serve handoff: a
        # serving engine warming the matching mesh/buckets from this
        # store starts with ZERO compiles (cross-surface reuse).
        # The model-axis modes (--tp/--pp) keep lazy jit dispatch.
        serve_prewarm = bool(getattr(args, "serve_prewarm", False))
        if tp_degree == 1 and not pp_on:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from .compile import (
                ExecutableStore,
                Program,
                build_programs,
                predict_store_size,
                serving_predict_programs,
                train_config,
            )
            from .models.net import INPUT_SHAPE

            aot_dir = getattr(args, "aot_cache", None)
            batch_sharding = NamedSharding(mesh, P(DATA_AXIS))

            def _batch_specs(batch: int) -> tuple:
                # The loader's static batch schema (data/loader.py: final
                # partial batches pad to shape, placement commits to the
                # data-axis sharding) — the one signature each program
                # ever sees.
                return (
                    jax.ShapeDtypeStruct(
                        (batch, *INPUT_SHAPE), jnp.float32,
                        sharding=batch_sharding,
                    ),
                    jax.ShapeDtypeStruct(
                        (batch,), jnp.int32, sharding=batch_sharding
                    ),
                    jax.ShapeDtypeStruct(
                        (batch,), jnp.float32, sharding=batch_sharding
                    ),
                )

            def _spec_of(tree):
                return jax.tree.map(
                    lambda a: jax.ShapeDtypeStruct(
                        np.shape(a), np.asarray(a).dtype
                        if not hasattr(a, "dtype") else a.dtype,
                        sharding=getattr(a, "sharding", None),
                    ),
                    tree,
                )

            handoff_buckets = []
            if serve_prewarm:
                from .serving.buckets import DEFAULT_MAX_BUCKET, pow2_buckets

                handoff_buckets = pow2_buckets(
                    n_shards, max(n_shards, min(DEFAULT_MAX_BUCKET, eval_batch))
                )
            store = None
            if aot_dir:
                store = ExecutableStore(
                    aot_dir,
                    registry=obs_registry,
                    sink=obs_sink,
                    # Train + eval entries plus the handoff grid, with
                    # the shared headroom formula — the default bound
                    # would prune the grid mid-prewarm.
                    max_entries=4 + predict_store_size(
                        1, 1, max(1, len(handoff_buckets))
                    ),
                )
            extras = dict(
                compute_dtype=jnp.dtype(compute_dtype).name,
                use_bn=syncbn,
                conv_impl=conv_impl,
                zero=zero,
            )
            step_program = Program(
                "train_step",
                step_fn,
                example_args=(
                    _spec_of(state), *_batch_specs(global_batch),
                    keys["dropout"], jnp.float32(0.0),
                ),
                config=train_config(
                    mesh, "train_step", global_batch=global_batch,
                    use_pallas=bool(use_pallas), **extras,
                ),
                store=store,
            )
            eval_program = Program(
                "eval_step",
                eval_fn,
                example_args=(
                    _spec_of(eval_variables(
                        state.params, state.batch_stats, syncbn
                    )),
                    *_batch_specs(eval_batch),
                ),
                config=train_config(
                    mesh, "eval_step", eval_batch=eval_batch, **extras
                ),
                store=store,
            )
            programs = [step_program, eval_program]
            if serve_prewarm:
                programs.extend(
                    serving_predict_programs(
                        mesh,
                        eval_variables(state.params, state.batch_stats, syncbn),
                        handoff_buckets,
                        store=store,
                        use_bn=syncbn,
                        conv_impl=conv_impl,
                    )
                )
            startup_span = (
                telemetry.span("startup")
                if telemetry is not None
                else contextlib.nullcontext()
            )
            with startup_span:
                build_programs(programs, registry=obs_registry, sink=obs_sink)
            step_fn = step_program.call
            eval_fn = eval_program.call
        want_stats = bool(getattr(args, "step_stats", False))
        # Resilient runtime (resilience/, docs/ROBUSTNESS.md): constructed
        # when a resilience flag is set OR a fault injector is installed
        # (the 'step' chaos site lives in runtime.run_step); the flagless
        # no-injector path never builds it and the step loop is untouched.
        runtime = None
        from .parallel.elastic import RankHeartbeat

        # ELASTIC_HEARTBEAT_FILE (set by the supervising launcher) opts
        # the step loop into liveness beats; unset — every flagless
        # run — builds nothing.
        heartbeat = RankHeartbeat.from_env()
        if resilience_flags or _faults.active() or heartbeat is not None:
            from .resilience import (
                LossGuard,
                MidEpochCheckpointer,
                PreemptionHandler,
                ResilientRuntime,
            )

            guard = (
                LossGuard(
                    spike_factor=float(getattr(args, "spike_factor", 10.0)),
                    retry_budget=int(getattr(args, "anomaly_budget", 3)),
                    lr_backoff=float(getattr(args, "anomaly_lr_backoff", 0.5)),
                )
                if loss_guard_on
                else None
            )
            checkpointer = (
                MidEpochCheckpointer(
                    save_state_path,
                    ckpt_every,
                    seed=int(args.seed),
                    global_batch=int(global_batch),
                    world_size=int(n_shards),
                    registry=obs_registry,
                    sink=obs_sink,
                )
                if ckpt_every > 0
                else None
            )
            preemption = (
                PreemptionHandler(
                    grace_s=float(getattr(args, "preempt_grace_s", 30.0))
                )
                if checkpointer is not None
                else None
            )

            def _host_state(s):
                # Archives are always per-leaf (same portability contract
                # as the end-of-run --save-state write below).
                if zero:
                    from .parallel.zero import zero_opt_to_per_leaf

                    s = s._replace(
                        opt=zero_opt_to_per_leaf(s.opt, s.params, mesh)
                    )
                return jax.device_get(s)

            runtime = ResilientRuntime(
                guard=guard,
                checkpointer=checkpointer,
                preemption=preemption,
                step_timeout_s=step_timeout_s,
                stall_abort=bool(getattr(args, "stall_abort", False)),
                prepare=_host_state,
                global_batch=int(global_batch),
                steps_total=int(resume_extras.get("steps_total", 0)),
                samples_total=int(resume_extras.get("samples_total", 0)),
                registry=obs_registry,
                sink=obs_sink,
                # Multi-rank coordination (ISSUE 10): every rank runs
                # the same deterministic cadence decisions and the
                # prepare collectives; only the chief writes (emergency
                # saves are best-effort chief-side — the signal lands
                # asynchronously; see ResilientRuntime.is_chief).
                is_chief=dist.is_chief,
                heartbeat=heartbeat,
            ).start()
        if telemetry is not None and resume_in_progress:
            # Seed the counters with the archive's totals so the resumed
            # run's exposition continues the killed run's numbers (the
            # replayed steps after the checkpoint count again on resume,
            # exactly as the uninterrupted run would have counted them).
            base_steps = int(resume_extras.get("steps_total", 0))
            base_samples = int(resume_extras.get("samples_total", 0))
            if base_steps:
                telemetry.registry.counter(
                    "train_steps_total", help="optimizer steps executed"
                ).inc(base_steps)
            if base_samples:
                telemetry.registry.counter(
                    "train_samples_total",
                    help="global training samples consumed",
                ).inc(base_samples)
            telemetry.events.emit(
                "train_resume",
                epoch=resume_in_progress,
                batch_cursor=resume_cursor,
                steps_total=base_steps,
                archive=resume_used_path,
            )
        try:
            for epoch in range(epoch0 + 1, epoch0 + args.epochs + 1):
                stats = StepStats() if want_stats else None
                epoch_span = (
                    telemetry.span("epoch", epoch=epoch)
                    if telemetry is not None
                    else contextlib.nullcontext()
                )
                with epoch_span:
                    state = train_one_epoch(
                        step_fn,
                        state,
                        train_loader,
                        epoch,
                        keys["dropout"],
                        lr_fn(epoch),
                        dist,
                        log_interval=args.log_interval,
                        dry_run=args.dry_run,
                        per_rank_batch=args.batch_size,
                        step_stats=stats,
                        telemetry=telemetry,
                        runtime=runtime,
                        # A mid-epoch archive re-enters ITS epoch at the
                        # saved cursor; every later epoch starts at 0.
                        start_batch=(
                            resume_cursor if epoch == epoch0 + 1 else 0
                        ),
                    )
                    if stats is not None and dist.is_chief:
                        print(stats.summary_line(epoch))
                    avg_loss, correct = evaluate(
                        eval_fn,
                        eval_variables(state.params, state.batch_stats, syncbn),
                        test_loader,
                        dist,
                        telemetry=telemetry,
                    )
                if telemetry is not None:
                    acc = correct / len(test_set)
                    telemetry.registry.gauge(
                        "test_accuracy", help="accuracy of the latest eval pass"
                    ).set(acc)
                    telemetry.events.emit(
                        "eval",
                        epoch=epoch,
                        avg_loss=avg_loss,
                        correct=correct,
                        accuracy=acc,
                    )
                if timings is not None:
                    acc = correct / len(test_set)
                    timings.setdefault("epoch1_test_accuracy", acc)
                    timings["final_test_accuracy"] = acc
                # scheduler.step() is implicit: lr_fn(epoch+1) next iteration.
        finally:
            if runtime is not None:
                runtime.stop()

    if getattr(args, "save_model", False) and save_path:
        params_for_save = state.params
        if tp_degree > 1:
            # Gather model-axis shards to a replicated copy.  Runs on EVERY
            # process (a chief-only collective would deadlock a
            # multi-controller world); only the file write is chief-gated.
            from .parallel.tp import gather_replicated

            params_for_save = gather_replicated(state.params, mesh)
        if dist.is_chief:
            # DDP-mode checkpoints carry the module. key prefix quirk
            # (reference mnist_ddp.py:195; SURVEY.md §3.5).
            sd = model_state_dict(
                jax.device_get(params_for_save),
                ddp_prefix=dist.distributed,
                batch_stats=(
                    jax.device_get(state.batch_stats) if syncbn else None
                ),
                num_batches=int(np.asarray(state.step)) if syncbn else None,
            )
            save_state_dict(sd, save_path)
    if save_state_path:
        from .utils.checkpoint import save_train_state

        state_for_save = state
        if zero:
            # Archives are always per-leaf (portable across --zero /
            # plain / --pallas-opt resumes); the gather runs on every
            # process, only the write below is chief-gated.
            from .parallel.zero import zero_opt_to_per_leaf

            state_for_save = state._replace(
                opt=zero_opt_to_per_leaf(state.opt, state.params, mesh)
            )
        if dist.is_chief:
            # Epochs completed = where the next continuation picks up the
            # schedule/shuffle/numbering.
            save_train_state(
                jax.device_get(state_for_save), save_state_path,
                epoch=epoch0 + args.epochs,
            )
    return state
