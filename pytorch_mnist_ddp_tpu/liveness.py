"""Process-liveness primitives shared by every supervisor in the repo.

Three subsystems supervise OS processes and previously each carried a
private copy of the same three mechanisms: the elastic gang launcher
(parallel/elastic.py, rank processes), the serving replica supervisor
(serving/pool.py, in-process replicas — backoff only), and now the
serving fleet control plane (serving/fleet.py, backend serving
processes).  This module is the one home for the shared machinery:

- **Heartbeat files** — a supervised process touches a file on its own
  work cadence (step boundary, dispatch-loop iteration); the supervisor
  reads mtime age.  A process that still answers ``poll()`` but stopped
  doing work (wedged collective, hung D2H, deadlocked dispatch loop) is
  detected by age, not just death.  A file that does not exist yet is
  STARTUP (rendezvous, warmup compile), never a hang — the age clock
  only runs once the first beat lands.
- **BackoffLadder** — the seeded exponential restart ladder every
  supervisor climbs: ``min(max, base * 2**attempts)`` with seeded
  jitter, so two chaos runs schedule identically (the determinism
  receipt docs/ROBUSTNESS.md promises).
- **signal_process_group** — deliver a signal to a child's whole
  process GROUP (supervised children run in their own sessions), with
  the fallbacks that make it safe for non-detached children and
  already-dead pids.

stdlib-only, no jax import: supervision must keep working exactly when
the thing it supervises is the part that is broken.
"""

from __future__ import annotations

import os
import random
import signal as _signal
import subprocess
import time


def heartbeat_path(directory: str, label: object) -> str:
    """The canonical heartbeat file for one supervised process."""
    return os.path.join(directory, f"{label}.hb")


def heartbeat_age_s(path: str, now_wall: float | None = None) -> float | None:
    """Seconds since the last beat, or None when the process has not
    written its first beat yet (startup — rendezvous / warmup compile —
    is covered by process liveness, not by heartbeat age)."""
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return None
    now_wall = time.time() if now_wall is None else now_wall
    return max(0.0, now_wall - mtime)


class Heartbeat:
    """Supervised-process-side writer: a throttled file touch.

    ``beat()`` is called on the process's own work cadence (every step
    boundary, every dispatch-loop iteration) but only touches the file
    once per ``interval_s`` — one ``os.utime`` per half second, never a
    per-call syscall storm.  The first beat creates the file, which is
    the supervisor's signal that startup is over and the age clock may
    run.
    """

    def __init__(self, path: str, interval_s: float = 0.5):
        self.path = path
        self.interval_s = float(interval_s)
        self._last = 0.0
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)

    def beat(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last < self.interval_s:
            return
        self._last = now
        with open(self.path, "a"):
            os.utime(self.path, None)

    @classmethod
    def from_env(cls, var: str) -> "Heartbeat | None":
        """The supervised side's constructor: the env var set by the
        launcher (or an operator) opts the work loop in; unset — the
        flagless path — builds nothing."""
        path = os.environ.get(var)
        return cls(path) if path else None


class BackoffLadder:
    """Seeded exponential backoff: the restart ladder every supervisor
    climbs.  ``delay_s(attempts)`` is rung ``attempts`` (0-based) —
    ``min(max, base * 2**attempts)`` times a seeded jitter factor in
    ``[1, 1 + jitter]``.  One RNG draw per call, so a replayed schedule
    is identical draw-for-draw (the chaos determinism contract)."""

    def __init__(
        self,
        base_s: float = 0.5,
        max_s: float = 30.0,
        jitter: float = 0.25,
        seed: int = 0,
    ):
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)

    def delay_s(self, attempts: int) -> float:
        backoff = min(self.max_s, self.base_s * (2 ** attempts))
        return backoff * (1.0 + self.jitter * self._rng.random())


def signal_process_group(proc: subprocess.Popen, signum: int) -> None:
    """Signal a child's whole process GROUP (supervised children run in
    their own sessions) — falling back to the single pid when the group
    is gone, or when the child SHARES the supervisor's group (a
    non-detached spawn: signalling that group would kill the supervisor
    itself)."""
    try:
        pgid = os.getpgid(proc.pid)
        if pgid == os.getpgrp():
            raise PermissionError("child shares the supervisor's group")
        os.killpg(pgid, signum)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            proc.send_signal(signum)
        except (ProcessLookupError, OSError):
            pass


def grace_stop(
    procs: list[subprocess.Popen], grace_s: float,
    term: int = _signal.SIGTERM, kill: int = _signal.SIGKILL,
) -> None:
    """SIGTERM every still-alive process (its emergency-save window),
    then SIGKILL whatever is left after ``grace_s`` — the bounded-grace
    contract shared by the gang launcher and the fleet control plane."""
    alive = [p for p in procs if p.poll() is None]
    for p in alive:
        signal_process_group(p, term)
    deadline = time.monotonic() + grace_s
    for p in alive:
        remaining = deadline - time.monotonic()
        try:
            p.wait(timeout=max(0.05, remaining))
        except subprocess.TimeoutExpired:
            signal_process_group(p, kill)
            p.wait()
