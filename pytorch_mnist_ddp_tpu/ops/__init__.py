from .adadelta import adadelta_init, adadelta_update, AdadeltaState
from .schedule import step_lr
from .loss import nll_loss
from .attention import full_attention
