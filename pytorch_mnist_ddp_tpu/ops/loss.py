"""Losses (replaces ``F.nll_loss``; SURVEY.md N9).

The reference computes ``F.nll_loss(log_probs, target)`` with mean
reduction in training (reference mnist_ddp.py:71) and sum reduction in eval
(mnist_ddp.py:97).  Because jit needs static shapes, partial final batches
are padded and carried with a 0/1 weight vector; the weighted forms below
reduce to the reference's exact numbers on unpadded data.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def nll_loss(
    log_probs: jax.Array,
    targets: jax.Array,
    weights: jax.Array | None = None,
    reduction: str = "mean",
) -> jax.Array:
    """Negative log likelihood from log-probabilities.

    ``weights`` (0/1 per sample) masks padding: 'mean' divides by the real
    sample count, 'sum' adds only real samples — matching torch on unpadded
    input.
    """
    per_sample = -jnp.take_along_axis(
        log_probs, targets[:, None].astype(jnp.int32), axis=1
    )[:, 0]
    if weights is not None:
        per_sample = per_sample * weights
        denom = jnp.maximum(weights.sum(), 1.0)
    else:
        denom = per_sample.shape[0]
    if reduction == "mean":
        return per_sample.sum() / denom
    if reduction == "sum":
        return per_sample.sum()
    if reduction == "none":
        return per_sample
    raise ValueError(f"unknown reduction {reduction!r}")
