"""Learning-rate schedules (replaces ``StepLR``; SURVEY.md N12).

The reference steps ``StepLR(optimizer, step_size=1, gamma=0.7)`` once per
epoch (reference mnist.py:126-130, mnist_ddp.py:178,189), i.e. the lr for
epoch e (1-based) is ``lr * gamma**((e-1)//step_size)``.  Here the schedule
is a pure function of the epoch index; the epoch driver feeds the resulting
scalar into the jitted train step as a traced argument (no recompilation
per epoch).
"""

from __future__ import annotations

from typing import Callable


def step_lr(base_lr: float, gamma: float = 0.7, step_size: int = 1) -> Callable[[int], float]:
    """Return ``epoch (1-based) -> lr`` with StepLR semantics: the lr decays
    by ``gamma`` after every ``step_size`` epochs (so epoch 1 uses
    ``base_lr``, matching torch where ``scheduler.step()`` runs at epoch
    end)."""

    def lr_for_epoch(epoch: int) -> float:
        return base_lr * gamma ** ((epoch - 1) // step_size)

    return lr_for_epoch
