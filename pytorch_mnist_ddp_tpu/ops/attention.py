"""Scaled-dot-product attention: dense reference + blockwise-online form.

The reference repo has no attention anywhere (SURVEY.md §5 "Long-context /
sequence parallelism: N/A" — its only model is the fixed 28x28 CNN,
reference mnist_ddp.py:46).  This module exists for the framework's
beyond-parity long-context story: the blockwise online-softmax update is
the building block `parallel/sp.py` rotates around the device ring
(ring attention), and the dense form is the numerics oracle the sharded
path is tested against.

Layouts: `q/k/v` are `[batch, tokens, heads, head_dim]` (token axis second
so sequence sharding splits dim 1); scores are computed in `[batch, heads,
q_tokens, k_tokens]`.  All softmax accumulation happens in float32
regardless of input dtype — on TPU the matmuls can run bf16 while the
running (max, normalizer, accumulator) triple stays exact enough to match
the dense oracle.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # additive mask value; finite so (masked - max) stays finite


class BlockAcc(NamedTuple):
    """Online-softmax running state for one query block.

    m: running row max            [batch, heads, q_tokens]
    l: running normalizer         [batch, heads, q_tokens]
    o: unnormalized output accum  [batch, heads, q_tokens, head_dim]
    """

    m: jax.Array
    l: jax.Array
    o: jax.Array


def init_block_acc(
    batch: int, heads: int, q_tokens: int, head_dim: int
) -> BlockAcc:
    return BlockAcc(
        m=jnp.full((batch, heads, q_tokens), NEG_INF, jnp.float32),
        l=jnp.zeros((batch, heads, q_tokens), jnp.float32),
        o=jnp.zeros((batch, heads, q_tokens, head_dim), jnp.float32),
    )


def block_update(
    acc: BlockAcc,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,
) -> BlockAcc:
    """Fold one (k, v) block into the online-softmax accumulator.

    The classic flash/blockwise recurrence: rescale the previous (l, o) by
    ``exp(m_old - m_new)`` and add this block's contribution.  Processing
    blocks in ANY order yields the same result as dense softmax, which is
    what lets ring attention start each device at a different ring offset.

    q:        [b, tq, h, d]   (the local, never-moving query block)
    k, v:     [b, tk, h, d]   (the visiting key/value block)
    kv_mask:  [b, tk] bool/0-1, False = padding token (excluded exactly)
    """
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    m_new = jnp.maximum(acc.m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    if kv_mask is not None:
        # exp(NEG_INF - m) underflows to 0 already, but make the exclusion
        # exact even when every score in the row is masked (m == NEG_INF).
        p = jnp.where(kv_mask[:, None, None, :], p, 0.0)
    corr = jnp.exp(acc.m - m_new)
    l_new = acc.l * corr + p.sum(axis=-1)
    o_new = acc.o * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32)
    )
    return BlockAcc(m=m_new, l=l_new, o=o_new)


def finalize_block_acc(acc: BlockAcc, dtype: jnp.dtype) -> jax.Array:
    """Normalize the accumulator into attention output `[b, tq, h, d]`.

    Rows whose every key was masked have l == 0; emit 0 for them (they are
    padding queries whose output is dropped downstream anyway) instead of
    0/0 NaN, which would poison grads through unselected branches.
    """
    l = acc.l[..., None]
    out = jnp.where(l > 0, acc.o / jnp.where(l > 0, l, 1.0), 0.0)
    return out.transpose(0, 2, 1, 3).astype(dtype)


def full_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,
) -> jax.Array:
    """Dense single-device attention — the numerics oracle.

    Written AS one block_update so the blockwise path and the oracle share
    every numerical decision (scale, f32 accumulation, mask semantics);
    tests then pin ring == full to tight tolerances.
    """
    b, _, h, d = q.shape
    acc = block_update(init_block_acc(b, h, q.shape[1], d), q, k, v, kv_mask)
    return finalize_block_acc(acc, q.dtype)
