"""Pallas TPU kernel: fused flash-attention forward + blockwise backward.

The framework's attention family (ops/attention.py) computes the
blockwise online-softmax in plain JAX — XLA materializes the
``[b, h, tq, tk]`` score tile of each block in HBM between kernels.  This
module fuses the whole per-(batch*head) attention into ONE Pallas pass:
scores, the running (max, normalizer) rescale, and the value matmul stay
in VMEM; HBM sees only q/k/v in and (output, logsumexp) out — the
flash-attention memory shape, O(t) instead of O(t^2).

The reference repo has no attention at all (SURVEY.md §5: its one model
is the fixed 28x28 CNN, reference mnist.py:11-34); like ops/attention.py
this exists for the beyond-parity long-context story, where it is the
single-device/per-shard building block — ring attention (parallel/sp.py)
rotates k/v blocks BETWEEN chips, this kernel fuses the math WITHIN one.

Design (mirrors the framework's other kernel, ops/pallas_adadelta.py):

- layout ``[b, t, h, d]`` (the family's convention) folds to
  ``[b*h, t, d]``; t pads to a block multiple, d pads to the 128-lane
  boundary — zero-padding is exact for d (zero columns contribute zero
  dot products) and masked via in-kernel iota comparison for t.
- grid ``(b*h, q_blocks, k_blocks)``, k innermost ("arbitrary" —
  sequential), carrying the online-softmax state in VMEM scratch:
  ``m``/``l`` as ``[bq, 128]`` lane-broadcast f32 (the TPU-native shape
  for per-row scalars), the output accumulator as ``[bq, dp]`` f32.
- the kernel also emits per-row ``logsumexp = m + log(l)`` (lane-
  broadcast, sliced to ``[..., 0]`` by the wrapper): the backward can
  then reconstruct each probability block EXACTLY — no second online
  pass — which is what makes the custom-VJP backward a simple
  ``lax.scan`` over k blocks in plain JAX (O(t) memory, XLA-fused), the
  standard flash backward split.
- the softmax stats (m, l, logsumexp) and the output accumulator are
  float32 regardless of input dtype; the probability block is rounded to
  v.dtype before the value matmul (standard flash practice — bf16 p·v
  feeds the MXU at native width).  For bf16 inputs the forward therefore
  differs from the dense oracle (which never rounds p) by that rounding,
  and the custom-VJP backward — which reconstructs p in f32 — computes
  the gradient of the UNROUNDED function; tests/test_flash.py's bf16
  tolerances (2e-2) absorb both.  f32 inputs match
  ops/attention.py:block_update exactly.

Non-TPU backends run the kernel in interpret mode for tests
(``TPU_MNIST_PALLAS_INTERPRET=1``); the CLI gate (``flash_active``)
falls back to the dense path rather than ever reaching interpret mode by
accident — the ops/pallas_adadelta.py dispatch idiom.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_INF
from ..utils.jax_compat import shape_dtype_struct, tpu_compiler_params, typeof

_LANES = 128
_MAX_BLOCK = 128  # q/k block rows; small t uses one sublane-aligned block


def flash_active(use_flash: bool | None) -> bool:
    """Would ``--flash`` actually run the kernel on this backend?  Real
    TPU lowering, or the explicit interpret-mode test hook — the
    ops/pallas_adadelta.py:pallas_opt_active gate, shared semantics."""
    return bool(use_flash) and (
        jax.default_backend() == "tpu"
        or os.environ.get("TPU_MNIST_PALLAS_INTERPRET") == "1"
    )


def _block(t: int) -> int:
    """Block rows for a t-token axis: full 128 rows when there is that
    much sequence, else one sublane-aligned block covering everything."""
    return _MAX_BLOCK if t >= _MAX_BLOCK else -(-t // 8) * 8


def flash_pad_len(t: int) -> int:
    """Token-axis length after padding to a whole number of kernel
    blocks — what callers that hold kernel-layout state across calls
    (the ring, parallel/sp.py) must pad to."""
    block = _block(t)
    return -(-t // block) * block


def flash_lane_pad(d: int) -> int:
    """Head-dim after padding to the kernel's lane boundary."""
    return -(-d // _LANES) * _LANES


def flash_fold_pad(x: jax.Array, t_pad: int) -> jax.Array:
    """Public fold+pad into the kernel's ``[b*h, t_pad, d_pad]`` layout —
    the ONE place the convention lives; external callers (the ring,
    parallel/sp.py) must not re-derive it."""
    return _pad_to(_pad_to(_fold(x), 1, t_pad), 2, flash_lane_pad(x.shape[-1]))


def _pad_to(x: jax.Array, axis: int, size: int) -> jax.Array:
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _out_struct(shape, dtype, *inputs) -> jax.ShapeDtypeStruct:
    """Output aval for a pallas_call that may run under a VMA-tracking
    ``shard_map`` (the sequence-parallel steps): the outputs vary on the
    union of the inputs' mesh axes.  Outside shard_map every vma is
    empty and this is a plain ShapeDtypeStruct."""
    vma = frozenset()
    for x in inputs:
        vma = vma | typeof(x).vma
    return shape_dtype_struct(shape, dtype, vma=vma)


def _fold_block(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                kb, block: int, t_kv: int, scale: float) -> None:
    """THE online-softmax fold, shared by both kernels (whole-forward and
    partial): score matmul, padded-key-column mask, running (m, l, acc)
    rescale-update — the numerically load-bearing body lives once.
    Scratch layout: lane-broadcast ``[bq, 128]`` m/l, ``[bq, dp]`` acc."""
    q = q_ref[0]  # [bq, dp]
    k = k_ref[0]  # [bk, dp]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [bq, bk] f32
    # Mask padded key columns (t padded up to a block multiple): their
    # zero-filled k rows would otherwise contribute exp(0 - m) mass.
    cols = kb * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(cols < t_kv, s, NEG_INF)

    m_prev = m_scr[:]  # [bq, 128] lane-broadcast
    row_max = jnp.max(s, axis=1, keepdims=True)  # [bq, 1]
    m_new = jnp.maximum(m_prev, jnp.broadcast_to(row_max, m_prev.shape))
    p = jnp.exp(s - m_new[:, :1])  # masked cols: exp(NEG_INF - m) == 0
    corr = jnp.exp(m_prev - m_new)  # [bq, 128], lanes identical
    l_scr[:] = l_scr[:] * corr + jnp.broadcast_to(
        jnp.sum(p, axis=1, keepdims=True), m_prev.shape
    )
    acc_scr[:] = acc_scr[:] * corr[:, :1] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_scr[:] = m_new


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, t_real: int, block: int, nk: int, scale: float):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    _fold_block(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                kb, block, t_real, scale)

    @pl.when(kb == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe = jnp.where(l > 0, l, 1.0)
        o_ref[0] = jnp.where(l > 0, acc_scr[:] / safe, 0.0).astype(o_ref.dtype)
        # logsumexp, lane-broadcast like the scratch stats themselves.
        lse_ref[0] = m_scr[:] + jnp.log(jnp.where(l_scr[:] > 0, l_scr[:], 1.0))


def _flash_fwd(q3, k3, v3, t_real: int, scale: float, interpret: bool):
    """Kernel driver over folded ``[BH, t_pad, d_pad]`` inputs; returns
    ``(out [BH, t_pad, d_pad], lse [BH, t_pad] f32)``.  ``scale`` is
    ``1/sqrt(real head_dim)`` — computed by the wrapper from the
    UNPADDED d, matching the dense oracle exactly."""
    bh, tp, dp = q3.shape
    block = _block(t_real)
    nq = tp // block
    nk = tp // block
    kern = functools.partial(
        _fwd_kernel, t_real=t_real, block=block, nk=nk, scale=scale
    )
    qo_spec = pl.BlockSpec(
        (1, block, dp), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM
    )
    kv_spec = pl.BlockSpec(
        (1, block, dp), lambda b, qi, ki: (b, ki, 0), memory_space=pltpu.VMEM
    )
    lse_spec = pl.BlockSpec(
        (1, block, _LANES), lambda b, qi, ki: (b, qi, 0),
        memory_space=pltpu.VMEM,
    )
    out, lse = pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[qo_spec, kv_spec, kv_spec],
        out_specs=[qo_spec, lse_spec],
        out_shape=[
            _out_struct((bh, tp, dp), q3.dtype, q3, k3, v3),
            _out_struct((bh, tp, _LANES), jnp.float32, q3, k3, v3),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, _LANES), jnp.float32),  # m
            pltpu.VMEM((block, _LANES), jnp.float32),  # l
            pltpu.VMEM((block, dp), jnp.float32),      # output accumulator
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q3, k3, v3)
    return out, lse[:, :, 0]


def _fold(x: jax.Array) -> jax.Array:
    """[b, t, h, d] -> [b*h, t, d]."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unfold(x3: jax.Array, b: int, h: int) -> jax.Array:
    """[b*h, t, d] -> [b, t, h, d]."""
    bh, t, d = x3.shape
    return x3.reshape(b, h, t, d).transpose(0, 2, 1, 3)




def _bwd_blockwise(q3, k3, v3, out3, lse, g3, t_real: int, scale: float):
    """Memory-efficient flash backward in plain JAX: one ``lax.scan`` over
    k blocks reconstructs each probability tile from the kernel's saved
    logsumexp (``p = exp(s - lse)`` — exact, no second online pass) and
    accumulates dq while emitting per-block dk/dv.  All math in f32, the
    dense oracle's contract; XLA fuses the scan body.

    Shapes: folded UNPADDED ``[BH, t, d]``; lse ``[BH, t]``.
    """
    bh, t, d = q3.shape
    block = _block(t)
    nk = -(-t // block)
    tp = nk * block
    kp = _pad_to(k3, 1, tp).reshape(bh, nk, block, d).transpose(1, 0, 2, 3)
    vp = _pad_to(v3, 1, tp).reshape(bh, nk, block, d).transpose(1, 0, 2, 3)
    qf = q3.astype(jnp.float32)
    gf = g3.astype(jnp.float32)
    # delta_i = sum_d dO_i * O_i — the rowwise correction of the softmax
    # jacobian (the standard flash backward identity).
    delta = jnp.sum(gf * out3.astype(jnp.float32), axis=-1)  # [BH, t]

    def body(dq_acc, inputs):
        kb_idx, kb, vb = inputs  # [], [BH, block, d], [BH, block, d]
        kf = kb.astype(jnp.float32)
        vf = vb.astype(jnp.float32)
        s = scale * jnp.einsum("bqd,bkd->bqk", qf, kf)
        cols = kb_idx * block + jnp.arange(block)[None, None, :]
        p = jnp.where(cols < t_real, jnp.exp(s - lse[..., None]), 0.0)
        dv_b = jnp.einsum("bqk,bqd->bkd", p, gf)
        dp_ = jnp.einsum("bqd,bkd->bqk", gf, vf)
        ds = p * (dp_ - delta[..., None])
        dq_acc = dq_acc + scale * jnp.einsum("bqk,bkd->bqd", ds, kf)
        dk_b = scale * jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq_acc, (dk_b, dv_b)

    dq, (dks, dvs) = jax.lax.scan(
        body, jnp.zeros_like(qf), (jnp.arange(nk), kp, vp)
    )
    dk = dks.transpose(1, 0, 2, 3).reshape(bh, tp, d)[:, :t]
    dv = dvs.transpose(1, 0, 2, 3).reshape(bh, tp, d)[:, :t]
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _flash_attention_core(
    q: jax.Array, k: jax.Array, v: jax.Array
) -> jax.Array:
    out, _ = _flash_fwd_res(q, k, v)
    return out


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_mask: jax.Array | None = None,
) -> jax.Array:
    """Fused flash-attention, signature-compatible with
    ``ops.attention.full_attention``.  ``q/k/v``: ``[b, t, h, d]``.

    MASKLESS: the kernel has no kv_mask plumbing (every current caller is
    an unpadded ViT path).  The argument exists so a masked caller
    arriving through ``select_attention`` fails loudly here instead of
    silently attending to padding — route masked inputs to the dense
    path."""
    if kv_mask is not None:
        raise ValueError(
            "flash_attention does not support kv_mask; use "
            "ops.attention.full_attention for masked inputs"
        )
    return _flash_attention_core(q, k, v)


def _dense_fwd_res(q, k, v, scale):
    """Pure-JAX twin of the whole-forward kernel, same (out, lse) contract
    — the off-TPU route when tracing under VMA tracking (a Ulysses
    shard_map), where the Pallas interpreter cannot run.  Numerics match
    ops/attention.py:full_attention's f32 contract."""
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32), k.astype(jnp.float32),
    ) * scale
    lse = jax.scipy.special.logsumexp(s, axis=-1)  # [b, h, t]
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    b, t, h, _ = q.shape
    return out.astype(q.dtype), lse.reshape(b * h, t)


def _flash_fwd_res(q, k, v):
    b, t, h, d = q.shape
    scale = 1.0 / float(d) ** 0.5
    interpret = jax.default_backend() != "tpu"
    if interpret and typeof(q).vma:
        # Under VMA-tracked shard_map the interpreter cannot trace the
        # kernel (see _flash_partial); same exact-twin dispatch.
        return _dense_fwd_res(q, k, v, scale)
    tp = flash_pad_len(t)
    out3, lse = _flash_fwd(
        flash_fold_pad(q, tp), flash_fold_pad(k, tp), flash_fold_pad(v, tp),
        t_real=t, scale=scale, interpret=interpret,
    )
    out = _unfold(out3[:, :t, :d], b, h)
    return out, lse[:, :t]


def _vjp_fwd(q, k, v):
    out, lse = _flash_fwd_res(q, k, v)
    return out, (q, k, v, out, lse)


def _vjp_bwd(res, g):
    q, k, v, out, lse = res
    b, t, h, d = q.shape
    scale = 1.0 / float(d) ** 0.5
    dq3, dk3, dv3 = _bwd_blockwise(
        _fold(q), _fold(k), _fold(v), _fold(out), lse, _fold(g),
        t_real=t, scale=scale,
    )
    cast = lambda x3, ref: _unfold(x3, b, h).astype(ref.dtype)
    return cast(dq3, q), cast(dk3, k), cast(dv3, v)


_flash_attention_core.defvjp(_vjp_fwd, _vjp_bwd)


def _partial_kernel(q_ref, k_ref, v_ref, m0_ref, l0_ref, a0_ref,
                    m_out, l_out, a_out, m_scr, l_scr, acc_scr,
                    *, t_kv: int, block: int, nk: int, scale: float):
    """The accumulator-in/accumulator-out variant of ``_fwd_kernel``: the
    online-softmax state enters as (m0, l0, a0) instead of the empty
    accumulator and leaves RAW (no normalization) — the fused building
    block ring attention folds once per hop (parallel/sp.py).  State
    layout is the kernel's own: lane-broadcast [tq, 128] m/l, [tq, dp]
    f32 accumulator."""
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _load():
        m_scr[:] = m0_ref[0]
        l_scr[:] = l0_ref[0]
        acc_scr[:] = a0_ref[0]

    _fold_block(q_ref, k_ref, v_ref, m_scr, l_scr, acc_scr,
                kb, block, t_kv, scale)

    @pl.when(kb == nk - 1)
    def _store():
        m_out[0] = m_scr[:]
        l_out[0] = l_scr[:]
        a_out[0] = acc_scr[:]


def _partial_ref(m, l, a, q3, k3, v3, t_kv: int, scale: float):
    """Pure-JAX twin of ``_partial_kernel`` on the SAME kernel-layout
    state — the recompute target for the custom-VJP backward (and the
    parity oracle in tests).  Math identical to
    ops/attention.py:block_update, re-expressed on lane-broadcast
    stats."""
    qf = q3.astype(jnp.float32)
    kf = k3.astype(jnp.float32)
    s = scale * jnp.einsum("bqd,bkd->bqk", qf, kf)
    cols = jnp.arange(s.shape[-1])[None, None, :]
    s = jnp.where(cols < t_kv, s, NEG_INF)
    row_max = jnp.max(s, axis=-1, keepdims=True)  # [BH, tq, 1]
    m_new = jnp.maximum(m, row_max)  # broadcast over the 128 lanes
    p = jnp.exp(s - m_new[..., :1])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    a_new = a * corr[..., :1] + jnp.einsum(
        "bqk,bkd->bqd", p, v3.astype(jnp.float32)
    )
    return m_new, l_new, a_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def flash_block_update(m, l, a, q3, k3, v3, t_kv: int, scale: float):
    """One fused ring-attention hop: fold the visiting (k3, v3) block into
    the kernel-layout accumulator.  ``q3/k3/v3``: padded folded
    ``[BH, t_pad, dp]``; state as ``_partial_kernel`` documents.  The
    backward recomputes through the pure-JAX twin (``_partial_ref``) —
    O(block) memory, no residual score tensors."""
    return _flash_partial(m, l, a, q3, k3, v3, t_kv, scale)


def _flash_partial(m, l, a, q3, k3, v3, t_kv, scale,
                   interpret: bool | None = None):
    """``interpret=None`` (the custom-VJP path): real kernel on TPU,
    the EXACT pure-JAX twin elsewhere — the Pallas interpreter cannot
    trace under the VMA tracking the sequence-parallel shard_maps rely
    on, and ``_partial_ref`` is the same math (pinned against the
    interpreted kernel in tests/test_flash.py, which forces
    ``interpret=True`` outside shard_map)."""
    if interpret is None:
        if jax.default_backend() != "tpu":
            return _partial_ref(m, l, a, q3, k3, v3, t_kv, scale)
        interpret = False
    bh, tqp, dp = q3.shape
    tkp = k3.shape[1]
    bq = _block(tqp)
    bk = _block(t_kv)
    assert tqp % bq == 0 and tkp % bk == 0, (tqp, bq, tkp, bk)
    nq = tqp // bq
    nk = tkp // bk
    kern = functools.partial(
        _partial_kernel, t_kv=t_kv, block=bk, nk=nk, scale=scale
    )
    q_spec = pl.BlockSpec(
        (1, bq, dp), lambda b, qi, ki: (b, qi, 0), memory_space=pltpu.VMEM
    )
    kv_spec = pl.BlockSpec(
        (1, bk, dp), lambda b, qi, ki: (b, ki, 0), memory_space=pltpu.VMEM
    )
    ml_spec = pl.BlockSpec(
        (1, bq, _LANES), lambda b, qi, ki: (b, qi, 0),
        memory_space=pltpu.VMEM,
    )
    return pl.pallas_call(
        kern,
        grid=(bh, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, ml_spec, ml_spec, q_spec],
        out_specs=[ml_spec, ml_spec, q_spec],
        out_shape=[
            _out_struct((bh, tqp, _LANES), jnp.float32, m, l, a, q3, k3, v3),
            _out_struct((bh, tqp, _LANES), jnp.float32, m, l, a, q3, k3, v3),
            _out_struct((bh, tqp, dp), jnp.float32, m, l, a, q3, k3, v3),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, dp), jnp.float32),
        ],
        # The state updates in place: (m0, l0, a0) buffers are dead after
        # the hop and become (m, l, a) out.
        input_output_aliases={3: 0, 4: 1, 5: 2},
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q3, k3, v3, m, l, a)


def _partial_vjp_fwd(m, l, a, q3, k3, v3, t_kv, scale):
    out = _flash_partial(m, l, a, q3, k3, v3, t_kv, scale)
    return out, (m, l, a, q3, k3, v3)


def _partial_vjp_bwd(t_kv, scale, res, cot):
    m, l, a, q3, k3, v3 = res
    _, vjp = jax.vjp(
        lambda m, l, a, q3, k3, v3: _partial_ref(
            m, l, a, q3, k3, v3, t_kv, scale
        ),
        m, l, a, q3, k3, v3,
    )
    return vjp(cot)


flash_block_update.defvjp(_partial_vjp_fwd, _partial_vjp_bwd)


def flash_ring_state(bh: int, tq_pad: int, dp: int):
    """Empty kernel-layout accumulator for a ring of
    ``flash_block_update`` hops."""
    return (
        jnp.full((bh, tq_pad, _LANES), NEG_INF, jnp.float32),
        jnp.zeros((bh, tq_pad, _LANES), jnp.float32),
        jnp.zeros((bh, tq_pad, dp), jnp.float32),
    )


def flash_ring_finalize(m, l, a, b: int, h: int, t: int, d: int, dtype):
    """Normalize kernel-layout state into attention output
    ``[b, t, h, d]`` — the finalize_block_acc counterpart (all-masked
    rows, l == 0, emit 0 not NaN)."""
    l1 = l[..., :1]
    out3 = jnp.where(l1 > 0, a / jnp.where(l1 > 0, l1, 1.0), 0.0)
    return _unfold(out3[:, :t, :d], b, h).astype(dtype)


def flash_active_or_warn(
    use_flash: bool | None, stacklevel: int = 2
) -> bool:
    """``flash_active`` plus the one shared off-TPU fallback warning —
    every CLI branch (single-device/--zero via :func:`attention_best`,
    the --sp ring) reports the inactive-kernel case through here.
    ``stacklevel`` counts from THIS function's caller (2); wrappers add
    their own frame so the warning lands on the user's line."""
    active = flash_active(use_flash)
    if use_flash and not active:
        import warnings

        warnings.warn(
            f"--flash requested on backend {jax.default_backend()!r}, "
            "which would run the kernel in slow interpret mode; using "
            "the dense attention path instead (set "
            "TPU_MNIST_PALLAS_INTERPRET=1 to force interpret mode for "
            "testing)",
            stacklevel=stacklevel,
        )
    return active


def select_attention(use_flash: bool):
    """``use_flash`` -> ``AttentionFn``, for an ALREADY-GATED flag (the
    caller ran ``flash_active``/``flash_active_or_warn``).  The one
    selection every flash-capable mode shares — CLI branches, the TP
    head-shard forward, the EP blocks."""
    from .attention import full_attention

    return flash_attention if use_flash else full_attention


def attention_best(use_flash: bool | None = None):
    """Gate + pick in one call: the Pallas kernel when ``--flash`` is
    active on a capable backend (warning otherwise), else the dense
    oracle.  Returns an ``AttentionFn`` — models/vit.py injects it
    through the family's shared sublayer."""
    return select_attention(flash_active_or_warn(use_flash, stacklevel=3))
