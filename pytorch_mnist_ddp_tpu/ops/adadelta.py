"""Adadelta with exact torch-update parity (replaces ``optim.Adadelta``;
SURVEY.md N11).

The reference constructs ``optim.Adadelta(params, lr=1.0)`` with defaults
``rho=0.9, eps=1e-6, weight_decay=0`` (reference mnist.py:124,
mnist_ddp.py:176).  torch's update, reproduced exactly (eps placement
*inside* both square roots):

    square_avg <- rho * square_avg + (1-rho) * g^2
    delta      <- sqrt(acc_delta + eps) / sqrt(square_avg + eps) * g
    acc_delta  <- rho * acc_delta + (1-rho) * delta^2
    p          <- p - lr * delta

State is two accumulators per parameter (``square_avg``, ``acc_delta``),
initialized to zeros like torch.  ``lr`` is a traced scalar so the
epoch-stepped StepLR schedule (``ops/schedule.py``) never retriggers
compilation.  Implemented as a pure pytree transform (jit/shard_map
friendly) rather than a stateful class; parity is pinned by
``tests/test_adadelta.py`` against ``torch.optim.Adadelta``.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class AdadeltaState(NamedTuple):
    square_avg: Any  # pytree like params
    acc_delta: Any   # pytree like params


def adadelta_init(params: Any) -> AdadeltaState:
    zeros = jax.tree.map(jnp.zeros_like, params)
    return AdadeltaState(square_avg=zeros, acc_delta=jax.tree.map(jnp.zeros_like, params))


def adadelta_delta(g, sq, ac, rho: float, eps: float):
    """The core recurrence on one (grad, square_avg, acc_delta) triple:
    returns ``(delta, new_square_avg, new_acc_delta)`` where the caller
    applies ``p - lr * delta`` (torch accumulates delta WITHOUT lr).
    The ONE definition of the update math — shared by the per-leaf pytree
    path below and the ZeRO-1 flat-shard path (parallel/zero.py), so the
    recurrence cannot drift between optimizer-state layouts.  Any
    weight-decay gradient adjustment happens before this."""
    sq = rho * sq + (1.0 - rho) * g * g
    delta = jnp.sqrt(ac + eps) / jnp.sqrt(sq + eps) * g
    ac = rho * ac + (1.0 - rho) * delta * delta
    return delta, sq, ac


def adadelta_update(
    params: Any,
    grads: Any,
    state: AdadeltaState,
    lr: jax.Array | float,
    rho: float = 0.9,
    eps: float = 1e-6,
    weight_decay: float = 0.0,
) -> tuple[Any, AdadeltaState]:
    """One Adadelta step over a whole parameter pytree."""

    def leaf(p, g, sq, ac):
        if weight_decay:
            g = g + weight_decay * p
        delta, sq, ac = adadelta_delta(g, sq, ac, rho, eps)
        return p - lr * delta, sq, ac

    flat = jax.tree.map(leaf, params, grads, state.square_avg, state.acc_delta)
    new_params = jax.tree.map(lambda t: t[0], flat, is_leaf=lambda t: isinstance(t, tuple))
    new_sq = jax.tree.map(lambda t: t[1], flat, is_leaf=lambda t: isinstance(t, tuple))
    new_ac = jax.tree.map(lambda t: t[2], flat, is_leaf=lambda t: isinstance(t, tuple))
    return new_params, AdadeltaState(new_sq, new_ac)
