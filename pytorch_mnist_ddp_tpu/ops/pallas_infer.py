"""Pallas TPU kernel: the int8 dense head as ONE fused pass.

models/quant.py's serving forward spends ~99% of its FLOPs in two dense
GEMMs (fc1 9216->128, fc2 128->10), and the reference path round-trips
through f32 between them: quantize activations, int8 GEMM, rescale to
f32, bias, relu, then do it all again — each stage its own XLA op with
an HBM-resident intermediate.  This kernel fuses the whole head

    q1   <- clip(round(x / a_scale1), -127, 127)        per-row scale
    h    <- relu(int32(q1 @ W1_q) * (a_scale1 * s1) + b1)
    q2   <- clip(round(h / a_scale2), -127, 127)        per-row scale
    y    <- int32(q2 @ W2_q) * (a_scale2 * s2) + b2

into one VMEM-resident pass: activations never leave the core between
fc1 and fc2, and the rank-1 rescales + bias + relu ride the MXU
epilogue.  The arithmetic is OP-FOR-OP the reference
``models/quant.py:_int8_dense`` (same jnp calls in the same order): the
integer quantize/GEMM stages are exact, and the f32 rescale tail agrees
to within compiler mul+add fusion (~1 ulp) — far inside the engine's
parity gate (logit tolerance + argmax-identical), which covers the
kernel with the same budget as the reference int8 variant.

fc2's 10 output channels pad to the 128-lane tile with zero weights,
unit scales, and zero biases — padded lanes compute exactly 0 and are
sliced off on the way out, so the log_softmax tail (outside the kernel,
f32, unchanged) sees the true ``[n, 10]`` logits.

On non-TPU backends the kernel runs in Pallas interpret mode, which
keeps CPU tests meaningful (gate: TPU_MNIST_PALLAS_INTERPRET=1, same
contract as ops/pallas_adadelta.py).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_QMAX = 127.0  # symmetric int8, mirrors models/quant.py
_BLOCK_ROWS = 128  # 128x9216 f32 x block + int8 copy + W1 ~ 7 MiB VMEM


def pallas_infer_active(use_pallas: bool | None) -> bool:
    """Would ``--int8-impl pallas`` actually run the kernel here?

    Same gate as ``ops.pallas_adadelta.pallas_opt_active``: a real TPU
    lowering, or the explicit interpret-mode test hook.  The serving
    engine uses it to resolve the requested impl BEFORE composing AOT
    config keys, so the persisted key always names the impl that ran.
    """
    return bool(use_pallas) and (
        jax.default_backend() == "tpu"
        or os.environ.get("TPU_MNIST_PALLAS_INTERPRET") == "1"
    )


def _head_kernel(x_ref, w1_ref, s1_ref, b1_ref, w2_ref, s2_ref, b2_ref, out):
    def dense(x, w_ref, s_ref, b_ref):
        # Op-for-op models/quant.py:_int8_dense — exact integer core,
        # f32 tail within fusion jitter of the reference path.
        a_max = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        a_scale = jnp.where(a_max > 0, a_max / _QMAX, 1.0)
        x_q = jnp.clip(jnp.round(x / a_scale), -_QMAX, _QMAX).astype(jnp.int8)
        acc = jax.lax.dot_general(
            x_q, w_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return acc.astype(jnp.float32) * (a_scale * s_ref[:]) + b_ref[:]

    h = jnp.maximum(dense(x_ref[:], w1_ref, s1_ref, b1_ref), 0.0)
    out[:] = dense(h, w2_ref, s2_ref, b2_ref)


def _pad_axis(v: jax.Array, axis: int, to: int, value: float) -> jax.Array:
    pad = to - v.shape[axis]
    if pad == 0:
        return v
    widths = [(0, 0)] * v.ndim
    widths[axis] = (0, pad)
    return jnp.pad(v, widths, constant_values=value)


def fused_int8_head(
    fc1: dict, fc2: dict, x: jax.Array, interpret: bool | None = None
) -> jax.Array:
    """``relu(int8_dense(x, fc1))`` then ``int8_dense(., fc2)`` fused.

    ``fc1``/``fc2`` are ``quantize_params`` layer dicts (``kernel_q``
    int8 ``[in, out]``, ``scale`` f32 ``[out]``, ``bias`` f32 ``[out]``);
    ``x`` is the f32 ``[n, 9216]`` flattened conv stack output.  Returns
    f32 ``[n, out2]`` pre-softmax logits.  Rows pad to the f32 sublane
    tile (and tile in ``_BLOCK_ROWS`` chunks past 128) — zero rows are
    self-contained under per-row quantization, so padding never touches
    real rows.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d_in = x.shape
    d_mid = fc1["kernel_q"].shape[1]
    d_out = fc2["kernel_q"].shape[1]
    if d_mid % _LANES:
        raise ValueError(f"fc1 output width {d_mid} is not lane-aligned")

    rows = -(-n // 8) * 8 if n <= _BLOCK_ROWS else -(-n // _BLOCK_ROWS) * _BLOCK_ROWS
    block_rows = min(rows, _BLOCK_ROWS)
    x2 = _pad_axis(x.astype(jnp.float32), 0, rows, 0.0)

    # fc2's narrow output pads to one lane tile: zero weights keep the
    # int32 accumulator at 0, unit scales keep the rescale finite, zero
    # biases keep the padded lanes exactly 0.
    w2 = _pad_axis(fc2["kernel_q"], 1, _LANES, 0)
    s2 = _pad_axis(fc2["scale"], 0, _LANES, 1.0)
    b2 = _pad_axis(fc2["bias"], 0, _LANES, 0.0)

    row2d = lambda v: v.reshape(1, -1).astype(jnp.float32)
    fixed = lambda shape: pl.BlockSpec(
        shape, lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    y = pl.pallas_call(
        _head_kernel,
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec(
                (block_rows, d_in), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            fixed((d_in, d_mid)),
            fixed((1, d_mid)),
            fixed((1, d_mid)),
            fixed((d_mid, _LANES)),
            fixed((1, _LANES)),
            fixed((1, _LANES)),
        ],
        out_specs=pl.BlockSpec(
            (block_rows, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        interpret=interpret,
    )(
        x2,
        fc1["kernel_q"],
        row2d(fc1["scale"]),
        row2d(fc1["bias"]),
        w2,
        row2d(s2),
        row2d(b2),
    )
    return y[:n, :d_out]
