"""Pallas TPU kernel: the whole Adadelta update as ONE fused pass.

The optimizer update is the framework's only elementwise-heavy stage that
XLA cannot fold into a matmul (it sits between the gradient ``pmean`` and
the next step's forward).  Per parameter it reads 4 HBM buffers
(param, grad, square_avg, acc_delta) and writes 3; as separate XLA ops
that is several kernel launches and intermediate materializations.  This
kernel does the full torch-parity update (ops/adadelta.py docstring;
reference ``optim.Adadelta`` semantics, SURVEY.md N11):

    square_avg <- rho * square_avg + (1-rho) * g^2
    delta      <- sqrt(acc_delta + eps) / sqrt(square_avg + eps) * g
    acc_delta  <- rho * acc_delta + (1-rho) * delta^2
    p          <- p - lr * delta

in one VMEM-resident pass over a [rows, 128] lane-aligned flat buffer, so
one grid covers all ~1.2M parameters instead of one tiny dispatch per
leaf — the TPU-idiomatic "fused optimizer" shape.

Two generations of the kernel live here:

- **ravel-per-step** (round 2): ``adadelta_update_pallas`` flattens
  params+grads+both accumulators around every call.  Measured on v5e,
  those concats cost ~0.3 ms/step more than the fusion saves at this
  model's size — which is why ``adadelta_update_best`` defaults to the
  plain per-leaf XLA update.
- **persistent-flat** (round 3, verdict item 7): ``adadelta_init_flat``
  keeps the accumulators in the kernel's padded layout ACROSS steps, and
  ``_make_delta_kernel`` emits the raw delta so parameters never ravel
  either — per step only the (about-to-be-dead) grads concat in and the
  delta splits out, where ``p - lr*delta`` fuses into the split.  lr
  never enters the kernel (torch accumulates delta without it), dropping
  the SMEM scalar too.  ``tools/pallas_opt_bench.py`` times all three
  paths head-to-head on hardware; the dispatch default follows the
  measurement.

On non-TPU backends the kernels run in Pallas interpret mode, which keeps
CPU tests meaningful (gate: TPU_MNIST_PALLAS_INTERPRET=1).
"""

from __future__ import annotations

import os
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.flatten_util import ravel_pytree

from .adadelta import AdadeltaState, adadelta_update

_LANES = 128
_BLOCK_ROWS = 256  # 256x128 f32 = 128 KiB per buffer; 7 buffers < 1 MiB VMEM


def pallas_opt_active(use_pallas: bool | None) -> bool:
    """Would ``--pallas-opt`` actually run the kernel on this backend?

    The same gate ``adadelta_update_best`` applies (real TPU lowering, or
    the explicit interpret-mode test hook) — state-init sites use it to
    decide between the padded-flat accumulator layout the kernel wants and
    the plain per-leaf pytree, so the two can never disagree."""
    return bool(use_pallas) and (
        jax.default_backend() == "tpu"
        or os.environ.get("TPU_MNIST_PALLAS_INTERPRET") == "1"
    )


def _make_kernel(rho: float, eps: float):
    def kernel(lr_ref, p_ref, g_ref, sq_ref, ac_ref, p_out, sq_out, ac_out):
        g = g_ref[:]
        sq = rho * sq_ref[:] + (1.0 - rho) * g * g
        delta = jnp.sqrt(ac_ref[:] + eps) / jnp.sqrt(sq + eps) * g
        ac = rho * ac_ref[:] + (1.0 - rho) * delta * delta
        p_out[:] = p_ref[:] - lr_ref[0, 0] * delta
        sq_out[:] = sq
        ac_out[:] = ac

    return kernel


def _pad_rows(n: int) -> tuple[int, int]:
    """Rows after lane packing and the block height: small tensors use one
    sublane-aligned block, large ones tile in _BLOCK_ROWS chunks."""
    rows = -(-n // _LANES)
    if rows <= _BLOCK_ROWS:
        rows = -(-rows // 8) * 8  # f32 min tile is (8, 128)
        return rows, rows
    return -(-rows // _BLOCK_ROWS) * _BLOCK_ROWS, _BLOCK_ROWS


def fused_adadelta_flat(
    flat_p: jax.Array,
    flat_g: jax.Array,
    flat_sq: jax.Array,
    flat_ac: jax.Array,
    lr: jax.Array | float,
    rho: float = 0.9,
    eps: float = 1e-6,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused update over 1-D f32 vectors; returns (p, square_avg, acc_delta)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = flat_p.shape[0]
    rows, block_rows = _pad_rows(n)
    pad = rows * _LANES - n

    def shape2d(v):
        return jnp.pad(v, (0, pad)).reshape(rows, _LANES)

    lr2d = jnp.full((1, 1), lr, jnp.float32)
    grid = (rows // block_rows,)
    vec_spec = pl.BlockSpec(
        (block_rows, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    out_shape = jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)
    p2, sq2, ac2 = pl.pallas_call(
        _make_kernel(rho, eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            vec_spec,
            vec_spec,
            vec_spec,
            vec_spec,
        ],
        out_specs=[vec_spec, vec_spec, vec_spec],
        out_shape=[out_shape, out_shape, out_shape],
        # In-place: params/square_avg/acc_delta update their own buffers.
        input_output_aliases={1: 0, 3: 1, 4: 2},
        interpret=interpret,
    )(lr2d, shape2d(flat_p), shape2d(flat_g), shape2d(flat_sq), shape2d(flat_ac))
    unpad = lambda v: v.reshape(-1)[:n]
    return unpad(p2), unpad(sq2), unpad(ac2)


def _make_delta_kernel(rho: float, eps: float):
    """Variant that emits the raw ``delta`` instead of applying it: the
    caller folds ``p - lr*delta`` into each leaf, so parameters never pass
    through a ravel.  (``acc_delta`` accumulates delta WITHOUT lr — torch
    semantics, ops/adadelta.py — so lr never enters this kernel at all.)"""

    def kernel(g_ref, sq_ref, ac_ref, delta_out, sq_out, ac_out):
        g = g_ref[:]
        sq = rho * sq_ref[:] + (1.0 - rho) * g * g
        delta = jnp.sqrt(ac_ref[:] + eps) / jnp.sqrt(sq + eps) * g
        ac_out[:] = rho * ac_ref[:] + (1.0 - rho) * delta * delta
        delta_out[:] = delta
        sq_out[:] = sq

    return kernel


def _param_count(params: Any) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(params))


class FlatAdadeltaState(NamedTuple):
    """Adadelta accumulators in the kernel's persistent padded layout
    (two ``[rows, 128]`` f32 buffers).  A DISTINCT type, not a shape
    convention: dispatch keys on ``isinstance`` so a plain
    :class:`AdadeltaState` whose pytree happens to hold a bare 2-D array
    can never be misrouted into the kernel path."""

    square_avg: jax.Array
    acc_delta: jax.Array


def adadelta_init_flat(params: Any) -> FlatAdadeltaState:
    """Adadelta accumulators in the kernel's persistent layout: one padded
    lane-aligned ``[rows, 128]`` f32 buffer per accumulator, kept in that
    shape across every step (round-2 verdict item 7).  The old layout
    raveled+unraveled sq/ac around EVERY kernel call; this one touches
    pytree form never — the accumulators are kernel-internal state."""
    rows, _ = _pad_rows(_param_count(params))
    # Two DISTINCT buffers: the train step donates the whole state, and
    # sharing one zeros array here is a double-donation runtime error.
    return FlatAdadeltaState(
        square_avg=jnp.zeros((rows, _LANES), jnp.float32),
        acc_delta=jnp.zeros((rows, _LANES), jnp.float32),
    )


def is_flat_state(state: Any) -> bool:
    """True iff ``state`` is the kernel's :class:`FlatAdadeltaState`."""
    return isinstance(state, FlatAdadeltaState)


def ensure_opt_layout(opt: Any, params: Any, use_pallas: bool | None):
    """Convert Adadelta accumulators between the per-leaf pytree and the
    kernel's padded-flat layout to match what THIS run will execute
    (``pallas_opt_active``).  The layouts hold the same values — a
    ``--resume-state`` archive saved under one backend/flag combination
    must not commit a different backend to the saver's layout (e.g. a
    flat archive from a TPU ``--pallas-opt`` run silently dragging a CPU
    resume into interpret-mode kernels)."""
    want_flat = pallas_opt_active(use_pallas)
    if is_flat_state(opt) == want_flat:
        return opt
    flat_p, unravel = ravel_pytree(params)
    n = flat_p.shape[0]
    if want_flat:
        rows, _ = _pad_rows(n)

        def to2d(tree):
            v, _ = ravel_pytree(tree)
            return jnp.pad(v, (0, rows * _LANES - n)).reshape(rows, _LANES)

        return FlatAdadeltaState(
            square_avg=to2d(opt.square_avg), acc_delta=to2d(opt.acc_delta)
        )
    return AdadeltaState(
        square_avg=unravel(jnp.asarray(opt.square_avg).reshape(-1)[:n]),
        acc_delta=unravel(jnp.asarray(opt.acc_delta).reshape(-1)[:n]),
    )


def adadelta_update_flat(
    params: Any,
    grads: Any,
    state: FlatAdadeltaState,
    lr: jax.Array | float,
    rho: float = 0.9,
    eps: float = 1e-6,
    interpret: bool | None = None,
) -> tuple[Any, FlatAdadeltaState]:
    """Fused update over persistent padded-flat accumulators.

    Per step this moves only what it must: one ravel of the (freshly
    pmean'd, about-to-be-dead) gradients into the kernel layout, and one
    unravel of the delta back onto the leaves, where ``p - lr*delta`` fuses
    into the split.  Params and both accumulators never ravel — the
    round-2 measurement attributed the old kernel's ~0.3 ms/step loss to
    exactly those concats (ops/pallas_adadelta.py history; verdict weak #6).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    flat_g, unravel = ravel_pytree(grads)
    n = flat_g.shape[0]
    rows, block_rows = _pad_rows(n)
    assert state.square_avg.shape == (rows, _LANES), (
        state.square_avg.shape, rows,
    )
    g2d = jnp.pad(flat_g, (0, rows * _LANES - n)).reshape(rows, _LANES)
    vec_spec = pl.BlockSpec(
        (block_rows, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    out_shape = jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)
    delta2, sq2, ac2 = pl.pallas_call(
        _make_delta_kernel(rho, eps),
        grid=(rows // block_rows,),
        in_specs=[vec_spec, vec_spec, vec_spec],
        out_specs=[vec_spec, vec_spec, vec_spec],
        out_shape=[out_shape, out_shape, out_shape],
        # g's buffer is dead after the kernel -> reuse for delta; the
        # accumulators update in place.
        input_output_aliases={0: 0, 1: 1, 2: 2},
        interpret=interpret,
    )(g2d, state.square_avg, state.acc_delta)
    delta = unravel(delta2.reshape(-1)[:n])
    new_params = jax.tree.map(lambda p, d: p - lr * d, params, delta)
    return new_params, FlatAdadeltaState(square_avg=sq2, acc_delta=ac2)


def adadelta_update_pallas(
    params: Any,
    grads: Any,
    state: AdadeltaState,
    lr: jax.Array | float,
    rho: float = 0.9,
    eps: float = 1e-6,
    interpret: bool | None = None,
) -> tuple[Any, AdadeltaState]:
    """Drop-in replacement for ops/adadelta.py:adadelta_update backed by the
    fused Pallas kernel: ravel the pytrees, one kernel over everything,
    unravel."""
    flat_p, unravel = ravel_pytree(params)
    flat_g, _ = ravel_pytree(grads)
    flat_sq, _ = ravel_pytree(state.square_avg)
    flat_ac, _ = ravel_pytree(state.acc_delta)
    p, sq, ac = fused_adadelta_flat(
        flat_p, flat_g, flat_sq, flat_ac, lr, rho, eps, interpret
    )
    return unravel(p), AdadeltaState(unravel(sq), unravel(ac))


def adadelta_update_best(
    params: Any,
    grads: Any,
    state: AdadeltaState,
    lr: jax.Array | float,
    rho: float = 0.9,
    eps: float = 1e-6,
    use_pallas: bool | None = None,
) -> tuple[Any, AdadeltaState]:
    """Dispatch between the fused Pallas kernel and the plain pytree update.

    Default (``use_pallas=None``) is the *measured* best: at this model's
    1.2M params the plain update wins on TPU v5e (XLA already fuses the
    elementwise chain per-leaf, and the kernel's ravel_pytree concatenation
    costs ~0.3 ms/step more than its fusion saves — benchmarked at
    0.19 s/epoch plain vs 0.20 s/epoch pallas, batch 200).  The kernel
    pays off when leaves are larger or more numerous; opt in with
    ``use_pallas=True`` (CLI ``--pallas-opt``).

    Opting in on a backend with no real Pallas TPU lowering falls back to
    the plain update with a warning: interpret mode is orders of magnitude
    slower and must never be reachable from the CLI by accident.  Tests
    exercise the interpreted kernel on CPU by setting
    ``TPU_MNIST_PALLAS_INTERPRET=1`` (or calling adadelta_update_pallas
    with ``interpret=True`` directly)."""
    if is_flat_state(state):
        # The init site (adadelta_init_flat, chosen via pallas_opt_active)
        # already committed to the kernel layout; only the kernel can
        # consume it.
        return adadelta_update_flat(
            params, grads, state, lr, rho, eps,
            interpret=jax.default_backend() != "tpu",
        )
    if use_pallas:
        backend = jax.default_backend()
        if backend == "tpu":
            return adadelta_update_pallas(params, grads, state, lr, rho, eps)
        if os.environ.get("TPU_MNIST_PALLAS_INTERPRET") == "1":
            return adadelta_update_pallas(
                params, grads, state, lr, rho, eps, interpret=True
            )
        import warnings

        warnings.warn(
            f"--pallas-opt requested on backend {backend!r}, which would "
            "run the kernel in slow interpret mode; using the plain "
            "Adadelta update instead (set TPU_MNIST_PALLAS_INTERPRET=1 "
            "to force interpret mode for testing)",
            stacklevel=2,
        )
    return adadelta_update(params, grads, state, lr, rho, eps)
