"""obs: the unified telemetry layer (docs/OBSERVABILITY.md).

PR 3 collapses the repo's three disconnected instrumentation islands —
``utils/profiling.StepStats``, ``serving/metrics.ServingMetrics`` (each
previously with its own, semantically different percentile), and the
``analysis/sentinel`` trace counts — onto one dependency-free core:

- :mod:`.registry` — named counters / gauges / reservoir histograms
  with label support and THE shared linear-interpolation
  :func:`~.registry.percentile`.
- :mod:`.events` — structured JSONL event sink (monotonic ``ts``, run
  id, rank; chief-only by default in distributed mode).
- :mod:`.spans` — ``span("name")`` context manager emitting
  start/end/duration events with nesting, optionally wrapping the
  XProf capture (``utils.profiling.trace``) so timing and profiling
  share one API.
- :mod:`.export` — Prometheus text exposition rendered from the
  registry (served by ``GET /metrics``, written as ``metrics.prom`` by
  training runs).

Training runs opt in with ``--telemetry-dir DIR`` (default stdout stays
byte-identical to the reference); the serving process is always on.
Everything here is stdlib-only — no jax import, same rationale as
analysis/engine.py: observability must never pay a device-init cost.
"""

from __future__ import annotations

import os

from .events import EventSink, NullSink, open_sink, read_events
from .export import render_prometheus, write_prometheus
from .registry import Counter, Gauge, Histogram, Registry, percentile
from .spans import current_span, span

__all__ = [
    "Counter",
    "EventSink",
    "Gauge",
    "Histogram",
    "NullSink",
    "Registry",
    "Telemetry",
    "current_span",
    "open_sink",
    "percentile",
    "read_events",
    "render_prometheus",
    "span",
    "write_prometheus",
]


class Telemetry:
    """One run's telemetry bundle: a registry + an event sink + spans.

    The trainer's ``--telemetry-dir`` object (trainer.fit).  Events and
    the end-of-run exposition file are chief-gated in distributed mode
    (the registry still records on every rank, for in-process readers);
    ``span`` binds this bundle's sink and registry so call sites just
    say ``with telemetry.span("epoch", epoch=3):``.
    """

    def __init__(
        self,
        directory: str,
        rank: int = 0,
        distributed: bool = False,
        registry: Registry | None = None,
        run_id: str | None = None,
    ):
        self.directory = directory
        self.registry = registry if registry is not None else Registry()
        self.events = open_sink(
            directory, rank=rank, distributed=distributed, run_id=run_id
        )

    def span(self, name: str, trace_dir: str | None = None, **fields):
        return span(
            name,
            sink=self.events,
            registry=self.registry,
            trace_dir=trace_dir,
            **fields,
        )

    def write_exposition(self, filename: str = "metrics.prom") -> str | None:
        """Render the registry to ``<dir>/metrics.prom`` (chief only, the
        same gate as events); returns the path, or None when gated."""
        if not self.events:
            return None
        path = os.path.join(self.directory, filename)
        write_prometheus(self.registry, path)
        return path

    def close(self) -> None:
        self.events.close()
