"""Structured JSONL event sink.

The registry (obs/registry.py) answers "what is the value now"; this
module answers "what happened, when" — one JSON object per line, written
as events occur, so a run that dies mid-epoch still leaves every step it
completed on disk (the append-and-flush discipline the bench artifacts
learned the hard way in round 3).

Schema: every record carries

- ``ts`` — ``time.monotonic()`` at emit.  Monotonic, not wall: event
  DELTAS are the measurement (step latency, span duration) and must not
  jump when NTP steps the clock.
- ``wall`` — ``time.time()`` at emit, for correlating against logs and
  other hosts (never subtract two ``wall`` values; that is what ``ts``
  is for).
- ``run_id`` — one opaque id per sink, so a directory accumulating
  several runs stays separable (tools/perf_report.py --telemetry).
- ``rank`` — the emitting process (0 in single-process runs).
- ``event`` — the event name; remaining keys are event-specific.

Rank gating: in distributed mode only the chief writes by default
(``open_sink``), mirroring the stdout convention (utils/logging.py —
"callers decide rank-gating, process 0 only").  Non-chief ranks get a
:class:`NullSink` so call sites stay unconditional.
"""

from __future__ import annotations

import json
import os
import threading
import time


class NullSink:
    """Swallows events; falsy so ``if sink:`` gates chief-only work."""

    path = None
    run_id = None

    def emit(self, event: str, **fields) -> None:
        pass

    def close(self) -> None:
        pass

    def __bool__(self) -> bool:
        return False


def _make_run_id() -> str:
    # Wall-clock prefix for human sorting + random suffix for uniqueness
    # (two runs starting within one second must not interleave as one).
    return time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()) + "-" + os.urandom(3).hex()


class EventSink:
    """Append-mode JSONL writer; ``emit`` is thread-safe and flushes per
    line (a crashed run keeps everything already emitted)."""

    def __init__(
        self,
        directory: str,
        run_id: str | None = None,
        rank: int = 0,
        filename: str | None = None,
    ):
        os.makedirs(directory, exist_ok=True)
        self.run_id = run_id or _make_run_id()
        self.rank = int(rank)
        self.path = os.path.join(
            directory, filename or f"events-rank{self.rank}.jsonl"
        )
        self._lock = threading.Lock()
        self._f = open(self.path, "a", encoding="utf-8")

    def emit(self, event: str, **fields) -> None:
        record = {
            "ts": time.monotonic(),
            "wall": time.time(),
            "run_id": self.run_id,
            "rank": self.rank,
            "event": event,
        }
        record.update(fields)
        line = json.dumps(record, sort_keys=False)
        with self._lock:
            if self._f.closed:
                return  # late emit after close (daemon thread tail): drop
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def __bool__(self) -> bool:
        return True


def open_sink(
    directory: str | None,
    rank: int = 0,
    distributed: bool = False,
    chief_only: bool = True,
    run_id: str | None = None,
) -> EventSink | NullSink:
    """The one constructor call sites use: falsy ``directory`` or a
    non-chief rank (distributed + ``chief_only``) yields a NullSink, so
    telemetry code never branches on mode."""
    if not directory:
        return NullSink()
    if distributed and chief_only and rank != 0:
        return NullSink()
    return EventSink(directory, run_id=run_id, rank=rank)


def read_events(path: str) -> list[dict]:
    """Parse one JSONL file, skipping blank and torn lines (a live run's
    last line may be mid-write; a summarizer must not crash on it)."""
    out: list[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
    return out
