"""Prometheus text-format exposition rendered from a Registry.

Text format 0.0.4 (``# HELP`` / ``# TYPE`` / samples), the thing every
scraper in existence parses.  Counters and gauges render directly;
reservoir histograms render as Prometheus *summaries* — ``{quantile=
"0.5|0.95|0.99"}`` samples plus lifetime ``_sum``/``_count`` — because
quantiles over the recent window are exactly what the reservoir holds
(fixed-bucket ``histogram`` series would impose a bucket ladder the
recording sites never chose).

Consumed by serving/server.py's ``GET /metrics`` (``Accept: text/plain``
or ``?format=prom``) and by :meth:`obs.Telemetry.write_exposition`,
which drops ``metrics.prom`` into the ``--telemetry-dir`` at end of run
for offline scraping/grepping (the CI smoke does exactly that).
"""

from __future__ import annotations

from .registry import Registry, percentile

_QUANTILES = (("0.5", 50.0), ("0.95", 95.0), ("0.99", 99.0))


def _escape_label(value: str) -> str:
    return (
        value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _labels_str(labels: dict[str, str], extra: tuple[str, str] | None = None) -> str:
    items = list(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in items)
    return "{" + body + "}"


def _fmt_value(v) -> str:
    # Integral values print as integers (counter idiom); floats use repr
    # so no precision is invented or lost.
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def render_prometheus(registry: Registry) -> str:
    """The full exposition document (trailing newline included).

    Rendered under the registry-wide lock, so one scrape is a consistent
    cut across every metric (a request completing mid-render cannot show
    a completed count without its latency observation)."""
    lines: list[str] = []
    with registry.locked():
        _render_into(lines, registry)
    return "\n".join(lines) + "\n"


def _render_into(lines: list[str], registry: Registry) -> None:
    for name, type_str, help_text, children in registry.collect():
        if help_text:
            lines.append(f"# HELP {name} {_escape_help(help_text)}")
        # Reservoir histograms expose as the summary metric type (module
        # docstring); counters/gauges map 1:1.
        lines.append(
            f"# TYPE {name} {'summary' if type_str == 'histogram' else type_str}"
        )
        for labels, metric in children:
            if type_str == "histogram":
                sorted_window = sorted(metric.values())
                for q_label, q in _QUANTILES:
                    lines.append(
                        f"{name}{_labels_str(labels, ('quantile', q_label))} "
                        f"{_fmt_value(percentile(sorted_window, q))}"
                    )
                lines.append(
                    f"{name}_sum{_labels_str(labels)} {_fmt_value(metric.sum)}"
                )
                lines.append(
                    f"{name}_count{_labels_str(labels)} {_fmt_value(metric.count)}"
                )
            else:
                lines.append(
                    f"{name}{_labels_str(labels)} {_fmt_value(metric.value)}"
                )


def write_prometheus(registry: Registry, path: str) -> None:
    """Atomic-enough single write (scrapers re-read whole files)."""
    with open(path, "w", encoding="utf-8") as f:
        f.write(render_prometheus(registry))
