"""``span("name")``: one API for timed regions, events, and XProf.

A span emits ``span_start``/``span_end`` events (obs/events.py), records
its duration into the registry histogram ``span_duration_seconds{span=
name}``, and — when ``trace_dir`` is set — wraps the region in the
existing ``utils.profiling.trace`` XProf capture, so "time this" and
"profile this" are the same call site with one extra argument instead of
two nested context managers that can drift apart.

Nesting is tracked per thread: a child span's events carry
``parent``/``depth``, so the JSONL reconstructs the call tree without
any end-time matching heuristics.
"""

from __future__ import annotations

import contextlib
import threading
import time

_stack = threading.local()


def current_span() -> str | None:
    """Name of the innermost open span on this thread, or None."""
    stack = getattr(_stack, "names", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def span(
    name: str,
    sink=None,
    registry=None,
    trace_dir: str | None = None,
    **fields,
):
    """Time a region; emit start/end events; optionally XProf it.

    ``sink`` and ``registry`` are both optional — a span with neither is
    still a correct (if silent) timer, so library code can open spans
    unconditionally and let the caller decide where they land.  Extra
    ``fields`` ride on both events (``epoch=3`` etc.).
    """
    stack = getattr(_stack, "names", None)
    if stack is None:
        stack = _stack.names = []
    parent = stack[-1] if stack else None
    depth = len(stack)
    if sink is not None:
        sink.emit("span_start", span=name, parent=parent, depth=depth, **fields)
    stack.append(name)
    t0 = time.perf_counter()
    try:
        if trace_dir:
            from ..utils.profiling import trace

            with trace(trace_dir):
                yield
        else:
            yield
    finally:
        duration = time.perf_counter() - t0
        stack.pop()
        if registry is not None:
            registry.histogram(
                "span_duration_seconds",
                help="wall duration of obs.span regions",
                span=name,
            ).observe(duration)
        if sink is not None:
            sink.emit(
                "span_end",
                span=name,
                parent=parent,
                depth=depth,
                duration_s=duration,
                **fields,
            )
