"""Named metrics registry: counters, gauges, reservoir histograms.

Before this module the repo had THREE percentile implementations and
three disconnected places numbers lived (utils/profiling.StepStats,
serving/metrics.ServingMetrics, analysis/sentinel trace counts) — none
scrapeable, none correlatable.  This registry is the one place a number
goes to become observable: every metric is named, optionally labeled
(rank/bucket/phase/...), thread-safe, and renderable as Prometheus text
(obs/export.py) or readable in-process.

Deliberately dependency-free (stdlib only, no jax import) for the same
reason as analysis/engine.py: observability must never pay a device-init
cost, and the serving HTTP handlers scrape it from plain threads.

Conventions
-----------
- One :class:`Registry` per process surface (the serving process owns
  one via ``ServingMetrics.registry``; a ``--telemetry-dir`` training
  run owns one via ``obs.Telemetry``).  Module-global state is avoided
  so tests compose freely.
- A *family* is one metric name with one type and one label-key set;
  children are distinguished by label values, exactly the Prometheus
  data model.  Re-registering a name with a conflicting type or label
  keys raises immediately — silent aliasing is how metrics lie.
- All percentiles in the repo go through :func:`percentile` (linear
  interpolation, the numpy default).  The previous split — StepStats'
  rounded nearest-index vs serving's ceil nearest-rank — meant "p95"
  was two different statistics depending on which subsystem printed it.
"""

from __future__ import annotations

import threading
from collections import deque


def percentile(sorted_values: list[float], q: float) -> float:
    """Linear-interpolation percentile over an ascending-sorted list.

    ``q`` is in [0, 100].  Empty input returns 0.0 (metrics surfaces
    render before the first observation).  This is THE percentile of the
    repo: StepStats, ServingMetrics, and the telemetry reports all call
    it, so a p95 means the same thing on every surface.
    """
    if not sorted_values:
        return 0.0
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile out of range: {q}")
    if len(sorted_values) == 1:
        return sorted_values[0]
    pos = (q / 100.0) * (len(sorted_values) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_values) - 1)
    frac = pos - lo
    return sorted_values[lo] * (1.0 - frac) + sorted_values[hi] * frac


class Counter:
    """Monotonically increasing count.  ``inc`` only; a counter that can
    go down is a gauge wearing the wrong type and breaks rate() math."""

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0

    def inc(self, n: int | float = 1) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """A value that goes up and down (queue depth, samples/sec, ...)."""

    def __init__(self, lock: threading.RLock):
        self._lock = lock
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def add(self, delta: float) -> None:
        with self._lock:
            self._value += delta

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Reservoir histogram: the newest ``reservoir`` observations plus
    lifetime count/sum.

    Same bounded-window rationale as the old ServingMetrics ring: a
    long-lived process must not grow without bound, and tail percentiles
    over the recent window are what an operator acts on.  ``count`` and
    ``sum`` are lifetime totals (Prometheus summary semantics);
    percentiles come from the window.
    """

    def __init__(self, lock: threading.RLock, reservoir: int = 8192):
        self._lock = lock
        self._window: deque[float] = deque(maxlen=reservoir)
        self._count = 0
        self._sum = 0.0

    def observe(self, v: float) -> None:
        with self._lock:
            self._window.append(float(v))
            self._count += 1
            self._sum += v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def values(self) -> list[float]:
        """Snapshot of the current window (unsorted, insertion order)."""
        with self._lock:
            return list(self._window)

    def percentile(self, q: float) -> float:
        return percentile(sorted(self.values()), q)


_TYPES = {Counter: "counter", Gauge: "gauge", Histogram: "histogram"}


class _Family:
    """One metric name: its type, help text, label-key set, children."""

    def __init__(self, name: str, cls, help: str, label_keys: tuple[str, ...]):
        self.name = name
        self.cls = cls
        self.help = help
        self.label_keys = label_keys
        self.children: dict[tuple[str, ...], object] = {}


_NAME_OK = set("abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


class Registry:
    """Thread-safe named metric store.

    ``counter``/``gauge``/``histogram`` are get-or-create: callers hold
    the returned metric for hot-path recording, or re-look it up by name
    + labels (cheap, one dict hit under the lock).  One registry-wide
    RLock covers creation AND every metric mutation/read, so a
    ``collect()`` (the exposition path) sees a consistent cut.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._families: dict[str, _Family] = {}

    def locked(self):
        """The registry-wide lock, for multi-metric consistent reads
        (``with registry.locked(): ...``).  Reentrant, so metric
        reads/mutations inside the block still work."""
        return self._lock

    # -- get-or-create --------------------------------------------------------

    def counter(self, name: str, help: str = "", **labels: object) -> Counter:
        return self._child(name, Counter, help, labels)

    def gauge(self, name: str, help: str = "", **labels: object) -> Gauge:
        return self._child(name, Gauge, help, labels)

    def histogram(
        self, name: str, help: str = "", reservoir: int = 8192, **labels: object
    ) -> Histogram:
        return self._child(name, Histogram, help, labels, reservoir=reservoir)

    def _child(self, name, cls, help, labels, **metric_kwargs):
        if not name or not set(name) <= _NAME_OK or name[0].isdigit():
            raise ValueError(f"invalid metric name {name!r}")
        label_keys = tuple(sorted(labels))
        label_values = tuple(str(labels[k]) for k in label_keys)
        with self._lock:
            family = self._families.get(name)
            if family is None:
                family = _Family(name, cls, help, label_keys)
                self._families[name] = family
            elif family.cls is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{_TYPES[family.cls]}, not {_TYPES[cls]}"
                )
            elif family.label_keys != label_keys:
                raise ValueError(
                    f"metric {name!r} registered with labels "
                    f"{list(family.label_keys)}, got {list(label_keys)}; one "
                    "family, one label-key set (the Prometheus data model)"
                )
            child = family.children.get(label_values)
            if child is None:
                child = cls(self._lock, **metric_kwargs)
                family.children[label_values] = child
            return child

    # -- reading --------------------------------------------------------------

    def collect(self):
        """``[(name, type_str, help, [(labels_dict, metric), ...]), ...]``
        sorted by name — the exposition input (obs/export.py)."""
        with self._lock:
            out = []
            for name in sorted(self._families):
                family = self._families[name]
                children = [
                    (dict(zip(family.label_keys, values)), metric)
                    for values, metric in sorted(family.children.items())
                ]
                out.append((name, _TYPES[family.cls], family.help, children))
            return out
