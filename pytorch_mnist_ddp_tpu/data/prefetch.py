"""Device-resident double-buffered prefetch: the steady-state input leg.

PR 5 proved overlap wins at *startup* (compile ∥ H2D ∥ restore); this
module extends the discipline into steady state.  BENCH_r05's flagship
row shows ``device_run_share=0.684`` — roughly a third of the wall clock
is host-side feeding and bookkeeping.  The prefetcher attacks exactly
that slice: while step k executes on the device, batch k+1 is already
assembled on the host AND its H2D transfer dispatched (``jax.device_put``
/ ``make_array_from_process_local_data`` are async), so by the time the
consumer asks for it the transfer tail — not the whole assemble+transfer
chain — is all that remains.  The consumer's per-batch cost collapses to
a queue pop: a buffer swap.

:class:`DevicePrefetcher` is deliberately generic (and jax-free — the
placement callable is the caller's, same dependency contract as
``compile/service.py``): it wraps ANY host-batch iterator plus a
``place`` callable and keeps up to ``depth`` placed batches in a bounded
queue fed by a background thread.  ``data/loader.DataLoader`` builds its
epochs on it (sharded placement via the ``parallel/mesh`` data axis);
the serving engine stages padded batches on device the same way
(``serving/engine.InferenceEngine`` device staging).  The structural
throughput test drives it with a fake device (`tests/test_steadystate
.py`), mirroring the PR 4/5 fake-compiler pattern.

Observability (docs/OBSERVABILITY.md "steady state" family):

- ``data_wait_seconds{pipeline=}`` — histogram of the time the consumer
  blocked waiting for the next batch.  THE steady-state health number:
  near-zero means the device never waits on the host; large means the
  input pipeline is the bottleneck (deepen ``depth`` or speed up
  assembly).
- ``prefetch_buffer_occupancy{pipeline=}`` — histogram of how many
  placed batches were buffered at each consume.  Pinned at ``depth``
  when the producer keeps ahead; hugging 0 when the consumer is starved.
- a ``prefetch_epoch`` JSONL event per exhausted epoch (batches, total
  wait, consume wall, mean occupancy) — `tools/perf_report.py
  --telemetry` renders these as the "steady state" section with a
  ``device_run_share``-style wait/step split.

``depth <= 0`` is the synchronous baseline: assemble+place inline on the
consumer thread (the pre-prefetch serial pipeline, kept for A/Bs and the
bit-identity pin — batches are identical either way, only the overlap
changes).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator

_END = object()
_ERR = object()


def _identity(batch):
    return batch


class DevicePrefetcher:
    """Keep up to ``depth`` device-placed batches in flight ahead of the
    consumer.

    Parameters
    ----------
    source:
        Iterable of host batches (consumed on the producer thread when
        ``depth > 0``, inline otherwise).
    place:
        ``host batch -> device batch``; called as early as possible so
        an async H2D dispatch overlaps the consumer's current step.
        Defaults to identity (host-only pipelines still get the
        assembly overlap).
    depth:
        Bounded buffer size; ``>= 2`` double-buffers (batch k+1 places
        while batch k is consumed), ``<= 0`` is the synchronous serial
        baseline.
    registry / sink:
        Optional obs surfaces; see the module docstring for the metric
        family.  ``pipeline`` labels the family (``train``, ``eval``,
        ``serving``); ``epoch`` rides the ``prefetch_epoch`` event.
    """

    def __init__(
        self,
        source: Iterable,
        place: Callable | None = None,
        depth: int = 2,
        registry=None,
        sink=None,
        pipeline: str = "data",
        epoch: int | None = None,
    ):
        self._source = iter(source)
        self._place = place if place is not None else _identity
        self.depth = int(depth)
        self.pipeline = pipeline
        self._epoch = epoch
        self._sink = sink
        self._wait_hist = (
            registry.histogram(
                "data_wait_seconds",
                help="consumer wait for the next device-resident batch "
                "(near-zero = the device never waits on the host)",
                pipeline=pipeline,
            )
            if registry is not None
            else None
        )
        self._occ_hist = (
            registry.histogram(
                "prefetch_buffer_occupancy",
                help="placed batches buffered at each consume "
                "(pinned at depth = producer ahead; 0 = consumer starved)",
                pipeline=pipeline,
            )
            if registry is not None
            else None
        )
        self.batches = 0
        self.wait_s_total = 0.0
        self._occ_total = 0.0
        self._t_first: float | None = None
        self._t_last: float | None = None
        self._emitted = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._queue: queue.Queue | None = None
        if self.depth > 0:
            self._queue = queue.Queue(maxsize=self.depth)
            self._thread = threading.Thread(
                target=self._producer, name=f"prefetch-{pipeline}", daemon=True
            )
            self._thread.start()

    # -- producer (depth > 0) -------------------------------------------------

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self) -> None:
        try:
            for hb in self._source:
                # place() here IS the early H2D: dispatch is async, so
                # the transfer rides under the consumer's current step.
                if not self._put(self._place(hb)):
                    return  # consumer abandoned the epoch (dry-run break)
            self._put(_END)
        except BaseException as e:  # surfaced on the consumer side
            self._put((_ERR, e))

    # -- consumer -------------------------------------------------------------

    def _record(self, wait: float, occupancy: int) -> None:
        self.batches += 1
        self.wait_s_total += wait
        self._occ_total += occupancy
        if self._wait_hist is not None:
            self._wait_hist.observe(wait)
        if self._occ_hist is not None:
            self._occ_hist.observe(occupancy)

    def __iter__(self) -> Iterator:
        try:
            if self._queue is None:
                # Synchronous baseline: the whole assemble+place cost is
                # consumer wait, recorded so the A/B shows exactly what
                # depth > 0 hides.
                while True:
                    t0 = time.perf_counter()
                    if self._t_first is None:
                        self._t_first = t0
                    try:
                        item = self._place(next(self._source))
                    except StopIteration:
                        break
                    self._record(time.perf_counter() - t0, 0)
                    yield item
                    self._t_last = time.perf_counter()
                return
            while True:
                t0 = time.perf_counter()
                if self._t_first is None:
                    self._t_first = t0
                item = self._queue.get()
                if item is _END:
                    break
                if (
                    isinstance(item, tuple)
                    and len(item) == 2
                    and item[0] is _ERR
                ):
                    raise item[1]
                self._record(time.perf_counter() - t0, self._queue.qsize())
                yield item
                self._t_last = time.perf_counter()
        finally:
            self.close()

    # -- lifecycle ------------------------------------------------------------

    @property
    def occupancy_mean(self) -> float:
        return self._occ_total / self.batches if self.batches else 0.0

    @property
    def consume_wall_s(self) -> float:
        """First ask -> last yield consumed: the steady-state window the
        wait share is measured against."""
        if self._t_first is None or self._t_last is None:
            return 0.0
        return self._t_last - self._t_first

    def close(self) -> None:
        """Stop and reap the producer (idempotent; the epoch iterator
        calls it on exhaustion AND abandonment), then emit the epoch
        summary event once."""
        self._stop.set()
        if self._queue is not None:
            while True:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._sink and not self._emitted and self.batches:
            self._emitted = True
            self._sink.emit(
                "prefetch_epoch",
                pipeline=self.pipeline,
                epoch=self._epoch,
                depth=self.depth,
                batches=self.batches,
                wait_s_total=round(self.wait_s_total, 6),
                consume_wall_s=round(self.consume_wall_s, 6),
                occupancy_mean=round(self.occupancy_mean, 4),
            )
