from .mnist import MNIST, load_mnist_arrays
from .transforms import normalize, MNIST_MEAN, MNIST_STD
from .loader import DataLoader
from .prefetch import DevicePrefetcher
