"""Host-side input pipeline (replaces ``DataLoader`` + samplers +
pin-memory workers; SURVEY.md N5-N7).

The reference's loader stack is per-sample Python transforms inside worker
subprocesses feeding pinned staging buffers (reference mnist_ddp.py:146-151,
167-168).  The TPU-native pipeline is different in kind:

- Batches are assembled **vectorized** on the host: one fancy-index gather
  of uint8 images + one fused affine normalize (data/transforms.py) per
  batch — no per-sample Python, no worker processes needed at MNIST scale.
- A background prefetch thread stays ``prefetch_depth`` batches ahead and
  *starts the host->device transfer early* (``device_put`` is async), so
  the device never waits on the host — the role pin-memory + workers play
  in the reference, and the real risk to the wall-clock target
  (SURVEY.md §7 'hard parts': ~12 ms/step budget).
- Per-host sharding is folded in: each process materializes only its
  sampler shard (parallel/sampler.py) and placement produces a *global*
  jax.Array sharded over the mesh ``data`` axis
  (``jax.make_array_from_process_local_data`` — single- and multi-host).
- Final partial batches are padded to the static batch shape with a 0/1
  weight mask so jit never sees a new shape (SURVEY.md §7 'non-divisible
  eval batches').
"""

from __future__ import annotations

import time
from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sampler import epoch_indices, per_rank_count
from ..serving.faults import fault_point
from . import native
from .prefetch import DevicePrefetcher
from .transforms import MNIST_MEAN, MNIST_STD, normalize
from ..parallel.mesh import DATA_AXIS

Batch = tuple[jax.Array, jax.Array, jax.Array]  # (x, y, weight-mask)


class DataLoader:
    """Epoch-based batched loader over in-memory uint8 arrays.

    ``global_batch`` is the whole-mesh batch size; each process assembles
    ``global_batch / process_count`` samples and each device receives
    ``global_batch / world_size``.  ``epoch(e)`` yields device-placed
    ``(x, y, w)`` with static shapes.
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        global_batch: int,
        mesh: Mesh | None = None,
        shuffle: bool = True,
        seed: int = 0,
        process_rank: int = 0,
        process_count: int = 1,
        drop_last: bool = False,
        prefetch_depth: int = 2,
        device_place: bool = True,
        mask_padding: bool = False,
        registry=None,
        sink=None,
        pipeline: str = "train",
        data_retries: int = 3,
        data_backoff_s: float = 0.05,
    ) -> None:
        if global_batch % process_count:
            raise ValueError(
                f"global batch {global_batch} not divisible by "
                f"{process_count} processes"
            )
        self.images = images
        self._labels_raw = labels  # uint8 view for the native gather
        self.labels = labels.astype(np.int32)
        self.global_batch = global_batch
        self.host_batch = global_batch // process_count
        self.mesh = mesh
        self.shuffle = shuffle
        self.seed = seed
        self.process_rank = process_rank
        self.process_count = process_count
        self.drop_last = drop_last
        # mask_padding: zero-weight the sampler's pad-to-divisible duplicate
        # samples (eval wants each test sample counted exactly once; train
        # keeps duplicates live like torch's DistributedSampler).
        self.mask_padding = mask_padding
        self.prefetch_depth = prefetch_depth
        # Steady-state observability (data/prefetch.py): optional obs
        # registry + JSONL sink for the data_wait_seconds /
        # prefetch_buffer_occupancy family and per-epoch summary events.
        self.registry = registry
        self.sink = sink
        self.pipeline = pipeline
        # Transient-fault tolerance (PR 9, docs/ROBUSTNESS.md): each
        # batch assembly retries up to ``data_retries`` times with
        # exponential backoff on OSError/RuntimeError (the transient
        # storage/injection class) before giving up with one clear
        # error — a single flaky read must not kill a long run.
        self.data_retries = int(data_retries)
        self.data_backoff_s = float(data_backoff_s)
        self.device_place = device_place and mesh is not None
        if self.device_place:
            n_shards = mesh.shape[DATA_AXIS]
            if self.global_batch % n_shards:
                raise ValueError(
                    f"global batch {global_batch} not divisible by the "
                    f"{n_shards}-way data axis"
                )
            self._shardings = tuple(
                NamedSharding(mesh, spec) for spec in (P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS))
            )

    def __len__(self) -> int:
        """Batches per epoch (matches ``len(train_loader)`` in the log-line
        percentage, reference mnist_ddp.py:79)."""
        n = per_rank_count(len(self.labels), self.process_count)
        if self.drop_last:
            return n // self.host_batch
        return -(-n // self.host_batch)

    @property
    def dataset_len(self) -> int:
        """Global dataset size (the log lines' denominator)."""
        return len(self.labels)

    # -- host-side assembly --------------------------------------------------

    def _assemble(self, idx: np.ndarray, valid: np.ndarray, b: int):
        """Assemble host batch ``b`` of the epoch permutation ``idx``."""
        hb = self.host_batch
        take = idx[b * hb : (b + 1) * hb]
        # Native multithreaded gather+normalize when the C++ core is
        # available (data/native.py); identical numpy math otherwise.
        x = native.gather_normalize(self.images, take, MNIST_MEAN, MNIST_STD)
        if x is None:
            x = normalize(self.images[take])
        y = native.gather_labels(self._labels_raw, take)
        if y is None:
            y = self.labels[take]
        if self.mask_padding:
            w = valid[b * hb : (b + 1) * hb].astype(np.float32)
        else:
            w = np.ones(len(take), np.float32)
        if len(take) < hb:  # pad the final partial batch, mask it out
            pad = hb - len(take)
            x = np.concatenate([x, np.zeros((pad, *x.shape[1:]), x.dtype)])
            y = np.concatenate([y, np.zeros(pad, y.dtype)])
            w = np.concatenate([w, np.zeros(pad, np.float32)])
        return x, y, w

    def _host_batches(
        self, epoch: int, start_batch: int = 0
    ) -> Iterator[tuple[np.ndarray, np.ndarray, np.ndarray]]:
        idx, valid = epoch_indices(
            len(self.labels),
            self.process_count,
            self.process_rank,
            epoch,
            self.seed,
            self.shuffle,
            return_valid=True,
        )
        hb = self.host_batch
        n_full, rem = divmod(len(idx), hb)
        total = n_full + (0 if (self.drop_last or not rem) else 1)
        # start_batch (mid-epoch resume, resilience/checkpoint.py): skip
        # the first N batches of THIS epoch's permutation by index — the
        # skipped batches are never assembled, and the yielded ones are
        # bit-identical to batches N.. of the uninterrupted epoch.
        for b in range(start_batch, total):
            # Bounded retry-with-backoff on the transient-fault class
            # (flaky storage, the injected 'data_next' site): assembly
            # is deterministic, so a retried batch is bit-identical.
            for attempt in range(self.data_retries + 1):
                try:
                    fault_point("data_next")
                    batch = self._assemble(idx, valid, b)
                    break
                except (OSError, RuntimeError) as e:
                    if attempt >= self.data_retries:
                        raise RuntimeError(
                            f"data pipeline [{self.pipeline}] failed "
                            f"assembling batch {b} of epoch {epoch} after "
                            f"{attempt + 1} attempt(s): {e}"
                        ) from e
                    if self.registry is not None:
                        self.registry.counter(
                            "data_retries_total",
                            help="transient input-pipeline faults retried",
                            pipeline=self.pipeline,
                        ).inc()
                    if self.sink is not None:
                        self.sink.emit(
                            "data_retry",
                            pipeline=self.pipeline,
                            epoch=epoch,
                            batch=b,
                            attempt=attempt + 1,
                            error=f"{type(e).__name__}: {e}",
                        )
                    time.sleep(self.data_backoff_s * (2 ** attempt))
            yield batch

    def _place(self, host_batch: tuple[np.ndarray, ...]) -> Batch:
        if not self.device_place:
            return tuple(map(jax.numpy.asarray, host_batch))  # type: ignore[return-value]
        return tuple(
            jax.make_array_from_process_local_data(s, a)
            for s, a in zip(self._shardings, host_batch)
        )  # type: ignore[return-value]

    # -- prefetching epoch iterator ------------------------------------------

    def epoch(self, epoch: int, start_batch: int = 0) -> Iterator[Batch]:
        """Yield device-placed batches for one epoch, assembling and
        transferring ahead of consumption through a
        :class:`~.prefetch.DevicePrefetcher` (``prefetch_depth <= 0`` is
        the synchronous serial baseline; batches are bit-identical
        either way, only the overlap changes).  ``start_batch`` resumes
        mid-epoch: batches ``0..start_batch-1`` of this epoch's
        permutation are skipped (never assembled), so a resumed run
        consumes the exact remaining batches."""
        # Abandonment (dry-run break, train-loop exception) closes this
        # generator; GeneratorExit reaches the prefetcher's own finally
        # through the delegation, which reaps the producer thread.
        yield from DevicePrefetcher(
            self._host_batches(epoch, start_batch),
            place=self._place,
            depth=self.prefetch_depth,
            registry=self.registry,
            sink=self.sink,
            pipeline=self.pipeline,
            epoch=epoch,
        )
