"""Input transforms (replaces ``transforms.Compose([ToTensor, Normalize])``).

The reference composes ``ToTensor()`` (uint8 HWC -> float32 CHW in [0,1])
with ``Normalize((0.1307,), (0.3081,))`` (reference mnist.py:112-115,
mnist_ddp.py:153-156; SURVEY.md §2a #10).  On TPU we keep images in NHWC
(the TPU-idiomatic layout — SURVEY.md §7 step 2) and fold both steps into
one vectorized affine transform applied at batch time.
"""

from __future__ import annotations

import numpy as np

MNIST_MEAN = 0.1307
MNIST_STD = 0.3081


def normalize(images_u8: np.ndarray) -> np.ndarray:
    """uint8 [N,28,28] -> float32 [N,28,28,1], scaled to [0,1] then
    standardized with the MNIST mean/std — ToTensor∘Normalize folded into
    one affine pass (same scale/shift form as the native core,
    csrc/fastloader.cpp)."""
    scale = np.float32(1.0 / (255.0 * MNIST_STD))
    shift = np.float32(-MNIST_MEAN / MNIST_STD)
    x = images_u8.astype(np.float32) * scale + shift
    return x[..., None]
