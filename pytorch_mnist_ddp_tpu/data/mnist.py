"""MNIST dataset: IDX download + parse, with a deterministic synthetic
fallback (replaces ``torchvision.datasets.MNIST``; SURVEY.md N8).

The reference downloads the IDX files to ``./data`` on first use
(``download=True`` for the train split, reference mnist_ddp.py:157).  TPU
hosts have no torchvision, so this module is self-contained:

1. If the four IDX files exist under ``root`` (or ``$MNIST_DATA_DIR``),
   parse them.  Both raw and gzip files are accepted.
2. Else, if downloading is allowed, fetch them from the canonical mirrors.
3. Else (air-gapped hosts), generate a deterministic *synthetic* MNIST-like
   dataset — same shapes/dtypes/cardinality (60k/10k uint8 28x28, 10
   classes), learnable by the reference CNN — so every pipeline, test, and
   benchmark path runs without network access.  A notice is printed once.
"""

from __future__ import annotations

import gzip
import os
import struct
import urllib.request

import numpy as np

_MIRRORS = [
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "http://yann.lecun.com/exdb/mnist/",
]

_FILES = {
    ("train", "images"): "train-images-idx3-ubyte",
    ("train", "labels"): "train-labels-idx1-ubyte",
    ("test", "images"): "t10k-images-idx3-ubyte",
    ("test", "labels"): "t10k-labels-idx1-ubyte",
}

_IMAGE_MAGIC = 2051
_LABEL_MAGIC = 2049


def parse_idx(raw: bytes) -> np.ndarray:
    """Parse an IDX-format buffer (big-endian header) into a numpy array.

    Uses the native parser (csrc/fastloader.cpp via data/native.py) when
    built; pure-Python otherwise."""
    from . import native

    # Validation errors from the native parser (bad magic, truncated
    # payload) propagate — its stricter checks are part of the contract.
    parsed = native.parse_idx_native(raw)
    if parsed is not None:
        return parsed
    if len(raw) < 8:
        raise ValueError("truncated IDX header")
    magic, = struct.unpack(">i", raw[:4])
    if magic == _IMAGE_MAGIC:
        if len(raw) < 16:
            raise ValueError("truncated IDX image header")
        n, rows, cols = struct.unpack(">iii", raw[4:16])
        if n < 0 or rows <= 0 or cols <= 0:
            raise ValueError(f"invalid IDX image dims ({n}, {rows}, {cols})")
        data = np.frombuffer(raw, dtype=np.uint8, offset=16)
        if len(data) < n * rows * cols:
            raise ValueError("truncated IDX image payload")
        return data[: n * rows * cols].reshape(n, rows, cols)
    if magic == _LABEL_MAGIC:
        n, = struct.unpack(">i", raw[4:8])
        if n < 0:
            raise ValueError(f"invalid IDX label count ({n})")
        data = np.frombuffer(raw, dtype=np.uint8, offset=8)
        if len(data) < n:
            raise ValueError("truncated IDX label payload")
        return data[:n]
    raise ValueError(f"not an MNIST IDX buffer (magic={magic})")


def _read_maybe_gz(path: str) -> bytes | None:
    for candidate, opener in ((path, open), (path + ".gz", gzip.open)):
        if os.path.exists(candidate):
            with opener(candidate, "rb") as f:
                return f.read()
    return None


def _try_download(root: str, filename: str) -> bytes | None:
    os.makedirs(root, exist_ok=True)
    for mirror in _MIRRORS:
        url = mirror + filename + ".gz"
        try:
            with urllib.request.urlopen(url, timeout=20) as resp:
                gz = resp.read()
            raw = gzip.decompress(gz)
            with open(os.path.join(root, filename), "wb") as f:
                f.write(raw)
            return raw
        except Exception:
            continue
    return None


# ---------------------------------------------------------------------------
# Synthetic fallback


def synthetic_mnist(
    split: str, n: int | None = None, seed: int = 1234
) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped dataset for air-gapped hosts.

    Each class k is a fixed smooth random template (per-class blob pattern);
    a sample is its template under a random ±2px shift plus pixel noise.
    The task is learnable to >99% by the reference CNN while remaining
    non-trivial (shift invariance matters, which exercises the convs).
    Train and test are drawn from the same distribution with disjoint RNG
    streams.
    """
    if n is None:
        n = 60000 if split == "train" else 10000
    rng = np.random.RandomState(seed)  # template stream: shared across splits
    # 10 class templates: low-frequency random fields, rendered at 36x36 so
    # shifted 28x28 crops stay fully inside the canvas.
    freq = rng.normal(size=(10, 6, 6))
    templates = np.zeros((10, 36, 36), dtype=np.float32)
    for k in range(10):
        t = np.kron(freq[k], np.ones((6, 6)))  # 36x36 blocky field
        # cheap smoothing: two passes of a box blur
        for _ in range(2):
            t = (
                t
                + np.roll(t, 1, 0) + np.roll(t, -1, 0)
                + np.roll(t, 1, 1) + np.roll(t, -1, 1)
            ) / 5.0
        t = (t - t.min()) / (np.ptp(t) + 1e-8)
        templates[k] = t

    sample_rng = np.random.RandomState(seed + (1 if split == "train" else 2))
    labels = sample_rng.randint(0, 10, size=n).astype(np.uint8)
    shifts = sample_rng.randint(-2, 3, size=(n, 2))
    noise = sample_rng.normal(0.0, 0.08, size=(n, 28, 28)).astype(np.float32)
    base = 4  # crop origin for zero shift
    # All 5x5 shifted crops of every template, then one gather per sample —
    # vectorized but bit-identical to the per-sample crop loop.
    crops = np.empty((10, 5, 5, 28, 28), dtype=np.float32)
    for dy in range(-2, 3):
        for dx in range(-2, 3):
            crops[:, dy + 2, dx + 2] = templates[
                :, base + dy : base + dy + 28, base + dx : base + dx + 28
            ]
    gathered = crops[labels, shifts[:, 0] + 2, shifts[:, 1] + 2]
    images = (np.clip(gathered + noise, 0.0, 1.0) * 255).astype(np.uint8)
    return images, labels


# Bump when synthetic_mnist's algorithm or defaults change, so stale disk
# caches regenerate instead of silently serving pre-change data.
_SYNTH_VERSION = 1


def _synthetic_cached(split: str, seed: int = 1234) -> tuple[np.ndarray, np.ndarray]:
    """Disk-cached synthetic dataset: generated once per (split, seed,
    generator version), then the npz loads in ~100 ms on later runs
    (startup is part of the benchmarked wall clock, reference
    mnist_ddp.py:200-203)."""
    from ..utils.cache_dir import cache_root

    n = 60000 if split == "train" else 10000
    path = os.path.join(
        cache_root("synthetic"), f"{split}-s{seed}-v{_SYNTH_VERSION}.npz"
    )
    if os.path.exists(path):
        try:
            with np.load(path) as z:
                images, labels = z["images"], z["labels"]
            if images.shape == (n, 28, 28) and labels.shape == (n,):
                return images, labels
        except Exception:
            pass  # corrupt cache: regenerate below
    images, labels = synthetic_mnist(split, seed=seed)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".{os.getpid()}.tmp.npz"
        np.savez(tmp, images=images, labels=labels)
        os.replace(tmp, path)
    except OSError:
        pass  # read-only cache dir: serve from memory
    return images, labels


# ---------------------------------------------------------------------------

_synthetic_notice_printed = False


def load_mnist_arrays(
    root: str = "./data",
    split: str = "train",
    download: bool = True,
    allow_synthetic: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """Return ``(images uint8 [N,28,28], labels uint8 [N])`` for a split.

    Resolution order: ``$MNIST_DATA_DIR`` / ``root`` IDX files -> download
    (when allowed) -> deterministic synthetic fallback.
    """
    root = os.environ.get("MNIST_DATA_DIR", root)
    arrays = {}
    for kind in ("images", "labels"):
        filename = _FILES[(split, kind)]
        raw = _read_maybe_gz(os.path.join(root, filename))
        if raw is None and download:
            raw = _try_download(root, filename)
        if raw is None:
            if not allow_synthetic:
                raise FileNotFoundError(
                    f"MNIST file {filename} not found in {root} and download failed"
                )
            global _synthetic_notice_printed
            if not _synthetic_notice_printed:
                print(
                    "MNIST IDX files unavailable (no local copy, download "
                    "failed); using deterministic synthetic MNIST-like data"
                )
                _synthetic_notice_printed = True
            return _synthetic_cached(split)
        arrays[kind] = parse_idx(raw)
    images, labels = arrays["images"], arrays["labels"]
    if len(images) != len(labels):
        raise ValueError("image/label count mismatch")
    return images, labels


class MNIST:
    """Dataset object: raw uint8 arrays + length; transforms happen at batch
    time in the loader (vectorized, not per-sample like torchvision)."""

    def __init__(
        self,
        root: str = "./data",
        train: bool = True,
        download: bool = True,
        allow_synthetic: bool = True,
    ) -> None:
        self.images, self.labels = load_mnist_arrays(
            root, "train" if train else "test", download, allow_synthetic
        )

    def __len__(self) -> int:
        return len(self.images)
