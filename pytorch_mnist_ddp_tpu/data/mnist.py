"""MNIST dataset: IDX download + parse, with a deterministic synthetic
fallback (replaces ``torchvision.datasets.MNIST``; SURVEY.md N8).

The reference downloads the IDX files to ``./data`` on first use
(``download=True`` for the train split, reference mnist_ddp.py:157).  TPU
hosts have no torchvision, so this module is self-contained:

1. If the four IDX files exist under ``root`` (or ``$MNIST_DATA_DIR``),
   parse them.  Both raw and gzip files are accepted.
2. Else, if downloading is allowed, fetch them from the canonical mirrors.
3. Else (air-gapped hosts), generate a deterministic *synthetic* MNIST-like
   dataset — same shapes/dtypes/cardinality (60k/10k uint8 28x28, 10
   classes), learnable by the reference CNN — so every pipeline, test, and
   benchmark path runs without network access.  A notice is printed once.
"""

from __future__ import annotations

import gzip
import hashlib
import os
import struct
import sys
import urllib.request

import numpy as np

_MIRRORS = [
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
    "http://yann.lecun.com/exdb/mnist/",
]

_FILES = {
    ("train", "images"): "train-images-idx3-ubyte",
    ("train", "labels"): "train-labels-idx1-ubyte",
    ("test", "images"): "t10k-images-idx3-ubyte",
    ("test", "labels"): "t10k-labels-idx1-ubyte",
}

# Golden SHA-256 digests of the four RAW (uncompressed) IDX files — the
# canonical MNIST distribution (reference mnist_ddp.py:157 downloads the
# same files via torchvision).  Verified on load (round-4 verdict item 3):
# matching files record provenance "idx"; a mismatch is NEVER fatal — the
# data still loads, the computed digest is printed, and provenance becomes
# "idx-unverified" so bench.py's evidence chain stays honest either way.
_SHA256 = {
    "train-images-idx3-ubyte":
        "ba891046e6505d7aadcbbe25680a0738ad16aec93bde7f9b65e87a2fc25776db",
    "train-labels-idx1-ubyte":
        "65a50cbbf4e906d70832878ad85ccda5333a97f0f4c3dd2ef09a8a9eef7101c5",
    "t10k-images-idx3-ubyte":
        "1bf45877962fd391f7abb20534a30fd2203d0865309fec5f87d576dbdbefdcb1",
    "t10k-labels-idx1-ubyte":
        "b7e25cb63ef54da8d0fd3b0d8a38b9aaad06962e663b5d202cb1b7098e54aaf9",
}


def verify_idx_digest(filename: str, raw: bytes) -> bool:
    """True iff ``raw`` matches the golden SHA-256 for ``filename``.
    On mismatch, print both digests (stderr) so a wrong golden or a
    corrupt download is diagnosable from the run log alone."""
    golden = _SHA256.get(filename)
    digest = hashlib.sha256(raw).hexdigest()
    if digest == golden:
        return True
    print(
        f"warning: {filename} SHA-256 {digest} does not match golden "
        f"{golden}; loading anyway with provenance 'idx-unverified'",
        file=sys.stderr,
    )
    return False

_IMAGE_MAGIC = 2051
_LABEL_MAGIC = 2049


def parse_idx(raw: bytes) -> np.ndarray:
    """Parse an IDX-format buffer (big-endian header) into a numpy array.

    Uses the native parser (csrc/fastloader.cpp via data/native.py) when
    built; pure-Python otherwise."""
    from . import native

    # Validation errors from the native parser (bad magic, truncated
    # payload) propagate — its stricter checks are part of the contract.
    parsed = native.parse_idx_native(raw)
    if parsed is not None:
        return parsed
    if len(raw) < 8:
        raise ValueError("truncated IDX header")
    magic, = struct.unpack(">i", raw[:4])
    if magic == _IMAGE_MAGIC:
        if len(raw) < 16:
            raise ValueError("truncated IDX image header")
        n, rows, cols = struct.unpack(">iii", raw[4:16])
        if n < 0 or rows <= 0 or cols <= 0:
            raise ValueError(f"invalid IDX image dims ({n}, {rows}, {cols})")
        data = np.frombuffer(raw, dtype=np.uint8, offset=16)
        if len(data) < n * rows * cols:
            raise ValueError("truncated IDX image payload")
        return data[: n * rows * cols].reshape(n, rows, cols)
    if magic == _LABEL_MAGIC:
        n, = struct.unpack(">i", raw[4:8])
        if n < 0:
            raise ValueError(f"invalid IDX label count ({n})")
        data = np.frombuffer(raw, dtype=np.uint8, offset=8)
        if len(data) < n:
            raise ValueError("truncated IDX label payload")
        return data[:n]
    raise ValueError(f"not an MNIST IDX buffer (magic={magic})")


def _read_maybe_gz(path: str) -> bytes | None:
    for candidate, opener in ((path, open), (path + ".gz", gzip.open)):
        if os.path.exists(candidate):
            with opener(candidate, "rb") as f:
                return f.read()
    return None


def _try_download(root: str, filename: str) -> bytes | None:
    os.makedirs(root, exist_ok=True)
    for mirror in _MIRRORS:
        url = mirror + filename + ".gz"
        try:
            with urllib.request.urlopen(url, timeout=20) as resp:
                gz = resp.read()
            raw = gzip.decompress(gz)
            with open(os.path.join(root, filename), "wb") as f:
                f.write(raw)
            return raw
        except Exception:
            continue
    return None


# ---------------------------------------------------------------------------
# Synthetic fallback


# Difficulty knobs for the v2 generator, tuned on the real chip so the
# reference CNN's 20-epoch benchmark curve mirrors real MNIST's shape:
# epoch-1 accuracy ~90.7%, crossing 99% around epoch 4-5, topping out at
# ~99.35% by epoch 8-14 — never saturating at 100%, so the >=99% target of
# BASELINE.json stays meaningful (VERDICT r1 'Next round' #3).
_N_COARSE = 5      # coarse fields shared by class pairs (c and c+5)
_N_MODES = 10      # intra-class modes (all clean; slow learning, high floor)
_FINE_AMP = 0.7    # per-class fine detail: the pair discriminator
_MODE_AMP = 0.45   # mode-distortion amplitude (intra-class variance)
_NOISE = 0.18      # per-pixel Gaussian noise (sets the Bayes floor)
_SHIFT = 4         # max |shift| in px, each axis
_CONTRAST = 0.25   # multiplicative gain jitter half-range
_FLIP = 0.004      # label-flip rate: hard ~99.5% ceiling on test accuracy


def synthetic_mnist(
    split: str, n: int | None = None, seed: int = 1234
) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-shaped dataset for air-gapped hosts.

    Construction (v2 — non-saturating): class identity is carried by TWO
    spatial scales.  A pool of ``_N_COARSE`` smooth low-frequency fields is
    shared pairwise (class ``c`` and ``c + 5`` use the same coarse field),
    so coarse shape alone cannot separate all 10 classes; each class adds
    its own higher-frequency fine-detail field (amplitude ``_FINE_AMP``) —
    the discriminator the CNN must actually learn.  Intra-class variation
    comes from ``_N_MODES`` shared mode-distortion fields (shared across
    classes, so the mode id carries no label information), random shifts of
    up to ±``_SHIFT`` px on a 36x36 canvas, multiplicative contrast jitter,
    and per-pixel Gaussian noise.  A ``_FLIP`` fraction of labels is
    remapped to a random other class, putting a hard ceiling on attainable
    accuracy so no regression can hide behind a saturated 100%.

    Train and test are drawn from the same distribution with disjoint
    sample-RNG streams (the template stream is shared across splits).
    """
    if n is None:
        n = 60000 if split == "train" else 10000
    num_classes = 10
    rng = np.random.RandomState(seed)  # template stream: shared across splits

    def smooth(t: np.ndarray, passes: int) -> np.ndarray:
        for _ in range(passes):  # cheap box-blur via rolls
            t = (
                t
                + np.roll(t, 1, -2) + np.roll(t, -1, -2)
                + np.roll(t, 1, -1) + np.roll(t, -1, -1)
            ) / 5.0
        return t

    # All fields are rendered at 36x36 so shifted 28x28 crops stay inside
    # the canvas (base origin 4, shifts up to ±4).
    coarse = smooth(np.kron(rng.normal(size=(_N_COARSE, 6, 6)), np.ones((6, 6))), 2)
    fine = smooth(np.kron(rng.normal(size=(num_classes, 18, 18)), np.ones((2, 2))), 1)
    modes = smooth(np.kron(rng.normal(size=(_N_MODES, 9, 9)), np.ones((4, 4))), 2)

    templates = np.empty((num_classes, _N_MODES, 36, 36), dtype=np.float32)
    for c in range(num_classes):
        for m in range(_N_MODES):
            t = coarse[c % _N_COARSE] + _FINE_AMP * fine[c] + _MODE_AMP * modes[m]
            templates[c, m] = (t - t.min()) / (np.ptp(t) + 1e-8)

    sample_rng = np.random.RandomState(seed + (1 if split == "train" else 2))
    labels = sample_rng.randint(0, num_classes, size=n).astype(np.uint8)
    mode_ix = sample_rng.randint(0, _N_MODES, size=n)
    shifts = sample_rng.randint(-_SHIFT, _SHIFT + 1, size=(n, 2))
    gain = 1.0 + sample_rng.uniform(
        -_CONTRAST, _CONTRAST, size=(n, 1, 1)
    ).astype(np.float32)
    noise = sample_rng.normal(0.0, _NOISE, size=(n, 28, 28)).astype(np.float32)

    base = 4  # crop origin for zero shift
    rows = (base + shifts[:, 0])[:, None] + np.arange(28)[None, :]  # [n, 28]
    cols = (base + shifts[:, 1])[:, None] + np.arange(28)[None, :]
    # One fused advanced index (no [n, 36, 36] intermediate): ~190MB peak
    # instead of ~500MB for the 60k split.
    gathered = templates[
        labels[:, None, None], mode_ix[:, None, None],
        rows[:, :, None], cols[:, None, :],
    ]
    images = np.clip(gathered * gain + noise, 0.0, 1.0)
    images = (images * 255).astype(np.uint8)

    flips = sample_rng.rand(n) < _FLIP
    offsets = sample_rng.randint(1, num_classes, size=n)
    labels = np.where(flips, (labels + offsets) % num_classes, labels).astype(np.uint8)
    return images, labels


# Bump when synthetic_mnist's algorithm or defaults change, so stale disk
# caches regenerate instead of silently serving pre-change data.
_SYNTH_VERSION = 2


def _synthetic_cached(split: str, seed: int = 1234) -> tuple[np.ndarray, np.ndarray]:
    """Disk-cached synthetic dataset: generated once per (split, seed,
    generator version), then the npz loads in ~100 ms on later runs
    (startup is part of the benchmarked wall clock, reference
    mnist_ddp.py:200-203)."""
    from ..utils.cache_dir import cache_root

    n = 60000 if split == "train" else 10000
    path = os.path.join(
        cache_root("synthetic"), f"{split}-s{seed}-v{_SYNTH_VERSION}.npz"
    )
    if os.path.exists(path):
        try:
            with np.load(path) as z:
                images, labels = z["images"], z["labels"]
            if images.shape == (n, 28, 28) and labels.shape == (n,):
                return images, labels
        except Exception:
            pass  # corrupt cache: regenerate below
    images, labels = synthetic_mnist(split, seed=seed)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".{os.getpid()}.tmp.npz"
        np.savez(tmp, images=images, labels=labels)
        os.replace(tmp, path)
    except OSError:
        pass  # read-only cache dir: serve from memory
    return images, labels


# ---------------------------------------------------------------------------

_synthetic_notice_printed = False


def load_mnist_arrays(
    root: str = "./data",
    split: str = "train",
    download: bool = True,
    allow_synthetic: bool = True,
    return_source: bool = False,
):
    """Return ``(images uint8 [N,28,28], labels uint8 [N])`` for a split
    (plus the provenance string ``"idx"`` | ``"idx-unverified"`` |
    ``"synthetic"`` when ``return_source``).

    Resolution order: ``$MNIST_DATA_DIR`` / ``root`` IDX files -> download
    (when allowed) -> deterministic synthetic fallback.  Real files are
    SHA-256-checked against the canonical digests: drop the four IDX
    files into ``root`` and the whole evidence chain (bench JSON
    ``dataset`` field included) flips to verified real MNIST with zero
    code changes.
    """
    root = os.environ.get("MNIST_DATA_DIR", root)
    arrays = {}
    source = "idx"
    for kind in ("images", "labels"):
        filename = _FILES[(split, kind)]
        raw = _read_maybe_gz(os.path.join(root, filename))
        if raw is None and download:
            raw = _try_download(root, filename)
        if raw is not None and not verify_idx_digest(filename, raw):
            source = "idx-unverified"
        if raw is None:
            if not allow_synthetic:
                raise FileNotFoundError(
                    f"MNIST file {filename} not found in {root} and download failed"
                )
            global _synthetic_notice_printed
            if not _synthetic_notice_printed:
                print(
                    "MNIST IDX files unavailable (no local copy, download "
                    "failed); using deterministic synthetic MNIST-like data"
                )
                _synthetic_notice_printed = True
            images, labels = _synthetic_cached(split)
            return (images, labels, "synthetic") if return_source else (images, labels)
        arrays[kind] = parse_idx(raw)
    images, labels = arrays["images"], arrays["labels"]
    if len(images) != len(labels):
        raise ValueError("image/label count mismatch")
    return (images, labels, source) if return_source else (images, labels)


class MNIST:
    """Dataset object: raw uint8 arrays + length; transforms happen at batch
    time in the loader (vectorized, not per-sample like torchvision).
    ``source`` records provenance: ``"idx"`` (real files, SHA-256-verified
    against the canonical digests), ``"idx-unverified"`` (IDX files whose
    bytes miss the goldens — loaded, loudly), or ``"synthetic"``
    (air-gapped fallback) — surfaced in bench.py's JSON so recorded
    accuracy numbers say which task produced them."""

    def __init__(
        self,
        root: str = "./data",
        train: bool = True,
        download: bool = True,
        allow_synthetic: bool = True,
    ) -> None:
        self.images, self.labels, self.source = load_mnist_arrays(
            root, "train" if train else "test", download, allow_synthetic,
            return_source=True,
        )

    def __len__(self) -> int:
        return len(self.images)
