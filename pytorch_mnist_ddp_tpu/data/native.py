"""ctypes bindings for the native data-loader core (csrc/fastloader.cpp).

The shared library is built on first use with the system g++ (no pybind11
in the image; plain C ABI + ctypes).  Every entry point has a pure-numpy
fallback, so the framework works identically — just slower on the host
path — when no compiler is available.  ``DataLoader`` picks these up
automatically (data/loader.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc", "fastloader.cpp")
_LIB_ENV = "TPU_MNIST_NATIVE_LIB"

_lib = None
_tried = False


def _build_lib() -> str | None:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return None
    cache_dir = os.path.join(tempfile.gettempdir(), "tpu_mnist_native")
    os.makedirs(cache_dir, exist_ok=True)
    out = os.path.join(cache_dir, "libfastloader.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    tmp = out + f".build{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           "-o", tmp, src, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except Exception:
        return None


def get_lib():
    """Load (building if needed) the native library, or None."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    path = os.environ.get(_LIB_ENV) or _build_lib()
    if not path or not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.gather_normalize.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_void_p,
        ]
        lib.gather_labels.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.idx_parse_header.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.idx_parse_header.restype = ctypes.c_int
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def gather_normalize(
    images: np.ndarray, indices: np.ndarray, mean: float, std: float
) -> np.ndarray | None:
    """Native gather+normalize: uint8 [N,H,W] + int32 [B] ->
    float32 [B,H,W,1].  Returns None if the native lib is unavailable or
    the images aren't a contiguous uint8 buffer (caller falls back to
    numpy, which handles any dtype/stride — and copying a whole
    non-contiguous dataset per batch would defeat the point)."""
    lib = get_lib()
    if lib is None or images.dtype != np.uint8 or not images.flags["C_CONTIGUOUS"]:
        return None
    idx = np.ascontiguousarray(indices, dtype=np.int32)
    b = len(idx)
    h, w = images.shape[1], images.shape[2]
    out = np.empty((b, h, w, 1), np.float32)
    lib.gather_normalize(
        images.ctypes.data, idx.ctypes.data, b, h * w,
        ctypes.c_float(mean), ctypes.c_float(std), out.ctypes.data,
    )
    return out


def gather_labels(labels: np.ndarray, indices: np.ndarray) -> np.ndarray | None:
    lib = get_lib()
    # The native kernel reads raw uint8 labels; any other dtype takes the
    # numpy fallback (fancy indexing is already cheap there).
    if (
        lib is None
        or labels.dtype != np.uint8
        or not labels.flags["C_CONTIGUOUS"]
    ):
        return None
    idx = np.ascontiguousarray(indices, dtype=np.int32)
    out = np.empty(len(idx), np.int32)
    lib.gather_labels(labels.ctypes.data, idx.ctypes.data, len(idx), out.ctypes.data)
    return out


def parse_idx_native(raw: bytes) -> np.ndarray | None:
    """Native IDX parse; returns None when the lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    buf = np.frombuffer(raw, dtype=np.uint8)
    dims = np.zeros(4, np.int64)
    rc = lib.idx_parse_header(buf.ctypes.data, len(buf), dims.ctypes.data)
    if rc != 0:
        raise ValueError(f"not an MNIST IDX buffer (native parser rc={rc})")
    n, rows, cols, offset = (int(d) for d in dims)
    if rows:  # images
        return buf[offset : offset + n * rows * cols].reshape(n, rows, cols).copy()
    return buf[offset : offset + n].copy()
