"""ctypes bindings for the native data-loader core (csrc/fastloader.cpp).

The shared library is built on first use with the system g++ (no pybind11
in the image; plain C ABI + ctypes).  Every entry point has a pure-numpy
fallback, so the framework works identically — just slower on the host
path — when no compiler is available.  ``DataLoader`` picks these up
automatically (data/loader.py).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess

import numpy as np

from ..utils.cache_dir import cache_root

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc", "fastloader.cpp")
_LIB_ENV = "TPU_MNIST_NATIVE_LIB"
_CFLAGS = ["-O3", "-march=native", "-shared", "-fPIC", "-std=c++17"]


def _cpu_tag() -> str:
    """Discriminator for the -march=native binary: arch + ISA feature set,
    so a cache shared across heterogeneous hosts (NFS home) never serves a
    binary with unsupported instructions (SIGILL)."""
    feats = b""
    try:
        with open("/proc/cpuinfo", "rb") as f:
            for line in f:
                if line.startswith((b"flags", b"Features")):
                    feats = b" ".join(sorted(line.split(b":", 1)[1].split()))
                    break
    except OSError:
        pass
    return platform.machine() + "-" + hashlib.sha256(feats).hexdigest()[:8]

_lib = None
_tried = False


def _build_lib() -> str | None:
    src = os.path.abspath(_SRC)
    if not os.path.exists(src):
        return None
    # Per-user cache dir (never a shared /tmp path — a world-writable
    # location would let another local user plant the .so we CDLL), keyed
    # on the source+flags hash plus a CPU tag so edits, flag changes, and
    # host ISA differences all rebuild rather than reuse.
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read() + " ".join(_CFLAGS).encode()).hexdigest()
    cache_dir = cache_root("native")
    out = os.path.join(cache_dir, f"libfastloader-{digest[:16]}-{_cpu_tag()}.so")
    try:
        os.makedirs(cache_dir, exist_ok=True)
    except OSError:
        return None
    if os.path.exists(out):
        return out
    tmp = out + f".build{os.getpid()}"
    cmd = ["g++", *_CFLAGS, "-o", tmp, src, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except Exception:
        return None


def get_lib():
    """Load (building if needed) the native library, or None."""
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    path = os.environ.get(_LIB_ENV) or _build_lib()
    if not path or not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.gather_normalize.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_void_p,
        ]
        lib.gather_labels.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.idx_parse_header.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.idx_parse_header.restype = ctypes.c_int
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def _checked_indices(indices: np.ndarray, n: int) -> np.ndarray:
    """Validate gather indices with numpy's semantics before handing them
    to the native kernels (which, like any C gather, do no bounds checks):
    negatives wrap from the end, anything out of range raises IndexError —
    so native and numpy-fallback paths fail identically."""
    # Bounds-check in the ORIGINAL dtype: narrowing int64 -> int32 first
    # would wrap out-of-range values into range and gather the wrong row.
    orig = np.asarray(indices)
    if orig.size:
        lo, hi = int(orig.min()), int(orig.max())
        if lo < -n or hi >= n:
            bad = lo if lo < -n else hi
            raise IndexError(
                f"index {bad} is out of bounds for axis 0 with size {n}"
            )
    idx = np.ascontiguousarray(orig, dtype=np.int32)
    if orig.size and lo < 0:
        idx = np.where(idx < 0, idx + n, idx).astype(np.int32)
    return idx


def gather_normalize(
    images: np.ndarray, indices: np.ndarray, mean: float, std: float
) -> np.ndarray | None:
    """Native gather+normalize: uint8 [N,H,W] + int32 [B] ->
    float32 [B,H,W,1].  Returns None if the native lib is unavailable or
    the images aren't a contiguous uint8 buffer (caller falls back to
    numpy, which handles any dtype/stride — and copying a whole
    non-contiguous dataset per batch would defeat the point)."""
    lib = get_lib()
    if lib is None or images.dtype != np.uint8 or not images.flags["C_CONTIGUOUS"]:
        return None
    idx = _checked_indices(indices, len(images))
    b = len(idx)
    h, w = images.shape[1], images.shape[2]
    out = np.empty((b, h, w, 1), np.float32)
    lib.gather_normalize(
        images.ctypes.data, idx.ctypes.data, b, h * w,
        ctypes.c_float(mean), ctypes.c_float(std), out.ctypes.data,
    )
    return out


def gather_labels(labels: np.ndarray, indices: np.ndarray) -> np.ndarray | None:
    lib = get_lib()
    # The native kernel reads raw uint8 labels; any other dtype takes the
    # numpy fallback (fancy indexing is already cheap there).
    if (
        lib is None
        or labels.dtype != np.uint8
        or not labels.flags["C_CONTIGUOUS"]
    ):
        return None
    idx = _checked_indices(indices, len(labels))
    out = np.empty(len(idx), np.int32)
    lib.gather_labels(labels.ctypes.data, idx.ctypes.data, len(idx), out.ctypes.data)
    return out


def parse_idx_native(raw: bytes) -> np.ndarray | None:
    """Native IDX parse; returns None when the lib is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    buf = np.frombuffer(raw, dtype=np.uint8)
    dims = np.zeros(4, np.int64)
    rc = lib.idx_parse_header(buf.ctypes.data, len(buf), dims.ctypes.data)
    if rc != 0:
        raise ValueError(f"not an MNIST IDX buffer (native parser rc={rc})")
    n, rows, cols, offset = (int(d) for d in dims)
    if rows:  # images
        return buf[offset : offset + n * rows * cols].reshape(n, rows, cols).copy()
    return buf[offset : offset + n].copy()
