"""ResilientRuntime: the bundle the trainer's step loop drives (PR 9).

One object owns the four resilience concerns so trainer.py adds exactly
two call sites — ``run_step`` (the guarded/fault-injectable step
attempt) and ``after_step`` (cadence checkpoints + preemption polling)
— instead of interleaving snapshot/retry/save/signal logic through the
epoch loop.  Constructed only when a resilience flag is set or a fault
injector is installed; the flagless path never touches this module.

Step anatomy (``run_step``)::

    [snapshot pre-step state]        guard only; donated-buffer-safe
    fault point 'step'               kill/fail/hang/nan injection
    state' = step_fn(state, ...)     donates state's buffers
    [host-sync losses]               guard/watchdog only (opt-in sync)
    classify -> healthy: return
             -> anomaly: restore snapshot, retry (budget/backoff)

The snapshot is ``jax.tree.map(jnp.copy, state)`` taken BEFORE the
donating step call: the copies are new buffers the donation cannot
alias, so "restore pre-step params exactly" is a pointer swap, not a
reconstruction — bit-exact by construction.  Retried attempts re-enter
the SAME compiled step function with the same shapes: zero new traces,
the RecompileSentinel budget is untouched.
"""

from __future__ import annotations

import os

from ..serving.faults import FaultError, fault_point
from .guard import AnomalyBudgetExhausted, LossGuard
from .preempt import EXIT_STALLED, PreemptionHandler
from .watchdog import StepWatchdog


def _default_abort(code: int) -> None:  # pragma: no cover - process exit
    os._exit(code)


class ResilientRuntime:
    """Drive one training run's resilience: guard, watchdog,
    checkpointer, preemption.

    Parameters
    ----------
    guard:
        :class:`~.guard.LossGuard` or None.  Enabling it syncs each
        step's loss to host (the guard cannot classify what it cannot
        see) — the same opt-in per-step sync ``--step-stats`` makes.
    checkpointer:
        :class:`~.checkpoint.MidEpochCheckpointer` or None.
    preemption:
        :class:`~.preempt.PreemptionHandler` or None; polled at each
        step boundary in ``after_step``.
    step_timeout_s / stall_abort:
        ``> 0`` starts a :class:`~.watchdog.StepWatchdog` (which also
        forces the per-step host sync); on stall it emits
        ``train_stall`` + ``train_stalls_total`` and, with
        ``stall_abort``, exits ``EXIT_STALLED`` via ``abort_fn``
        (injectable for tests; ``os._exit`` in production).
    prepare:
        ``device state -> host state`` hook for checkpoint writes
        (device_get + any optimizer-layout gather; trainer closure).
    steps_total / samples_total:
        Telemetry-counter bases restored from a resumed archive so the
        continued run's totals match the uninterrupted run's.
    is_chief:
        Multi-rank coordination (ISSUE 10): CADENCE decisions run
        identically on every rank (deterministic ``steps_local``
        counting — the ranks agree on the step by construction), and
        ``prepare`` runs everywhere (a ZeRO gather is a collective
        every process must enqueue), but only the chief WRITES the
        coordinated archive.  Preemption is different in kind: the
        signal lands asynchronously, so ranks can observe the flag at
        DIFFERENT step boundaries — the emergency save is best-effort
        chief-side (a chief wedged in a collective against a departed
        peer is force-exited by its grace timer instead), and the
        coherent recovery floor is the last cadence archive, from
        which resume is bit-exact by the PR-9 contract (the
        distributed chaos driver pins exactly this path).
        Single-process runs (the default True) are unchanged.
    heartbeat:
        Optional :class:`~..parallel.elastic.RankHeartbeat` — touched
        at every step boundary so the supervising launcher can tell a
        hung rank from a slow one.
    """

    def __init__(
        self,
        *,
        guard: LossGuard | None = None,
        checkpointer=None,
        preemption: PreemptionHandler | None = None,
        step_timeout_s: float = 0.0,
        stall_abort: bool = False,
        prepare=None,
        global_batch: int = 0,
        steps_total: int = 0,
        samples_total: int = 0,
        registry=None,
        sink=None,
        abort_fn=_default_abort,
        is_chief: bool = True,
        heartbeat=None,
    ) -> None:
        self.guard = guard
        self.checkpointer = checkpointer
        self.preemption = preemption
        self.is_chief = bool(is_chief)
        self.heartbeat = heartbeat
        self.prepare = prepare if prepare is not None else (lambda s: s)
        self.global_batch = int(global_batch)
        self.steps_total = int(steps_total)
        self.samples_total = int(samples_total)
        self.steps_local = 0
        self._registry = registry
        self._sink = sink
        self._stall_abort = bool(stall_abort)
        self._abort_fn = abort_fn
        self.watchdog = (
            StepWatchdog(step_timeout_s, self._on_stall)
            if step_timeout_s and step_timeout_s > 0
            else None
        )

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ResilientRuntime":
        if self.preemption is not None:
            self.preemption.install()
        if self.watchdog is not None:
            self.watchdog.suspend()  # armed per-epoch by begin_train
            self.watchdog.start()
        # NOTE: no heartbeat at start() — the first beat lands at the
        # first completed step's boundary (after_step), so rendezvous
        # and the first step's compile never count against the
        # supervisor's age clock (it ignores a missing file).
        return self

    def stop(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.preemption is not None:
            self.preemption.uninstall()

    def begin_train(self) -> None:
        """Entering a stepping region (train_one_epoch's loop)."""
        if self.watchdog is not None:
            self.watchdog.resume()

    def end_train(self) -> None:
        """Leaving the stepping region (eval/epoch boundary follows)."""
        if self.watchdog is not None:
            self.watchdog.suspend()

    # -- the guarded step ---------------------------------------------------

    def run_step(
        self, step_fn, state, x, y, w, dropout_key, lr_arr,
        *, epoch: int, batch_idx: int,
    ):
        """One resilient optimizer step; returns ``(state, losses,
        host_losses-or-None)``.  ``host_losses`` is the per-replica
        numpy loss array when this step already synced it (guard or
        watchdog active) so the caller's telemetry/log reads reuse it
        instead of paying a second sync."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        attempt = 0  # 0 = first try; >0 = retry number
        while True:
            snapshot = (
                jax.tree.map(jnp.copy, state)
                if self.guard is not None
                else None
            )
            xs = x
            try:
                fault_point("step")
            except FaultError as e:
                if getattr(e, "op", "fail") != "nan":
                    raise  # fail: a simulated crash, propagate as one
                # nan: poison this attempt's batch — the guard (if any)
                # must catch the fallout, not the injection.
                xs = x * jnp.asarray(float("nan"), dtype=x.dtype)
            lr_in = lr_arr
            if attempt > 0:
                scale = self.guard.lr_scale(attempt)
                if scale != 1.0:
                    lr_in = lr_arr * jnp.float32(scale)
            new_state, losses = step_fn(state, xs, y, w, dropout_key, lr_in)
            if self.guard is None:
                if self.watchdog is not None:
                    jax.block_until_ready(losses)
                    self.watchdog.beat()
                return new_state, losses, None
            host = np.asarray(jax.device_get(losses))  # jaxlint: disable=JL006 -- the guard's documented opt-in read: it cannot classify a loss it never sees, and the flag text owns the one-sync-per-step trade
            if self.watchdog is not None:
                self.watchdog.beat()
            kind = self.guard.classify(host)
            if kind is None:
                self.guard.record_healthy(host)
                return new_state, losses, host
            # Anomalous step: the update in new_state is poison.  Count,
            # report, restore the pre-step snapshot, and retry (or give
            # up when the budget is spent).
            attempt += 1
            self.guard.anomalies += 1
            exhausted = attempt > self.guard.retry_budget
            if self._registry is not None:
                self._registry.counter(
                    "train_anomalies_total",
                    help="anomalous training steps detected by the "
                    "LossGuard, by kind",
                    kind=kind,
                ).inc()
            if self._sink is not None:
                self._sink.emit(
                    "train_anomaly",
                    kind=kind,
                    epoch=epoch,
                    step=batch_idx,
                    attempt=attempt,
                    loss=float(np.asarray(host, np.float64).mean()),
                    action="abort" if exhausted else "retry",
                )
            if exhausted:
                raise AnomalyBudgetExhausted(
                    f"step {batch_idx} of epoch {epoch} stayed anomalous "
                    f"({kind}) through {self.guard.retry_budget} "
                    "rollback-and-retry attempt(s) with LR backoff "
                    f"{self.guard.lr_backoff}; the pre-step parameters "
                    "were restored exactly — resume from the last "
                    "checkpoint after fixing the cause (bad data shard, "
                    "too-hot schedule, failing hardware)"
                )
            state = snapshot

    # -- the step boundary --------------------------------------------------

    def after_step(self, state, *, epoch: int, batch_idx: int) -> None:
        """Bookkeeping + checkpoint/preemption work at one completed
        step's boundary.  May raise SystemExit (preemption)."""
        self.steps_local += 1
        self.steps_total += 1
        self.samples_total += self.global_batch
        cursor = batch_idx + 1
        if self.heartbeat is not None:
            self.heartbeat.beat()
        if self.preemption is not None and self.preemption.requested:
            if self.checkpointer is not None:
                # No try/except: a failed EMERGENCY save must surface —
                # exiting "cleanly" without the archive would be a lie.
                self._save(state, epoch, cursor, reason="preempt")
            if self._sink is not None:
                self._sink.emit(
                    "preempt_exit",
                    signum=self.preemption.signum,
                    exit_code=self.preemption.exit_code,
                    epoch=epoch,
                    batch_cursor=cursor,
                )
            raise SystemExit(self.preemption.exit_code)
        if self.checkpointer is not None and self.checkpointer.due(
            self.steps_local
        ):
            try:
                self._save(state, epoch, cursor, reason="periodic")
            except Exception as e:
                # A failed PERIODIC save is survivable: report it and
                # keep training — the next cadence retries with a fresh
                # temp file, and the previous archives are intact by
                # the rotation discipline.
                if self._registry is not None:
                    self._registry.counter(
                        "train_checkpoint_failures_total",
                        help="periodic checkpoint saves that failed "
                        "(training continued)",
                    ).inc()
                if self._sink is not None:
                    self._sink.emit(
                        "checkpoint_failed",
                        epoch=epoch,
                        batch_cursor=cursor,
                        error=f"{type(e).__name__}: {e}",
                    )

    def _save(self, state, epoch: int, cursor: int, reason: str) -> None:
        # A checkpoint write is a suspended region per the watchdog's
        # contract (watchdog.py): no step is in flight, so a slow
        # device_get + npz write must not read as a stalled step (with
        # --stall-abort it would kill a healthy run mid-rotation).
        if self.watchdog is not None:
            self.watchdog.suspend()
        try:
            # prepare() runs on EVERY rank (a ZeRO layout gather is a
            # collective all processes must enqueue in the same order);
            # the file write is chief-only — that is the whole
            # coordinated-save protocol, because the cadence decision
            # that got us here is deterministic and identical per rank.
            host_state = self.prepare(state)
            if self.is_chief:
                self.checkpointer.save(
                    host_state,
                    epoch_in_progress=epoch,
                    batch_cursor=cursor,
                    steps_total=self.steps_total,
                    samples_total=self.samples_total,
                    reason=reason,
                )
        finally:
            if self.watchdog is not None:
                self.watchdog.resume()

    # -- stall handling -----------------------------------------------------

    def _on_stall(self, age_s: float) -> None:
        if self._registry is not None:
            self._registry.counter(
                "train_stalls_total",
                help="steps that exceeded --step-timeout-s",
            ).inc()
        if self._sink is not None:
            self._sink.emit(
                "train_stall",
                age_s=round(age_s, 3),
                steps_total=self.steps_total,
            )
        if self._stall_abort:
            if self._sink is not None:
                self._sink.close()  # flush: the abort is immediate
            self._abort_fn(EXIT_STALLED)
