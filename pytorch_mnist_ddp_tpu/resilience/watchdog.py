"""StepWatchdog: hung-step detection for the training loop (PR 9).

A wedged collective, a dead remote-accelerator tunnel, or an injected
``hang:step`` stalls the step loop SILENTLY — the process sits at 0%
CPU forever and no exception ever fires.  The serving fleet already
solved this shape with the ReplicaSupervisor's completion-stall
detector (serving/pool.py); this is the trainer-side twin: a polling
daemon thread that measures the age of the current step window and
fires ``on_stall(age_s)`` when it exceeds ``timeout_s``.

The contract with the step loop:

- ``beat()`` after every COMPLETED step (the runtime calls it right
  after the step's host sync) re-arms the window.
- ``suspend()``/``resume()`` bracket the regions where no step is in
  flight (eval passes, epoch boundaries, checkpoint writes) so a long
  eval never reads as a stalled step.
- ``on_stall`` fires ONCE per stalled step window (not once per poll
  tick): repeated events for one hang would read as N distinct stalls
  in the telemetry.  The abort decision lives in the callback
  (runtime.py: emit ``train_stall`` + counter, optionally
  ``os._exit(EXIT_STALLED)``) — the watchdog only detects.

Like the supervisor, the watchdog needs a real completion signal to
watch: enabling it makes the runtime block on each step's output (the
same one-sync-per-step trade ``--step-stats`` and ``--telemetry-dir``
already make, documented on the flag).  Without a per-step sync an
async dispatch queue never hangs on the host side and a watchdog would
be a placebo.

stdlib-only and jax-free: tests drive it with fake clocks/sleeps.
"""

from __future__ import annotations

import threading
import time

from ..analysis.lockwatch import make_lock


class StepWatchdog:
    def __init__(
        self,
        timeout_s: float,
        on_stall,
        poll_s: float | None = None,
    ) -> None:
        if timeout_s <= 0:
            raise ValueError("timeout_s must be > 0")
        self.timeout_s = float(timeout_s)
        self.on_stall = on_stall
        self.poll_s = float(poll_s) if poll_s else max(timeout_s / 4.0, 0.01)
        self._lock = make_lock("watchdog.heartbeat")
        self._window_start: float | None = None  # None = suspended
        self._beats = 0
        self._reported_window = -1  # beat index already reported stalled
        self.stalls = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- step-loop surface --------------------------------------------------

    def beat(self) -> None:
        """A step completed; re-arm the stall window."""
        with self._lock:
            self._beats += 1
            self._window_start = time.monotonic()

    def resume(self) -> None:
        """Enter a stepping region (epoch start): arm the window."""
        with self._lock:
            self._window_start = time.monotonic()

    def suspend(self) -> None:
        """Leave the stepping region (eval, epoch end): stop watching."""
        with self._lock:
            self._window_start = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "StepWatchdog":
        self._thread = threading.Thread(
            target=self._watch, name="train-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    # -- detector -----------------------------------------------------------

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                start = self._window_start
                beats = self._beats
                already = self._reported_window == beats
            if start is None or already:
                continue
            age = time.monotonic() - start
            if age <= self.timeout_s:
                continue
            with self._lock:
                # Re-check under the lock: a beat may have landed while
                # the age was computed, and that window is healthy.
                if self._beats != beats or self._window_start is None:
                    continue
                self._reported_window = beats
                self.stalls += 1
            try:
                self.on_stall(age)
            except Exception:
                # The detector must outlive a throwing callback: a
                # broken telemetry sink must not disable stall
                # detection for the rest of the run.
                pass
