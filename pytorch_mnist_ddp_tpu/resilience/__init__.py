"""resilience: the training runtime's survive-anything layer (PR 9,
docs/ROBUSTNESS.md trainer section).

PR 8 taught the SERVING fleet to detect, eject, and heal dead replicas;
this package gives the TRAINER the same discipline.  The reference
paper's only durability story is a final ``torch.save`` after the last
epoch — a preemption, a hung step, or one NaN loss loses the whole run.
Here:

- :mod:`.checkpoint` — :class:`MidEpochCheckpointer`: periodic
  (``--checkpoint-every-steps``) and on-demand full-state archives that
  capture the EXACT mid-epoch position (epoch in progress, batch
  cursor, data-order seed, step counter, telemetry counters) with a
  rotating ``last``/``last-1`` publish scheme, so a kill at ANY point —
  including mid-save — leaves a loadable archive and ``--resume-state``
  continues bit-identically to the uninterrupted run.
- :mod:`.guard` — :class:`LossGuard`: classifies each step's
  already-synced host loss (NaN/Inf, spike-over-EWMA); the runtime
  restores the pre-step state from a donated-buffer-safe snapshot and
  retries — first at the original LR (a transient fault heals with ZERO
  numeric divergence), then with LR backoff — aborting with one clear
  diagnostic (:class:`AnomalyBudgetExhausted`) when the budget runs out.
- :mod:`.watchdog` — :class:`StepWatchdog`: a supervisor-shaped thread
  (serving/pool.py lineage) that fires ``train_stall`` when a step
  exceeds ``--step-timeout-s``, optionally aborting the process.
- :mod:`.preempt` — :class:`PreemptionHandler`: SIGTERM/SIGINT land an
  emergency checkpoint at the next step boundary and exit with the
  conventional ``128+signum`` code, under a bounded grace timer.
- :mod:`.runtime` — :class:`ResilientRuntime`: the bundle the trainer
  drives; also hosts the ``step`` fault-injection site
  (serving/faults.py grammar: ``kill:step:after=7``, ``nan:step:...``)
  so ``tools/train_chaos.py`` can prove all of the above
  deterministically.

Everything is opt-in: with no resilience flag and no installed fault
injector the trainer's step loop does not construct (or consult) any of
this, and flagless stdout stays byte-identical to the reference.
"""

from __future__ import annotations

from .checkpoint import MidEpochCheckpointer
from .guard import EXIT_ANOMALY, AnomalyBudgetExhausted, LossGuard
from .preempt import EXIT_STALLED, PreemptionHandler
from .runtime import ResilientRuntime
from .watchdog import StepWatchdog

__all__ = [
    "AnomalyBudgetExhausted",
    "EXIT_ANOMALY",
    "EXIT_STALLED",
    "LossGuard",
    "MidEpochCheckpointer",
    "PreemptionHandler",
    "ResilientRuntime",
    "StepWatchdog",
]
