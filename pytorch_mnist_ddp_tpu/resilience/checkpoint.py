"""MidEpochCheckpointer: periodic + emergency full-state archives with a
rotating last/last-1 publish scheme (PR 9).

``--save-state`` (PR 5 era) wrote ONE archive at end of run; a kill at
step 4 of epoch 7 lost seven epochs.  This class generalizes the same
archive (utils/checkpoint.save_train_state) to arbitrary step
boundaries by recording the full mid-epoch position in ``meta.*``
extras:

- ``epoch_in_progress`` / ``batch_cursor`` — which epoch the run was
  inside and how many of its batches were consumed, so the resumed run
  replays the EXACT remaining batches (data/loader.py ``start_batch``).
- ``seed`` / ``global_batch`` — the data-order parameters the cursor is
  only meaningful under; resume validates them instead of silently
  training on a different permutation.
- ``steps_total`` / ``samples_total`` — telemetry counters, so a
  resumed run's exposition continues where the killed run's numbers
  actually were.

The optimizer state, params, BN stats, and the RNG chain (derivable
from seed + ``state.step``: utils/rng.py folds every per-step key from
those alone) all travel in the base archive already.

Publish discipline — the part a kill is aimed at::

    write archive to <path>.new      (atomic in itself: mkstemp+fsync)
    rotate  <path>      -> <path>.prev      (if a previous publish exists)
    [fault point 'ckpt_save']
    publish <path>.new  -> <path>

A kill during the write leaves the previous <path> (and <path>.prev)
untouched; a kill in the rotate->publish window leaves no <path> but a
complete <path>.prev — and ``--resume-state`` falls back to it
(utils/checkpoint.load_latest_train_state).  At every instant at least
one complete archive is loadable; the chaos harness kills inside the
window (``kill:ckpt_save``) to prove it.

A FAILED periodic save (disk full, injected ``fail:ckpt_save``) is
reported and survived — training continues and the next cadence tries
again; only the PREEMPTION save propagates its failure, because exiting
"cleanly" without the emergency archive would be a lie.
"""

from __future__ import annotations

import os
import time

from ..serving.faults import fault_point
from ..utils.checkpoint import PREV_SUFFIX, save_train_state


class MidEpochCheckpointer:
    """Write rotated mid-epoch archives for one training run.

    Parameters
    ----------
    path:
        The ``--save-state`` target; rotations live beside it at
        ``path + ".prev"`` and the in-flight write at ``path + ".new"``.
    every_steps:
        Cadence in optimizer steps (``due()``); ``0`` disables periodic
        saves (emergency saves still work).
    seed / global_batch:
        Data-order parameters recorded into (and validated against)
        every mid-epoch archive.
    world_size:
        The saving run's data-parallel degree — the last leg of the
        world fingerprint (world_size / seed / global_batch / step)
        stamped into mid-epoch archives (ISSUE 10).  Resume validates
        it: a mismatch is refused with a pointed error unless the run
        explicitly opts into re-sharding (``--resume-reshard`` —
        bit-compatible under the sampler contract when seed and
        global_batch match).  0 (the default) omits the stamp, keeping
        pre-elastic unit archives byte-stable.
    registry / sink:
        Optional obs surfaces: ``train_checkpoints_total{reason=}``,
        ``checkpoint_write_seconds``, and per-save ``checkpoint``
        events.
    """

    def __init__(
        self,
        path: str,
        every_steps: int = 0,
        seed: int = 0,
        global_batch: int = 0,
        world_size: int = 0,
        registry=None,
        sink=None,
    ) -> None:
        self.path = path
        self.prev_path = path + PREV_SUFFIX
        self.tmp_path = path + ".new"
        self.every_steps = int(every_steps)
        self.seed = int(seed)
        self.global_batch = int(global_batch)
        self.world_size = int(world_size)
        self._registry = registry
        self._sink = sink
        self.saves = 0
        self._write_hist = (
            registry.histogram(
                "checkpoint_write_seconds",
                help="wall time of one mid-epoch archive write+publish",
            )
            if registry is not None
            else None
        )

    def due(self, steps_done: int) -> bool:
        """True when ``steps_done`` (steps completed THIS run) hits the
        cadence.  The cadence guard jaxlint JL014 looks for lives here —
        the step loop calls ``due()`` every step, the O(full-state
        device_get + disk write) cost only on cadence steps."""
        return self.every_steps > 0 and steps_done % self.every_steps == 0

    def save(
        self,
        host_state,
        *,
        epoch_in_progress: int,
        batch_cursor: int,
        steps_total: int,
        samples_total: int,
        reason: str = "periodic",
    ) -> float:
        """Write + rotate + publish one mid-epoch archive; returns the
        wall seconds spent.  ``host_state`` is already on host (the
        runtime's ``prepare`` hook did the device_get and any layout
        gather) — this method is pure file discipline."""
        t0 = time.perf_counter()
        extras = {
            "epoch_in_progress": epoch_in_progress,
            "batch_cursor": batch_cursor,
            "seed": self.seed,
            "global_batch": self.global_batch,
            "steps_total": steps_total,
            "samples_total": samples_total,
        }
        if self.world_size > 0:
            # The world fingerprint's last leg (ISSUE 10): which
            # data-parallel degree this mid-epoch position was cut at.
            extras["world_size"] = self.world_size
        save_train_state(
            host_state,
            self.tmp_path,
            epoch=epoch_in_progress - 1,
            extras=extras,
        )
        if os.path.exists(self.path):
            os.replace(self.path, self.prev_path)
        # The chaos harness's mid-save kill point: a death here leaves
        # no <path>, only the complete rotation at <path>.prev.
        fault_point("ckpt_save")
        os.replace(self.tmp_path, self.path)
        duration = time.perf_counter() - t0
        self.saves += 1
        if self._registry is not None:
            self._registry.counter(
                "train_checkpoints_total",
                help="mid-epoch checkpoint archives published",
                reason=reason,
            ).inc()
        if self._write_hist is not None:
            self._write_hist.observe(duration)
        if self._sink is not None:
            self._sink.emit(
                "checkpoint",
                reason=reason,
                epoch=epoch_in_progress,
                batch_cursor=batch_cursor,
                steps_total=steps_total,
                duration_s=round(duration, 6),
                path=self.path,
            )
        return duration
