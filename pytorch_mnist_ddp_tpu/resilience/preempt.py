"""PreemptionHandler: SIGTERM/SIGINT -> emergency checkpoint -> clean
exit (PR 9).

Preemptible capacity (spot VMs, borrowed TPU slices, k8s evictions)
delivers SIGTERM and a grace window; the default Python behavior —
KeyboardInterrupt mid-step, or straight death — loses everything since
the last periodic checkpoint.  The handler converts the signal into a
FLAG the step loop polls at each step boundary (signal handlers must
not touch device state or take locks; the step boundary is the one
place a consistent snapshot exists), where the runtime writes an
emergency mid-epoch archive and exits with the conventional
``128 + signum`` code (143 for SIGTERM, 130 for SIGINT) so supervisors
see the same code an unhandled signal would have produced — but with
the work saved.

Bounded grace: the first signal starts a daemon timer; if the clean
path has not finished within ``grace_s`` (a wedged step, a slow
filesystem), the timer force-exits with the same code — a preemption
deadline missed because we were politely flushing is still a killed
run, and lying about it by blocking past the platform's grace window
just gets the process SIGKILLed with the checkpoint half-written.  A
second signal force-exits immediately (the operator pressing Ctrl-C
twice means NOW).

Install/uninstall is explicit and restores the previous handlers, so
in-process tests (and library embedders) keep their signal semantics.
Only the main thread can install (CPython restriction); elsewhere the
handler degrades to never-requested.
"""

from __future__ import annotations

import os
import signal
import threading

# sysexits.h EX_TEMPFAIL: the step watchdog aborted a wedged run
# (runtime.py uses it for --stall-abort; grouped here with the other
# process-exit conventions).
EXIT_STALLED = 75

_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class PreemptionHandler:
    def __init__(self, grace_s: float = 30.0) -> None:
        self.grace_s = float(grace_s)
        self.requested = False
        self.signum: int | None = None
        self._prev: dict[int, object] = {}
        self._timer: threading.Timer | None = None
        self._installed = False

    @property
    def exit_code(self) -> int:
        return 128 + (self.signum or signal.SIGTERM)

    # -- signal side --------------------------------------------------------

    def _handle(self, signum, frame) -> None:
        if self.requested:
            # Second signal: the grace period is over as far as the
            # sender is concerned.  Exit NOW, same code.
            os._exit(128 + signum)
        self.requested = True
        self.signum = signum
        if self.grace_s > 0:
            self._timer = threading.Timer(
                self.grace_s, os._exit, args=(128 + signum,)
            )
            self._timer.daemon = True
            self._timer.start()

    # -- lifecycle ----------------------------------------------------------

    def install(self) -> "PreemptionHandler":
        if threading.current_thread() is not threading.main_thread():
            return self  # signal.signal is main-thread-only; degrade
        for sig in _SIGNALS:
            self._prev[sig] = signal.signal(sig, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if self._installed:
            for sig, prev in self._prev.items():
                signal.signal(sig, prev)
            self._prev.clear()
            self._installed = False
        if self._timer is not None:
            # An in-process caller (tests, notebook embedding) survives
            # the "preemption": the force-exit timer must die with the
            # handler or it would kill the HOST process grace_s later.
            self._timer.cancel()
            self._timer = None
