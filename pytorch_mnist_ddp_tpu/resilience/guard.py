"""LossGuard: per-step anomaly detection + rollback policy (PR 9).

The hazard class: one poisoned optimizer step — NaN/Inf from a bad
batch, a hardware glitch, or a genuine divergence spike — silently
destroys every parameter, and with donated input buffers there is no
going back.  The guard classifies each step's loss on the HOST value
that the step loop already synced (no new device round-trip beyond the
one the guard's opt-in read performs — the flagless path never pays it),
and the runtime (runtime.py) rolls back to a pre-step snapshot and
retries:

- retry 1 runs at the ORIGINAL learning rate, so a transient anomaly
  (the injected-NaN chaos case, a flipped bit, a corrupt shard) heals
  with ZERO numeric divergence — the retried step is bit-identical to
  the step an unfaulted run would have taken.  This is the property the
  acceptance test pins: final params equal to the clean run's, not
  merely "accuracy about the same".
- retries 2..budget back the LR off multiplicatively
  (``lr * backoff^(attempt-1)``): a REPEATED anomaly on the same batch
  at the same params is a too-hot-step signal, and a smaller step is
  the only lever that changes the outcome of a deterministic retry.
- budget exhausted -> :class:`AnomalyBudgetExhausted`, which the CLIs
  turn into ONE clear stderr diagnostic and a non-zero exit
  (:data:`EXIT_ANOMALY`) instead of an unbounded skip-spiral that
  "finishes" training on garbage.

Spike detection is an EWMA gate: loss > ``spike_factor`` x the running
mean of accepted losses.  Only ACCEPTED (healthy) steps feed the EWMA —
an anomalous loss must not drag the baseline toward itself.

stdlib + numpy only; the device-side snapshot/restore lives in
runtime.py so this class is unit-testable with plain floats.
"""

from __future__ import annotations

import numpy as np

# sysexits.h EX_SOFTWARE: the run ABORTED on an unrecoverable training
# anomaly (budget exhausted), as opposed to crashing by accident.
EXIT_ANOMALY = 70


class AnomalyBudgetExhausted(RuntimeError):
    """Raised when a step stays anomalous through every allowed retry.

    The CLIs catch exactly this type and print its message as the run's
    single diagnostic (non-zero exit EXIT_ANOMALY); everything else
    still surfaces as a traceback — an unknown crash must not be dressed
    up as a handled anomaly."""


class LossGuard:
    """Anomaly classifier + retry/backoff policy for one training run.

    Parameters
    ----------
    spike_factor:
        A loss above ``spike_factor * ewma(accepted losses)`` counts as
        a spike anomaly.  ``0`` disables spike detection (NaN/Inf only).
    retry_budget:
        Retries allowed per step before aborting.  The budget is
        PER-STEP: a healthy step resets nothing because nothing carries
        over — each step's attempts count from zero.
    lr_backoff:
        Multiplicative LR factor applied from the second retry on
        (``lr_scale(1) == 1.0`` — see the module docstring for why the
        first retry must not perturb the numerics).
    ewma_alpha:
        Smoothing of the accepted-loss baseline.
    """

    def __init__(
        self,
        spike_factor: float = 10.0,
        retry_budget: int = 3,
        lr_backoff: float = 0.5,
        ewma_alpha: float = 0.1,
    ) -> None:
        if retry_budget < 1:
            raise ValueError("retry_budget must be >= 1")
        if not 0.0 < lr_backoff <= 1.0:
            raise ValueError("lr_backoff must be in (0, 1]")
        self.spike_factor = float(spike_factor)
        self.retry_budget = int(retry_budget)
        self.lr_backoff = float(lr_backoff)
        self.ewma_alpha = float(ewma_alpha)
        self._ewma: float | None = None
        self.anomalies = 0  # total classified anomalies (all kinds)

    # -- classification -----------------------------------------------------

    def classify(self, losses) -> str | None:
        """``None`` for a healthy step, else the anomaly kind.

        ``losses`` is the step's per-replica host loss array (any shape;
        a scalar works too).  NaN/Inf on ANY replica is an anomaly —
        the pmean'd gradients already poisoned every replica's params
        even if only one shard's local loss shows it."""
        arr = np.asarray(losses, dtype=np.float64)
        if not bool(np.isfinite(arr).all()):
            return "nan"
        if self.spike_factor > 0 and self._ewma is not None:
            if float(arr.mean()) > self.spike_factor * max(self._ewma, 1e-12):
                return "spike"
        return None

    def record_healthy(self, losses) -> None:
        """Feed an ACCEPTED step's loss into the spike baseline."""
        loss = float(np.asarray(losses, dtype=np.float64).mean())
        if self._ewma is None:
            self._ewma = loss
        else:
            self._ewma += self.ewma_alpha * (loss - self._ewma)

    # -- retry policy -------------------------------------------------------

    def lr_scale(self, attempt: int) -> float:
        """LR multiplier for retry number ``attempt`` (1-based).

        1.0 for the first retry (transparent heal of a transient), then
        ``lr_backoff ** (attempt - 1)`` — the deterministic-spike
        escape hatch."""
        if attempt <= 1:
            return 1.0
        return self.lr_backoff ** (attempt - 1)

    @property
    def ewma(self) -> float | None:
        return self._ewma
