"""Power-of-two shape buckets: the serving layer's retrace firewall.

A jitted forward compiles one executable per distinct input shape.  Real
request traffic arrives at every batch size from 1 to whatever the
micro-batcher coalesced, so dispatching raw request shapes would compile
continuously — the exact hazard class jaxlint JL004/JL007 and the
RecompileSentinel exist for, paid at tens of seconds per retrace on TPU.
The policy here is the standard fix: a small fixed ladder of power-of-two
batch sizes, every dispatch padded UP to the nearest rung and the results
sliced back down.  Power-of-two spacing bounds padding waste below 50%
in the worst case (amortized far lower under coalescing, since the
batcher fills toward the max bucket) while keeping the number of warmed
executables logarithmic in the max batch.

Pure host-side numpy; no jax import, so bucket policy is unit-testable
without device init.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..analysis.lockwatch import make_lock

# Default ladder ceiling: 128 matches the training eval batch order of
# magnitude; ~8 executables from bucket 1, 5 from bucket 8.
DEFAULT_MAX_BUCKET = 128


def pow2_buckets(
    min_bucket: int = 1, max_bucket: int = DEFAULT_MAX_BUCKET
) -> tuple[int, ...]:
    """The power-of-two ladder covering [min_bucket, max_bucket].

    ``min_bucket`` rounds UP to a power of two (serving on an N-way data
    mesh needs every bucket divisible by N, so callers pass N here).
    """
    if min_bucket < 1 or max_bucket < min_bucket:
        raise ValueError(
            f"need 1 <= min_bucket <= max_bucket, got "
            f"{min_bucket}..{max_bucket}"
        )
    b = 1
    while b < min_bucket:
        b *= 2
    out = []
    while b <= max_bucket:
        out.append(b)
        b *= 2
    if not out:
        raise ValueError(
            f"no power of two in [{min_bucket}, {max_bucket}]"
        )
    return tuple(out)


def validate_buckets(buckets: Sequence[int], n_shards: int = 1) -> tuple[int, ...]:
    """Sorted, deduplicated, sanity-checked bucket ladder.

    Every bucket must be positive, a power of two (the policy this module
    is named for — a free-form ladder silently reintroduces unbounded
    executable counts), and divisible by the data-axis size so padded
    batches shard evenly over the mesh.
    """
    out = sorted(set(int(b) for b in buckets))
    if not out:
        raise ValueError("empty bucket list")
    for b in out:
        if b < 1 or (b & (b - 1)):
            raise ValueError(f"bucket {b} is not a positive power of two")
        if b % n_shards:
            raise ValueError(
                f"bucket {b} not divisible by the {n_shards}-way data axis"
            )
    return tuple(out)


def packed_capacities(
    max_bucket: int, n_shards: int = 1, rungs: int = 1
) -> tuple[int, ...]:
    """The rows-capacity ladder for packed batch formation.

    Packed mode replaces the full pow2 ladder with one (default) or two
    rows-capacities: requests are concatenated into a single dense rows
    buffer, so per-batch shape variety — the reason the ladder had a rung
    per pow2 — disappears, and with it all but one (or two) executables,
    warmup traces, and AOT entries.  ``rungs=2`` adds a half-capacity
    rung for deployments where a lone small batch at the top capacity
    would be worse than a second executable.

    The top capacity is ``max_bucket`` rounded UP to a power of two (and
    to shard divisibility), so a packed engine accepts exactly the
    request sizes its bucketed twin did.  Idempotent: feeding a ladder
    that is already packed returns the same capacities.
    """
    if max_bucket < 1:
        raise ValueError(f"need max_bucket >= 1, got {max_bucket}")
    if rungs not in (1, 2):
        raise ValueError(f"packed ladders have 1 or 2 rungs, got {rungs}")
    top = 1
    while top < max(max_bucket, n_shards):
        top *= 2
    if top % n_shards:
        raise ValueError(
            f"capacity {top} not divisible by the {n_shards}-way data axis"
        )
    if rungs == 2 and top // 2 >= max(1, n_shards) and top // 2 % n_shards == 0:
        return (top // 2, top)
    return (top,)


def segment_ids(lengths: Sequence[int], capacity: int) -> np.ndarray:
    """The segment-id vector for one packed rows buffer.

    ``int32[capacity]`` mapping each row to the index of the request
    (segment) that owns it, in staging order; padding rows in the tail
    get ``-1`` so the device-side mask can zero them deterministically.
    Host numpy only — this is the single source of truth for the packed
    layout, shared by the batcher (staging), the engine (warmup example
    args), and the tests that pin unpacking bit-identity.
    """
    total = 0
    ids = np.full(capacity, -1, np.int32)
    for seg, n in enumerate(lengths):
        if n < 1:
            raise ValueError(f"segment {seg} has non-positive length {n}")
        if total + n > capacity:
            raise ValueError(
                f"segments total {total + n} overflow capacity {capacity}"
            )
        ids[total : total + n] = seg
        total += n
    return ids


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """The smallest bucket >= n (the shape actually dispatched).

    ``n`` larger than the top bucket is the caller's error — the
    micro-batcher caps coalescing at the top bucket, and the engine
    chunks oversized direct calls before asking for a bucket.
    """
    if n < 1:
        raise ValueError(f"batch size must be >= 1, got {n}")
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"batch of {n} exceeds the top bucket {buckets[-1]}")


def pad_to_bucket(x: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad rows so ``len(x) == bucket`` (jit sees only bucket shapes).

    Rows are per-sample independent through the whole forward (conv,
    dense, eval-mode BN all act within a sample), so padding rows cannot
    perturb real rows — the same invariant the training loader's
    final-partial-batch padding relies on.
    """
    n = len(x)
    if n > bucket:
        raise ValueError(f"batch of {n} does not fit bucket {bucket}")
    if n == bucket:
        return x
    pad = np.zeros((bucket - n, *x.shape[1:]), x.dtype)
    return np.concatenate([x, pad])


class StagingPool:
    """Preallocated per-bucket pad targets, recycled through a free list.

    :func:`pad_to_bucket` allocates a fresh padded array per dispatch —
    fine for a script, garbage-per-request on the serving hot path.  The
    pool allocates every buffer once up front (``slots`` per bucket) and
    steady-state staging is then pure ``memcpy``: copy the live rows in,
    zero the tail, dispatch, :meth:`release` when the device has consumed
    the batch.  ``slots`` must cover the maximum number of batches
    simultaneously staged-or-in-flight (the batcher sizes it to its
    in-flight window + 1 so padding batch N+1 overlaps batch N's
    compute); :meth:`acquire` blocks if a caller overruns that bound
    rather than silently allocating.

    A buffer is only safe to release once its dispatch's RESULT has been
    read back (D2H completing proves the compute consumed the input) —
    releasing right after the launch would let the next batch overwrite
    rows a backend that aliases host memory may still be reading.
    """

    def __init__(
        self,
        buckets: Sequence[int],
        item_shape: Sequence[int],
        slots: int = 1,
        dtype=np.float32,
    ):
        if slots < 1:
            raise ValueError(f"need >= 1 staging slot per bucket, got {slots}")
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.slots = slots
        self._cond = make_lock("buckets.staging", kind="condition")
        self._free: dict[int, list[np.ndarray]] = {
            b: [np.zeros((b, *item_shape), dtype) for _ in range(slots)]
            for b in self.buckets
        }

    def acquire(self, bucket: int) -> np.ndarray:
        """A free ``[bucket, *item_shape]`` buffer (blocks until one is
        released; the batcher's in-flight bound makes the wait momentary)."""
        with self._cond:
            free = self._free[bucket]  # KeyError = unknown bucket, loudly
            while not free:
                self._cond.wait()
            return free.pop()

    def release(self, buf: np.ndarray, bucket: int) -> None:
        with self._cond:
            self._free[bucket].append(buf)
            self._cond.notify()

    def stage(self, parts: Sequence[np.ndarray]) -> tuple[np.ndarray, int]:
        """Copy ``parts`` row-blocks into one bucket-shaped buffer.

        Returns ``(buffer, bucket)`` with the live rows at the front and
        a zeroed tail — exactly :func:`pad_to_bucket` of the concatenated
        parts, without the per-call concatenate + pad allocations.  The
        caller owns the buffer until :meth:`release`.
        """
        total = sum(len(p) for p in parts)
        bucket = bucket_for(total, self.buckets)
        buf = self.acquire(bucket)
        offset = 0
        for p in parts:
            buf[offset : offset + len(p)] = p
            offset += len(p)
        if offset < bucket:
            buf[offset:] = 0.0
        return buf, bucket
