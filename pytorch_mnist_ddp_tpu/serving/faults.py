"""Deterministic fault injection for the serving AND training stacks
(docs/ROBUSTNESS.md).

A fleet serving real traffic must DETECT, eject, and heal replicas that
throw, hang, or die under live load — and the only honest way to claim
that is to inject those faults on purpose and assert the recovery, not
narrate it.  This module is the injection surface: named **fault
points** compiled into the serving hot path (batcher dispatch and
completion, pool warmup, AOT deserialization) that are dormant — one
module-global ``None`` check — until a test or the loadgen's chaos mode
installs a :class:`FaultInjector`.

PR 9 extends the same grammar to the training runtime
(``pytorch_mnist_ddp_tpu/resilience``): trainer sites ``step`` (fired
once per optimizer-step attempt), ``data_next`` (fired per host-batch
assembly in ``data/loader.py``), and ``ckpt_save`` (fired inside the
mid-epoch checkpointer's rotate→publish window), plus two ops the
trainer chaos harness needs — ``kill`` (an uncatchable simulated
SIGKILL: ``os._exit(137)`` at the fault point, which is how
``tools/train_chaos.py`` dies at a DETERMINISTIC step instead of racing
a timer against the step loop) and ``nan`` (raises a
:class:`FaultError` tagged ``op="nan"`` that the trainer interprets by
poisoning that step's input batch with NaNs — the injection the
LossGuard's rollback is proven against; ``step``-site only, because
nothing else knows how to poison).

Determinism is the design constraint: the chaos acceptance tests must
produce the same fault sequence on every run, so triggers are
**event-counted** (``after=``/``count=``) rather than timed by default,
and the only randomness (``p=``) draws from a seeded RNG.  Wall-clock
triggers (``at=`` seconds since :meth:`FaultInjector.start`) exist for
the loadgen's operator-facing schedules ("kill replica 2 at t=5s") and
are deliberately absent from the pinned tests.

Spec grammar (one or more clauses joined by ``;``)::

    clause  := op ':' site [ ':' replica ] [ ':' params ]
    op      := 'fail' | 'hang' | 'kill' | 'nan'
    site    := 'launch' | 'complete' | 'warmup' | 'aot_load'
             | 'step' | 'data_next' | 'ckpt_save'
    replica := a replica name ('r0', ...); '*' or omitted = any replica
               (rejected for 'aot_load': the store is pool-shared, so a
               replica-scoped clause could never fire; the trainer sites
               fire unlabeled — there is one trainer)
    params  := key '=' value (',' key '=' value)*

    count=N | count=inf   fire on the next N matching events (default 1)
    after=K               skip the first K matching events (default 0)
    at=T                  arm only once T seconds have passed since start()
    for=S                 hang duration in seconds ('hang' op; default 0.5)
    p=X                   fire each armed event with probability X (seeded)
    rank=R                trainer sites only: fire only in the process
                          whose distributed RANK is R (the injector reads
                          its own rank from the launcher's env; the
                          distributed chaos harness kills ONE rank of a
                          real gang this way)

Examples::

    fail:launch:r1:count=6        # r1's next 6 dispatches raise (a kill)
    hang:complete:r0:for=2        # r0's next completion read stalls 2s
    fail:aot_load:count=1         # first AOT deserialize fails -> fallback
    fail:warmup:r2                # r2's warmup raises once
    fail:launch:r3:at=5,count=inf # kill r3 five seconds into the run
    kill:step:after=7             # preempt the trainer before step 8
    kill:ckpt_save:after=1        # die inside the 2nd checkpoint rotation
    nan:step:after=5              # poison step 6's batch (LossGuard test)
    fail:data_next:count=2        # two transient input-pipeline faults
    kill:step:rank=1:after=4      # kill RANK 1 of the gang before its
                                  # 5th step (elastic supervisor test)

The ``fail`` op raises :class:`FaultError` at the fault point — the
supervisor (serving/pool.py) must treat it exactly like any engine
exception, which is the point.  The ``hang`` op blocks the calling
thread for ``for=`` seconds (interruptibly: :func:`uninstall` releases
stuck sleepers), which is how the completion-stall detector is proven.
The ``kill`` op exits the process immediately (``os._exit(137)``,
the SIGKILL convention) — no finally blocks, no atexit, exactly what a
preemption looks like to the checkpoint files.  The ``nan`` op raises a
:class:`FaultError` whose ``op`` attribute is ``"nan"``; the trainer's
resilient runtime translates it into a NaN-poisoned input batch, every
other site treats it as a plain failure.

Off by default: ``fault_point()`` returns after a single global ``is
None`` test when nothing is installed, so production paths pay one
branch.  stdlib-only, no jax import — the injector is testable at
interactive speed and importable from the jax-free compile layer.
"""

from __future__ import annotations

import math
import random
import threading
import time
from contextlib import contextmanager

# Trainer sites (resilience/, data/loader.py): one step-attempt event
# per optimizer step, one data_next event per host-batch assembly, one
# ckpt_save event inside each checkpoint rotation.  They always fire
# unlabeled — there is one trainer — so replica-scoped clauses are
# rejected at parse time (same vacuous-green guard as aot_load).
TRAINER_SITES = ("step", "data_next", "ckpt_save")

SITES = ("launch", "complete", "warmup", "aot_load") + TRAINER_SITES
OPS = ("fail", "hang", "kill", "nan")


class FaultError(RuntimeError):
    """An injected failure.  Deliberately a plain RuntimeError subclass:
    the serving stack must recover from it through the SAME paths it
    recovers from real engine failures with — any special-casing of
    this type in non-test code would make the chaos harness a liar.

    ``op``/``site`` carry the firing clause's coordinates.  The ONE
    sanctioned read of them outside tests is the trainer's ``nan``
    translation (resilience/runtime.py): a ``nan`` fault is not a
    failure to recover from but an instruction to poison the step's
    numerics, so the runtime must be able to tell it from ``fail``."""

    def __init__(self, message: str, op: str = "fail", site: str = ""):
        super().__init__(message)
        self.op = op
        self.site = site


class FaultSpec:
    """One parsed clause: where it fires, when, how often, what it does."""

    __slots__ = (
        "op", "site", "replica", "rank", "count", "after", "at_s", "hang_s",
        "p", "fired", "source",
    )

    def __init__(self, op, site, replica, count, after, at_s, hang_s, p,
                 source, rank=None):
        self.op = op
        self.site = site
        self.replica = replica
        self.rank = rank
        self.count = count
        self.after = after
        self.at_s = at_s
        self.hang_s = hang_s
        self.p = p
        self.fired = 0
        self.source = source

    @classmethod
    def parse(cls, clause: str) -> "FaultSpec":
        parts = [p.strip() for p in clause.strip().split(":")]
        if len(parts) < 2:
            raise ValueError(
                f"fault clause {clause!r} needs at least op:site "
                f"(grammar: op:site[:replica][:k=v,...])"
            )
        op, site = parts[0], parts[1]
        if op not in OPS:
            raise ValueError(f"unknown fault op {op!r}; have {OPS}")
        if site not in SITES:
            raise ValueError(f"unknown fault site {site!r}; have {SITES}")
        replica: str | None = None
        params: dict[str, str] = {}
        for part in parts[2:]:
            if "=" in part:
                for pair in part.split(","):
                    key, _, value = pair.partition("=")
                    key, value = key.strip(), value.strip()
                    if key not in ("count", "after", "at", "for", "p", "rank"):
                        raise ValueError(
                            f"unknown fault param {key!r} in {clause!r}; "
                            "have count/after/at/for/p/rank"
                        )
                    params[key] = value
            elif part and part != "*":
                replica = part
        count = (
            math.inf if params.get("count") == "inf"
            else float(params.get("count", 1))
        )
        if count < 1:
            raise ValueError(f"count must be >= 1 in {clause!r}")
        if op == "nan" and site != "step":
            # Only the trainer's step attempt knows how to poison a
            # batch; a nan clause anywhere else would be armed but
            # uninterpretable — a vacuous green chaos run.
            raise ValueError(
                f"op 'nan' is only meaningful at site 'step' in {clause!r}"
            )
        if site == "aot_load" and replica is not None:
            # The AOT store is SHARED across replicas (one ExecutableStore
            # per pool), so its fault point fires unlabeled; accepting a
            # replica-scoped clause here would arm one that can never
            # trigger — a vacuous green chaos run.
            raise ValueError(
                f"aot_load cannot be replica-scoped in {clause!r}: the "
                "executable store is shared across the pool"
            )
        if site in TRAINER_SITES and replica is not None:
            # Same vacuous-green guard: the trainer sites fire with
            # replica=None, so a labeled clause could never match.
            raise ValueError(
                f"{site} cannot be replica-scoped in {clause!r}: trainer "
                "sites fire unlabeled (there is one trainer per rank; "
                "scope to a gang member with rank=R instead)"
            )
        rank = int(params["rank"]) if "rank" in params else None
        if rank is not None and rank < 0:
            raise ValueError(f"rank must be >= 0 in {clause!r}")
        if rank is not None and site not in TRAINER_SITES:
            # Serving processes are single-rank (replica scoping is their
            # addressing); a rank-scoped serving clause could never
            # match — the vacuous-green guard again.
            raise ValueError(
                f"rank= only scopes trainer sites in {clause!r}: serving "
                "clauses address replicas (r0, r1, ...), not gang ranks"
            )
        return cls(
            op=op,
            site=site,
            replica=replica,
            rank=rank,
            count=count,
            after=int(params.get("after", 0)),
            at_s=float(params["at"]) if "at" in params else None,
            hang_s=float(params.get("for", 0.5)),
            p=float(params.get("p", 1.0)),
            source=clause.strip(),
        )

    def __repr__(self):
        return f"FaultSpec({self.source!r}, fired={self.fired})"


class FaultInjector:
    """A parsed schedule of :class:`FaultSpec` clauses plus the seeded
    RNG and the (optional) virtual-time origin the ``at=`` triggers
    measure from.  Thread-safe: fault points fire from the dispatch
    worker, the completion worker, and N warmup threads concurrently.
    """

    def __init__(self, spec: str = "", seed: int = 0, rank: int | None = None):
        self.specs = [
            FaultSpec.parse(clause)
            for clause in spec.split(";")
            if clause.strip()
        ]
        self.seed = seed
        # This process's gang rank, for rank= scoped trainer clauses: the
        # launcher's env contract (RANK) by default, so a schedule like
        # 'kill:step:rank=1:after=4' handed identically to every gang
        # member fires only inside rank 1.
        import os as _os

        self.rank = (
            int(_os.environ.get("RANK", 0) or 0) if rank is None else int(rank)
        )
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._t0: float | None = None
        # Released by uninstall() so stuck hang sleepers wake instead of
        # outliving the test that injected them.
        self._unhang = threading.Event()

    def start(self) -> "FaultInjector":
        """Set the virtual-time origin for ``at=`` triggers (the moment
        the workload begins, not the moment the injector was built)."""
        self._t0 = time.monotonic()
        return self

    def fire(self, site: str, replica: str | None = None) -> None:
        """Evaluate every armed clause against one fault-point event.

        Raises :class:`FaultError` for a matching ``fail``; sleeps for a
        matching ``hang``; silently returns otherwise.  Counters mutate
        under the lock; the hang sleep runs outside it.
        """
        for spec in self.specs:
            if spec.site != site:
                continue
            if spec.replica is not None and spec.replica != replica:
                continue
            if spec.rank is not None and spec.rank != self.rank:
                continue
            if spec.at_s is not None and (
                self._t0 is None or time.monotonic() - self._t0 < spec.at_s
            ):
                continue
            with self._lock:
                if spec.after > 0:
                    spec.after -= 1
                    continue
                if spec.count <= 0:
                    continue
                if spec.p < 1.0 and self._rng.random() >= spec.p:
                    continue
                spec.count -= 1
                spec.fired += 1
                op, hang_s, source = spec.op, spec.hang_s, spec.source
            if op == "hang":
                self._unhang.wait(hang_s)
            elif op == "kill":
                # Simulated SIGKILL: no exception, no finally blocks, no
                # atexit — the process is simply gone, which is the
                # preemption the checkpoint rotation must survive.  137
                # is the 128+SIGKILL convention the chaos driver asserts.
                import os

                os._exit(137)
            else:
                raise FaultError(
                    f"injected {op} at {site}"
                    + (f" on {replica}" if replica else "")
                    + f" ({source})",
                    op=op,
                    site=site,
                )

    def fired_counts(self) -> dict[str, int]:
        """``{clause source: times fired}`` — the chaos report's receipt
        that the schedule actually bit."""
        with self._lock:
            return {spec.source: spec.fired for spec in self.specs}


# The module-global installed injector.  None = every fault point is a
# single attribute load + branch — the near-zero-overhead contract.
_INJECTOR: FaultInjector | None = None


def install(injector: FaultInjector) -> FaultInjector:
    global _INJECTOR
    _INJECTOR = injector
    return injector


def uninstall() -> None:
    """Remove the installed injector and wake any thread stuck in one of
    its ``hang`` sleeps (tests must not wait out a 3-second hang whose
    assertion already passed)."""
    global _INJECTOR
    injector, _INJECTOR = _INJECTOR, None
    if injector is not None:
        injector._unhang.set()


def fault_point(site: str, replica: str | None = None) -> None:
    """The hook the serving hot path calls.  Dormant unless installed."""
    injector = _INJECTOR
    if injector is not None:
        injector.fire(site, replica)


def active() -> bool:
    """True when an injector is installed.  The trainer reads this to
    decide whether to route steps through the resilient runtime (the
    fault sites live there) even when no resilience flag is set — so an
    in-process ``with injected("fail:step:after=3"):`` bites without
    extra plumbing.  The flagless no-injector path stays untouched."""
    return _INJECTOR is not None


def active_sites() -> frozenset:
    """The sites named by the installed schedule (empty when none).
    The trainer uses this to refuse configurations where an armed
    trainer-site clause could never fire (e.g. ``--fused``, whose one
    device call has no step/data_next/ckpt_save events) — a chaos run
    that injects nothing must fail loudly, not report green."""
    injector = _INJECTOR
    if injector is None:
        return frozenset()
    return frozenset(spec.site for spec in injector.specs)


@contextmanager
def injected(spec: str, seed: int = 0):
    """``with injected("fail:launch:r0:count=3"):`` — install, start,
    and always uninstall (the test-suite ergonomic surface)."""
    injector = install(FaultInjector(spec, seed=seed)).start()
    try:
        yield injector
    finally:
        uninstall()
