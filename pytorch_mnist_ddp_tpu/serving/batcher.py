"""Pipelined micro-batching: bounded admission, overlap, backpressure.

The serving trade: one 64-sample dispatch costs barely more device time
than a 1-sample dispatch (the forward is launch-bound at these shapes),
so coalescing concurrent requests multiplies throughput — but waiting to
coalesce adds latency.  The batcher resolves it the standard way: take
the first queued request, then keep pulling until the batch would exceed
the top bucket or a **linger deadline** passes, whichever comes first.

PR 4 splits the formerly serial submit→pad→H2D→compute→D2H→complete
chain into a two-thread pipeline (the Orca/Clipper lesson: throughput
lives in keeping a bounded window of batches in flight, not in a faster
serial loop):

- the **dispatch worker** coalesces, pads into preallocated per-bucket
  staging buffers (:class:`~.buckets.StagingPool` — zero allocation at
  steady state), and launches the jitted forward WITHOUT reading the
  result back — jax's async dispatch returns immediately;
- the **completion worker** performs the blocking D2H read, slices
  per-request results to their waiters, and recycles the staging buffer.

A semaphore bounds the launched-not-yet-read window (``max_inflight``,
default 2): batch N+1's host work (coalesce + pad + H2D) overlaps batch
N's device compute, but device memory for in-flight batches stays
bounded.  Time the dispatch thread spends blocked on a full window is
recorded as **pipeline stall** — the signal that the device, not the
host, is the bottleneck.

The **adaptive linger controller** closes the remaining latency knob:
when the admission queue is deep, waiting to coalesce is pure added
latency (the next batch fills instantly anyway), so the linger shrinks
toward 0; when traffic goes idle it relaxes back toward the configured
ceiling so lone requests still get coalescing's benefit.  Disable it
(``adaptive_linger=False``) for the fixed-linger PR 3 behavior.

Admission is a bounded **QoS-weighted** queue (serving/qos.py): requests
carry a class (``interactive``/``batch``), dequeue is weighted
round-robin so latency-sensitive work overtakes bulk backlog, and a full
queue sheds the lowest class first before rejecting
(:class:`RejectedError`, the HTTP 503) — the backpressure contract,
docs/SERVING.md.  Requests that expire while queued are completed with
:class:`RequestTimeout` (504) without being dispatched — eagerly, on the
workers' cadence, not when batch formation happens to reach them.  Batch
close is **deadline-aware**: the linger is clamped so the oldest
member's remaining deadline budget still covers the estimated service
time, instead of holding a nearly-expired request to a global linger.

Shutdown is a graceful drain: ``stop()`` closes admission (new submits
get 503) and, by default, lets the dispatch worker finish everything
already admitted AND the completion worker read back everything already
launched before joining — nothing in the queue or the in-flight window
is lost.

**Packed ragged batching** (PR 19, docs/SERVING.md): when the engine
serves the packed path (``engine.packed``), batches are SEGMENT lists —
``(request, start, rows)`` triples — instead of whole-request lists.
Requests concatenate back-to-back into one rows-capacity buffer with a
segment-id vector (serving/buckets.py), and a request that would
overflow the forming batch is SPLIT: the head fills this batch exactly
to capacity, the remainder carries to lead the next one — so every
deep-queue batch dispatches 100% full, which is where the ratcheted
``min_mean_fill_ratio`` budget comes from.  The completion worker
reassembles split requests from per-request assembly buffers keyed by
segment boundaries, bit-identical to the padded path (pinned in
tests).  Under light load the **fill wait** (``fill_wait_ms``) replaces
the millisecond linger as the adaptive controller's ceiling: packed
mode trades a bounded wait for a full buffer, and the controller still
collapses the wait toward 0 when the queue is deep (a deep queue fills
the buffer instantly anyway).
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from ..analysis.lockwatch import make_lock
from ..obs.spans import span
from .buckets import StagingPool
from .engine import InferenceEngine
from .faults import fault_point
from .metrics import ServingMetrics
from .qos import DEFAULT_QOS, QOS_CLASSES, QoSQueue


class RejectedError(RuntimeError):
    """Admission refused (queue full or server draining) — HTTP 503."""


class ReplicaDeadError(RejectedError):
    """A pool replica failed or was torn down with this request aboard —
    the work never produced a result, so resubmitting it on a surviving
    replica cannot duplicate a response.  Subclasses
    :class:`RejectedError` on purpose: the HTTP handler's drain-race
    retry (serving/server.py) and the router's skip logic treat a dead
    replica exactly like a draining one, which is the failure-aware
    retry contract (docs/ROBUSTNESS.md)."""


class RequestTimeout(RuntimeError):
    """Deadline expired before a result was produced — HTTP 504."""


class PendingRequest:
    """One admitted request: input rows + dtype + QoS class + deadline +
    a result slot.  ``dtype`` selects the engine variant the batch
    dispatches on (docs/SERVING.md reduced-precision variants); requests
    only coalesce with same-dtype neighbors.  ``qos`` is the scheduling
    class (serving/qos.py) the weighted admission queue orders by."""

    __slots__ = (
        "x", "dtype", "qos", "deadline", "t_submit", "completed_by",
        "_copies", "_event", "_value", "_error", "_lock",
    )

    def __init__(
        self,
        x: np.ndarray,
        deadline: float,
        dtype: str = "f32",
        qos: str = DEFAULT_QOS,
    ):
        self.x = x
        self.dtype = dtype
        self.qos = qos
        self.deadline = deadline
        self.t_submit = time.perf_counter()
        # Live-copy count: 1 for the original admission, +1 per hedge
        # twin (submit_hedge).  Eviction paths — shed, queue flush,
        # abort's in-flight flush, launch/read failures — consume a
        # copy (:meth:`drop_copy`) and set a client-visible error ONLY
        # on the LAST one: while a twin is still live it owns the
        # outcome, and an eviction error would win the first-wins race
        # and clobber the twin's (likely successful) answer.
        self._copies = 1
        # Which replica's completion won (hedged dispatch,
        # serving/router.py): set atomically with the winning outcome so
        # the hedge accounting can tell won from lost without a second
        # synchronization point.  None for error outcomes that carry no
        # replica (flushes, expiry).
        self.completed_by: str | None = None
        self._event = threading.Event()
        self._lock = make_lock("batcher.pending")
        self._value: np.ndarray | None = None
        self._error: BaseException | None = None

    @property
    def n(self) -> int:
        return len(self.x)

    def expired(self, now: float | None = None) -> bool:
        return (now if now is not None else time.perf_counter()) > self.deadline

    def done(self) -> bool:
        """An outcome (result or error) is already set — a hedged twin
        answered, or the client's expiry fired.  The dispatch worker
        skips done requests instead of wasting a device slot on them."""
        return self._event.is_set()

    # -- live-copy accounting (hedged dispatch, serving/router.py) ----------

    def add_copy(self) -> None:
        """A hedge twin is being enqueued: one more live copy exists."""
        with self._lock:
            self._copies += 1

    def drop_copy(self) -> int:
        """One copy was evicted without producing an outcome (shed,
        flush, abort, launch/read failure); returns the number of live
        copies REMAINING.  Non-zero means another copy still owns the
        outcome and the evicting path must stay silent."""
        with self._lock:
            self._copies = max(0, self._copies - 1)
            return self._copies

    # -- completion (worker side) -------------------------------------------
    #
    # First writer wins, atomically: the supervisor's abort path
    # (serving/pool.py) errors a hung batch's waiters so they can retry
    # on a survivor, and the stuck completion read may STILL finish later
    # and try to set a result.  Exactly one outcome must be visible — a
    # late set after the first is a silent no-op, so a request the
    # handler already retried can never grow a second answer.  The same
    # lock is what makes hedged dispatch safe (serving/router.py): the
    # SAME PendingRequest rides two replicas' queues, and whichever
    # completion worker sets first is the one client-visible outcome.
    # Both setters return True only to the winner, so the loser's
    # worker can skip its metrics/breaker accounting — a hedge must
    # never double-count (docs/SERVING.md).

    def set_result(self, value: np.ndarray, by: str | None = None) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._value = value
            self.completed_by = by
            self._event.set()
            return True

    def set_error(self, error: BaseException, by: str | None = None) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._error = error
            self.completed_by = by
            self._event.set()
            return True

    # -- consumption (handler side) -----------------------------------------

    def result(self, grace_s: float = 1.0) -> np.ndarray:
        """Block until completed; raises the worker's error if it set one.

        Waits until the request deadline plus ``grace_s`` (the worker
        expires overdue requests itself; the grace only covers a dispatch
        already in flight when the deadline passed).
        """
        timeout = max(0.0, self.deadline - time.perf_counter()) + grace_s
        if not self._event.wait(timeout):
            raise RequestTimeout("request deadline expired")
        # Read the outcome under the same lock the setters hold: the
        # event wait already orders the winning write before this read,
        # but the lock keeps the (error, value, completed_by) triple one
        # atomic cut — no torn view if a late loser is mid-no-op.
        with self._lock:
            if self._error is not None:
                raise self._error
            assert self._value is not None
            return self._value


class AdaptiveLinger:
    """Queue-depth-driven linger: shrink under load, relax when idle.

    The linger only buys throughput while the queue is SHALLOW — it is
    the time spent hoping more requests arrive.  A deep queue already
    holds the next batch, so every lingered millisecond there is pure
    added latency.  The controller halves the linger whenever the
    admission queue is at least ``deep_depth`` requests deep (snapping to
    0 below ``floor_s`` — half-lives below a tenth of a millisecond are
    indistinguishable from none) and relaxes it additively back toward
    the configured ceiling on an empty queue; in-between depths hold.
    Multiplicative decrease / additive increase reacts in O(log) batches
    to a burst and recovers smoothly, and both moves keep the value
    inside ``[0, ceiling_s]`` by construction (the bound the property
    test pins).

    State is published to the obs registry as the
    ``serving_linger_seconds`` gauge, so /metrics shows what the
    controller is currently doing.
    """

    def __init__(
        self,
        ceiling_s: float,
        enabled: bool = True,
        registry=None,
        replica: str | None = None,
        deep_depth: int = 4,
        shrink: float = 0.5,
        relax_frac: float = 0.25,
        floor_s: float = 1e-4,
    ):
        if not 0.0 < shrink < 1.0:
            raise ValueError(f"shrink factor must be in (0, 1), got {shrink}")
        if not 0.0 < relax_frac <= 1.0:
            raise ValueError(f"relax_frac must be in (0, 1], got {relax_frac}")
        self.ceiling_s = max(0.0, ceiling_s)
        self.enabled = enabled
        self.deep_depth = max(1, deep_depth)
        self.shrink = shrink
        self.relax_frac = relax_frac
        self.floor_s = floor_s
        self.current_s = self.ceiling_s
        # Pool mode labels the gauge per replica: N controllers sharing
        # one registry would otherwise last-writer-race a single series
        # (the same hazard set_inflight's replica= label exists for).
        self._gauge = (
            registry.gauge(
                "serving_linger_seconds",
                help="current adaptive linger (shrinks under queue depth, "
                "relaxes toward the configured ceiling when idle)",
                **({"replica": replica} if replica else {}),
            )
            if registry is not None
            else None
        )
        if self._gauge is not None:
            self._gauge.set(self.current_s)

    def update(self, queue_depth: int) -> float:
        """Observe the admission depth; return the linger to use now."""
        if not self.enabled:
            return self.ceiling_s
        if queue_depth >= self.deep_depth:
            self.current_s *= self.shrink
            if self.current_s < self.floor_s:
                self.current_s = 0.0
        elif queue_depth == 0:
            self.current_s = min(
                self.ceiling_s,
                self.current_s + self.relax_frac * self.ceiling_s,
            )
        if self._gauge is not None:
            self._gauge.set(self.current_s)
        return self.current_s


class _InFlight:
    """One launched batch riding the dispatch→completion queue.

    ``batch`` is the unique member requests (the failure/abort paths'
    unit of accounting — a request appears at most once per batch, even
    split); ``segments`` is the row layout: ``(request, start, rows)``
    per staged block, in staging order, where ``start`` is the block's
    offset within the REQUEST (non-zero only for the carried remainder
    of a packed split).  In bucketed mode segments are always whole
    requests, so the completion slicing below reduces to the PR-4
    ``host[offset : offset + req.n]`` exactly."""

    __slots__ = (
        "batch", "segments", "logits", "staged", "bucket", "n", "stall_s",
        "dtype", "t_launch",
    )

    def __init__(self, batch, segments, logits, staged, bucket, n, stall_s,
                 dtype):
        self.batch = batch
        self.segments = segments
        self.logits = logits
        self.staged = staged
        self.bucket = bucket
        self.n = n
        self.stall_s = stall_s
        self.dtype = dtype
        self.t_launch = time.perf_counter()


class MicroBatcher:
    """Coalesce admitted requests into a pipelined engine dispatch chain.

    Exactly one dispatch worker touches ``engine.launch`` (jax dispatch
    is not re-entrant here) and exactly one completion worker reads
    results back; HTTP handler threads only ``submit()`` and wait.  The
    engine contract is ``engine.buckets`` plus ``engine.launch(staged,
    n)`` returning an object ``np.asarray`` resolves to ``[bucket,
    classes]`` logits (tests substitute a fake).
    """

    def __init__(
        self,
        engine: InferenceEngine,
        metrics: ServingMetrics | None = None,
        max_batch: int | None = None,
        linger_ms: float = 2.0,
        queue_depth: int = 64,
        timeout_ms: float = 1000.0,
        max_inflight: int = 2,
        adaptive_linger: bool = True,
        fill_wait_ms: float | None = None,
        sink=None,
        replica: str | None = None,
        deadline_aware: bool = True,
        qos_classes: tuple[str, ...] = QOS_CLASSES,
        qos_weights: dict[str, int] | None = None,
        heartbeat=None,
    ):
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
        top = engine.buckets[-1]
        self.engine = engine
        # Pool mode (serving/router.py): ``replica`` names this batcher
        # on the per-replica metric families and telemetry events, and
        # the pool assigns ``on_complete(latency_s, rows)`` after
        # construction to feed the router's per-replica (and per-shape-
        # class — ``rows`` is the completed request's row count) EWMAs
        # from the completion worker.  Both are None in single-engine
        # use, where the unlabeled PR-4 surface is unchanged.
        self.replica = replica
        self.on_complete = None
        # Failure hook (pool mode): called with the failed-request count
        # from the worker that observed the failure — the router's
        # circuit breaker feed (serving/router.py).
        self.on_failure = None
        # Expiry hook (pool mode): called per request that expires in
        # the admission queue before any dispatch.  The router returns
        # the request's half-open trial token through it — a pre-
        # dispatch expiry is no outcome either way, and without the
        # return a trial that times out in queue would pin the breaker
        # half-open forever (trial_limit tokens never freed).
        self.on_expire = None
        self.metrics = metrics if metrics is not None else engine.metrics
        self.max_batch = min(max_batch or top, top)
        # Packed ragged batching rides the ENGINE's mode (module
        # docstring): segment staging, request splitting at the capacity
        # boundary, and the fill-wait close ceiling all key off it, so a
        # batcher can never disagree with its engine about the layout.
        self.packed = bool(getattr(engine, "packed", False))
        self.linger_s = linger_ms / 1e3
        # The packed close ceiling: waiting to FILL the rows buffer is
        # the whole fill-ratio win under light load, and is worth more
        # than a millisecond linger (the capacity only pads one buffer,
        # not one per rung).  None keeps the plain linger — bucketed
        # mode ignores the flag entirely.
        self.fill_wait_s = (
            fill_wait_ms / 1e3
            if (self.packed and fill_wait_ms is not None)
            else None
        )
        self.timeout_s = timeout_ms / 1e3
        self.max_inflight = max_inflight
        # Variant routing: engines expose their served dtype names (the
        # reduced-precision variants, serving/engine.py); fakes without
        # the surface serve the default only.
        self._default_dtype = getattr(engine, "default_dtype", "f32")
        self._registry = self.metrics.registry if self.metrics is not None else None
        self._sink = sink
        self._linger = AdaptiveLinger(
            self.fill_wait_s if self.fill_wait_s is not None else self.linger_s,
            enabled=adaptive_linger, registry=self._registry,
            replica=self.replica,
        )
        # Deadline-aware batch close (docs/SERVING.md tail latency): the
        # linger is additionally clamped so the batch dispatches while
        # the OLDEST member's remaining deadline budget still covers the
        # estimated service time (EWMA of launch -> read-back, fed by
        # the completion worker).  Off = the PR-4 global linger.
        self.deadline_aware = deadline_aware
        self._service_ewma_s: float | None = None
        self.qos_classes = tuple(qos_classes)
        self._queue: QoSQueue = QoSQueue(
            maxsize=queue_depth, classes=self.qos_classes, weights=qos_weights
        )
        # Eager pre-registration: the per-class families must appear on
        # the Prometheus exposition from the first scrape, not after the
        # first completion of each class (CI greps the families from a
        # short smoke — a lazy family is a flaky grep).
        if self.metrics is not None:
            for name in self.qos_classes:
                self.metrics.ensure_qos(name)
        # Launched-but-unread batches; the semaphore IS the window bound,
        # the queue just carries them to the completion worker in order.
        self._window = threading.Semaphore(max_inflight)
        self._completions: queue.Queue[_InFlight | None] = queue.Queue()
        # One spare staging slot beyond the window so batch N+1 pads
        # while the window is still full with batches N-k..N.
        self._staging: StagingPool | None = None
        # Packed-split reassembly (completion worker only — single
        # thread, no lock): id(request) -> [request, out_buffer,
        # rows_filled].  A split request completes when its last part
        # lands; entries whose request settled elsewhere (hedge twin,
        # launch failure on the sibling batch) are swept on the
        # completion cadence.
        self._assembly: dict[int, list] = {}
        self._inflight_lock = make_lock("batcher.inflight")
        self._inflight = 0
        self.peak_inflight = 0
        # Health signals the supervisor polls (serving/pool.py): launched
        # batches not yet read back (hang detection via the oldest one's
        # age) and the current launch-failure streak.
        self._live: set[_InFlight] = set()
        self.consecutive_launch_failures = 0
        # Fleet liveness (serving/fleet.py): a throttled callable beaten
        # once per dispatch-loop iteration, so a backend whose dispatch
        # loop wedges stops beating even while its process answers
        # poll() — the supervisor's mtime-age signal
        # (liveness.Heartbeat.beat; None = flagless no-op).
        self._heartbeat = heartbeat
        # Monotonic abort flag: an Event, not a lock-guarded bool — the
        # fast paths (submit, dispatch, completion) read it without any
        # lock and Event.set() publishes with the same release ordering
        # the old under-lock store did.
        self._aborted = threading.Event()
        self._closed = threading.Event()
        self._stop_lock = make_lock("batcher.stop")  # stop() is concurrency-safe
        self._worker: threading.Thread | None = None
        self._completer: threading.Thread | None = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "MicroBatcher":
        # Same lock as _stop_locked: a start() racing a concurrent
        # stop() must see either no workers or both, never a torn pair.
        with self._stop_lock:
            if self._worker is not None:
                raise RuntimeError("batcher already started")
            self._worker = threading.Thread(
                target=self._run, name="serve-dispatch", daemon=True
            )
            self._completer = threading.Thread(
                target=self._complete_loop, name="serve-complete", daemon=True
            )
            self._completer.start()
            self._worker.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Close admission; by default finish the queue AND the window.

        ``drain=False`` abandons queued requests — each is completed with
        :class:`RejectedError` so no handler thread is left hanging.
        Batches already launched on the device are always read back and
        completed (abandoning them would waste finished device work).

        Safe to call concurrently (a pool ``drain()`` racing the
        shutdown path's ``Router.stop()``): calls serialize, and the
        loser sees already-joined workers and returns.
        """
        if self._aborted.is_set():
            # An aborted batcher's completion worker may be permanently
            # stuck inside a dead replica's D2H read; abort() already
            # completed every waiter, so there is nothing to drain and a
            # join here would hang the whole shutdown on one sick thread.
            return
        self._closed.set()
        with self._stop_lock:
            self._stop_locked(drain)

    def _stop_locked(self, drain: bool) -> None:
        if not drain:
            self._flush_rejected()
        if self._worker is not None:
            self._worker.join()  # jaxlint: disable=JL021 -- the join IS the drain: stop() holds _stop_lock exactly so concurrent stops serialize behind worker exit; admission is already closed, so the wait is bounded
            self._worker = None
        # The dispatch worker has exited, so every launched batch is
        # already enqueued; the sentinel lands strictly after them and
        # the join below proves the in-flight window fully drained.
        if self._completer is not None:
            self._completions.put(None)
            self._completer.join()  # jaxlint: disable=JL021 -- stop-path serialization, same contract as the dispatch-worker join above; the sentinel just enqueued guarantees exit
            self._completer = None
        # A submit() racing stop() can land a request AFTER the worker saw
        # the empty queue and exited; without this flush that request would
        # sit unserviced until its client's deadline expired (504 during a
        # "graceful" drain).  Post-join the queue is ours alone.
        self._flush_rejected()

    def _flush_rejected(self) -> None:
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req.drop_copy() > 0:
                # A hedge twin is still live elsewhere and owns the
                # outcome; erroring this copy would clobber it.
                continue
            req.set_error(RejectedError("server shutting down"))
            # Pool mode: the HTTP handler resubmits a flushed request on
            # a surviving replica (serving/server.py), so the client may
            # never see this rejection — counting here would alert
            # operators on phantom 503s during every drain.  The
            # client-visible outcome is counted where it is decided: the
            # router's last-replica submit, or the handler's final
            # result().  Single-engine mode has no retry; the flush IS
            # the client outcome and keeps the PR-4 accounting.
            if self.metrics is not None and self.replica is None:
                self.metrics.record_rejected()

    def abort(self) -> int:
        """Tear down a DEAD replica's pipeline without waiting on it.

        The drain path (``stop(drain=True)``) is for healthy replicas:
        it joins both workers, which presumes the device still answers.
        A replica that hangs mid-completion or fails every launch would
        park that join forever — so the supervisor calls this instead
        (serving/pool.py).  Every queued request and every
        launched-but-unread batch is completed with
        :class:`ReplicaDeadError` so its handler retries on a survivor;
        the workers are unstuck where possible and abandoned (daemon
        threads) where not.  Returns the number of requests flushed.
        First-wins completion (:class:`PendingRequest`) makes this safe
        against a stuck read that later finishes: the late result is
        discarded, never a second client-visible outcome.
        """
        self._closed.set()
        with self._inflight_lock:
            self._aborted.set()
            live = list(self._live)
            # Zero the in-flight bookkeeping NOW: a permanently wedged
            # completion worker never reaches its finally block, so
            # without this sweep the gauge, Router.inflight(), and
            # oldest_inflight_age would report phantom stuck load for an
            # ejected replica forever.  A worker that later unsticks
            # clamps at zero instead of double-decrementing.
            self._live.clear()
            self._inflight = 0
            if self.metrics is not None:
                self.metrics.set_inflight(0, replica=self.replica)
        # Unstick a dispatch worker blocked on a full in-flight window.
        for _ in range(self.max_inflight):
            self._window.release()
        flushed = self._flush_dead()
        dead = ReplicaDeadError(
            f"replica {self.replica or '?'} aborted by the supervisor"
        )
        for item in live:
            for req in item.batch:
                if req.drop_copy() > 0:
                    continue  # a live hedge twin owns the outcome
                req.set_error(dead)
                flushed += 1
        # If the completion worker is merely slow (not hung), the
        # sentinel lets it exit once it unsticks.
        self._completions.put(None)
        return flushed

    def _flush_dead(self) -> int:
        """Complete every queued request with :class:`ReplicaDeadError`
        (retriable on a survivor).  Shared by :meth:`abort` and the
        submit-side re-check that closes abort's flush-vs-enqueue race;
        first-wins completion makes a double flush harmless."""
        dead = ReplicaDeadError(
            f"replica {self.replica or '?'} aborted by the supervisor"
        )
        flushed = 0
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return flushed
            if req.drop_copy() > 0:
                continue  # a live hedge twin owns the outcome
            req.set_error(dead)
            flushed += 1

    def depth(self) -> int:
        """Current admission-queue depth (the /metrics gauge)."""
        return self._queue.qsize()

    def qos_depths(self) -> dict[str, int]:
        """Per-class admission-queue depths (the /metrics qos block)."""
        return self._queue.sizes()

    def inflight(self) -> int:
        """Batches launched but not yet read back (the /metrics gauge)."""
        with self._inflight_lock:
            return self._inflight

    def oldest_inflight_age(self, now: float | None = None) -> float:
        """Seconds the OLDEST launched-but-unread batch has been waiting
        (0.0 when nothing is in flight) — the supervisor's completion-
        stall signal: a healthy replica's reads finish in milliseconds,
        so an age past the stall timeout means the completion worker is
        wedged on a dead device."""
        with self._inflight_lock:
            if not self._live:
                return 0.0
            oldest = min(item.t_launch for item in self._live)
        return (now if now is not None else time.perf_counter()) - oldest

    @property
    def current_linger_ms(self) -> float:
        """What the adaptive controller is currently waiting (ms)."""
        return 1e3 * (
            self._linger.current_s
            if self._linger.enabled
            else self._linger.ceiling_s
        )

    # -- admission (any thread) ----------------------------------------------

    def submit(
        self,
        x: np.ndarray,
        timeout_ms: float | None = None,
        dtype: str | None = None,
        qos: str | None = None,
        count_reject: bool = True,
    ) -> PendingRequest:
        """Admit one request of ``[n, 28, 28, 1]`` rows or reject now.

        Raises :class:`RejectedError` when draining, when the request is
        bigger than one maximal batch (it would never fit a dispatch),
        when the bounded queue is full — the reject-don't-queue
        backpressure contract — or when ``dtype`` names a variant the
        engine does not serve / has not parity-verified (the refusal
        contract, docs/SERVING.md).  ``qos`` names the scheduling class
        (serving/qos.py; default the most latency-sensitive): a full
        queue first sweeps expired entries, then sheds the newest
        request of a strictly LOWER class to admit this one
        (``serving_shed_total{qos=}``) before giving up with the 503.
        ``count_reject=False`` suppresses the rejection COUNTER only
        (the exception still raises): the router tries replicas in
        policy order and a skipped-and-retried replica is not a
        client-visible 503.
        """
        x = np.asarray(x, np.float32)
        if self._closed.is_set():
            if count_reject and self.metrics is not None:
                self.metrics.record_rejected()
            raise RejectedError("server draining; not accepting requests")
        qos = qos or DEFAULT_QOS
        if qos not in self.qos_classes:
            if count_reject and self.metrics is not None:
                self.metrics.record_rejected()
            raise RejectedError(
                f"unknown QoS class {qos!r}; have {list(self.qos_classes)}"
            )
        dtype = dtype or self._default_dtype
        if dtype != self._default_dtype:
            served = getattr(self.engine, "dtypes", (self._default_dtype,))
            if dtype not in served:
                if count_reject and self.metrics is not None:
                    self.metrics.record_rejected()
                raise RejectedError(
                    f"dtype {dtype!r} is not served (have {list(served)})"
                )
            verified = getattr(self.engine, "variant_verified", None)
            if verified is not None and not verified(dtype):
                if count_reject and self.metrics is not None:
                    self.metrics.record_rejected()
                raise RejectedError(
                    f"dtype {dtype!r} has not passed its parity gate; "
                    "refusing to serve it"
                )
        if not 1 <= len(x) <= self.max_batch:
            if count_reject and self.metrics is not None:
                self.metrics.record_rejected()
            raise RejectedError(
                f"request of {len(x)} samples outside [1, {self.max_batch}]"
            )
        timeout_s = self.timeout_s if timeout_ms is None else timeout_ms / 1e3
        req = PendingRequest(
            x, deadline=time.perf_counter() + timeout_s, dtype=dtype, qos=qos
        )
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            if not self._admit_under_pressure(req):
                if count_reject and self.metrics is not None:
                    self.metrics.record_rejected()
                raise RejectedError(
                    f"admission queue full ({self._queue.maxsize} deep)"
                ) from None
        if self.metrics is not None:
            self.metrics.record_admitted()
        # Close the abort race: admission passed the _closed check
        # before a concurrent abort() set it, and the enqueue may have
        # landed AFTER abort's queue flush with both workers gone —
        # stop() closes the same race with a post-join flush, but abort
        # cannot join a wedged worker.  If _aborted reads False here,
        # abort's flush (which follows its _aborted store) has yet to
        # run and will sweep this request; if True, we sweep it
        # ourselves.  Either way the waiter gets ReplicaDeadError and
        # the handler retries on a survivor instead of idling into 504.
        if self._aborted.is_set():
            self._flush_dead()
        return req

    def _admit_under_pressure(self, req: PendingRequest) -> bool:
        """Full-queue admission ladder: (1) eagerly sweep requests that
        expired (or were satisfied by a hedge twin) while queued — the
        satellite bugfix: their slots and any held circuit trial tokens
        free NOW, not when batch formation reaches them; (2) shed the
        newest queued request of a strictly lower class (lowest class
        first, serving/qos.py) so interactive goodput holds under
        pressure while batch absorbs the 503s.  Returns True once
        ``req`` is queued."""
        for attempt in range(2):
            if attempt == 0:
                self.sweep_expired()
            else:
                victim = self._queue.shed_for(req.qos)
                if victim is None:
                    return False
                self._shed(victim)
            try:
                self._queue.put_nowait(req)
                return True
            except queue.Full:
                continue
        return False

    def _shed(self, victim: PendingRequest) -> None:
        """Complete a load-shed victim with the 503 and count it.  In
        pool mode the handler's failure-aware retry may still land it on
        a less-loaded replica; the shed counter is the operator's
        pressure signal either way (docs/OBSERVABILITY.md)."""
        if victim.drop_copy() > 0:
            # One copy of a hedged request: another live copy owns the
            # outcome (or will).  Setting RejectedError here would WIN
            # the first-wins race and discard the twin's — likely
            # successful — answer, turning a hedge into a client 503.
            # Dropping the copy silently just cancels this replica's
            # side of the hedge; the slot is freed either way.  (When
            # the LAST copy is evicted, whichever eviction path takes
            # it sets the client-visible error as usual.)
            return
        won = victim.set_error(
            RejectedError(
                f"shed under pressure (QoS {victim.qos!r} yielded the "
                "queue slot to a higher class)"
            )
        )
        if self.metrics is not None and won:
            self.metrics.record_shed(victim.qos)
            # Single-engine mode: the shed IS the client outcome (no
            # retry exists), same accounting rule as _flush_rejected.
            if self.replica is None:
                self.metrics.record_rejected()
        if self._sink and won:
            self._sink.emit(
                "qos_shed", qos=victim.qos, n=victim.n,
                **({"replica": self.replica} if self.replica else {}),
            )
        if self.on_expire is not None and won:
            # A shed is no outcome for the replica either way — but any
            # half-open trial token the victim held must come back, the
            # same leak the expiry path plugs (serving/router.py).
            try:
                self.on_expire(1)
            except Exception:
                pass  # an observability hook must not kill the caller

    def sweep_expired(self) -> int:
        """Eagerly expire every queued request whose deadline already
        passed (and silently drop hedge twins that were satisfied
        elsewhere).  Called by the workers on their natural cadence and
        by the full-queue admission path; public so the supervisor or
        tests can force a sweep.  Returns the number expired."""
        expired = self._queue.sweep_expired()
        for req in expired:
            self._expire(req)
        return len(expired)

    def submit_hedge(self, req: PendingRequest) -> None:
        """Enqueue an ALREADY-ADMITTED request a second time — hedged
        dispatch (serving/router.py): the same :class:`PendingRequest`
        rides this replica's queue beside its still-in-flight twin, and
        the first completion wins under the request's own lock.

        Deliberately narrower than :meth:`submit`: no new deadline (the
        hedge runs on the ORIGINAL admission's remaining budget), no
        admitted count (one client request, one admission), no shedding
        (a hedge is opportunistic — it must never evict real work), and
        a full queue is a plain :class:`RejectedError` the hedger treats
        as "this replica declined".
        """
        if self._closed.is_set():
            raise RejectedError("replica draining; not accepting hedges")
        if req.done() or req.expired():
            raise RejectedError("hedge target already settled")
        # Counted BEFORE the enqueue: from this instant the request has
        # two live copies, and eviction paths consume copies silently
        # until the last one (drop_copy).
        req.add_copy()
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            # The twin was never enqueued: give its copy back.  If an
            # eviction consumed the ORIGIN's copy during the window
            # where the count read 2 (it stayed silent, expecting this
            # twin to own the outcome), this request now has zero live
            # copies — set the retriable eviction error here so the
            # client's handler resubmits instead of idling into a 504.
            if req.drop_copy() == 0 and not req.done():
                req.set_error(RejectedError(
                    "evicted under pressure while a hedge was declined"
                ))
            raise RejectedError("admission queue full; hedge declined") from None
        if self._aborted.is_set():
            self._flush_dead()

    # -- dispatch worker ------------------------------------------------------

    def _expire(self, req: PendingRequest) -> None:
        # won=False: a hedge twin on another replica already settled the
        # request (or a concurrent sweep beat us) — the timeout must not
        # double-count, but any trial token THIS replica holds for the
        # request still returns through on_expire.
        won = req.set_error(RequestTimeout("expired in queue before dispatch"))
        if self.metrics is not None and won:
            self.metrics.record_timeout()
        if self.on_expire is not None:
            try:
                self.on_expire(1)
            except Exception:
                pass  # an observability hook must not kill the worker

    def _close_at(self, now: float, linger: float, oldest_deadline: float) -> float:
        """When this forming batch must dispatch: the linger ceiling,
        clamped — when ``deadline_aware`` — so the OLDEST member's
        remaining deadline budget still covers the estimated service
        time (EWMA of launch→read-back).  A global linger holds a
        nearly-expired request hostage to traffic that may never come;
        the member's own budget is the thing that actually expires
        (docs/SERVING.md tail latency)."""
        close = now + linger
        if self.deadline_aware:
            margin = self._service_ewma_s or 0.0
            close = min(close, oldest_deadline - margin)
        return close

    def _run(self) -> None:
        # The carried leader of the next batch: (request, start-row).
        # start > 0 only in packed mode, where a request split at the
        # capacity boundary carries its REMAINDER forward; bucketed mode
        # always carries whole requests (start 0).
        carry: tuple[PendingRequest, int] | None = None
        while True:
            if self._heartbeat is not None:
                self._heartbeat()
            if carry is not None:
                (first, first_start), carry = carry, None
            else:
                try:
                    first = self._queue.get(timeout=0.05)
                except queue.Empty:
                    if self._closed.is_set():
                        return
                    # Idle tick: let the controller relax back toward the
                    # ceiling even when no batch is forming, and eagerly
                    # expire anything whose deadline passed while queued
                    # (the satellite bugfix — its slot and any circuit
                    # trial token free now, not at next batch formation).
                    self._linger.update(0)
                    self.sweep_expired()
                    continue
                first_start = 0
            if first.done():
                continue  # settled elsewhere (hedge twin won); free slot
            if first.expired():
                self._expire(first)
                continue
            segs = [(first, first_start, first.n - first_start)]
            total = first.n - first_start
            oldest_deadline = first.deadline
            # Linger: coalesce until the batch is full or the close
            # deadline passes.  A draining batcher skips the linger —
            # nothing new is being admitted, so waiting only delays
            # shutdown.  The adaptive controller sets the linger from
            # the CURRENT queue depth: deep queue -> the next batch is
            # already here, lingering is pure latency.  Deadline-aware
            # close additionally dispatches early when the oldest
            # member's budget is nearly spent (_close_at).  In packed
            # mode the controller's ceiling is the FILL WAIT (module
            # docstring): worth paying under light load, collapsed by
            # the controller when the queue is deep.
            linger = (
                0.0 if self._closed.is_set()
                else self._linger.update(self._queue.qsize())
            )
            close_at = self._close_at(time.perf_counter(), linger, oldest_deadline)
            while total < self.max_batch:
                remaining = close_at - time.perf_counter()
                try:
                    nxt = (
                        self._queue.get_nowait()
                        if remaining <= 0
                        else self._queue.get(timeout=remaining)
                    )
                except queue.Empty:
                    break
                if nxt.done():
                    continue  # hedge twin already answered; drop silently
                if nxt.expired():
                    self._expire(nxt)
                    continue
                if nxt.dtype != first.dtype:
                    # Variants dispatch on different executables; a
                    # mixed batch cannot coalesce.  The stranger leads
                    # the next batch instead.  (Checked BEFORE the size
                    # split: a packed split across dtypes would stage
                    # rows on the wrong executable.)
                    carry = (nxt, 0)
                    break
                if total + nxt.n > self.max_batch:
                    if self.packed:
                        # Packed split: the head fills THIS buffer to
                        # exactly its capacity, the remainder leads the
                        # next batch.  This is what keeps deep-queue
                        # batches at 100% fill instead of fragmenting at
                        # every carry boundary.
                        head = self.max_batch - total
                        segs.append((nxt, 0, head))
                        total = self.max_batch
                        carry = (nxt, head)
                        if nxt.deadline < oldest_deadline:
                            oldest_deadline = nxt.deadline
                    else:
                        carry = (nxt, 0)  # doesn't fit; leads the next batch
                    break
                segs.append((nxt, 0, nxt.n))
                total += nxt.n
                if nxt.deadline < oldest_deadline:
                    # QoS-weighted dequeue can hand us a member with an
                    # EARLIER deadline than the batch leader; the close
                    # clamp tracks the tightest budget aboard.
                    oldest_deadline = nxt.deadline
                    close_at = min(
                        close_at,
                        self._close_at(
                            time.perf_counter(), linger, oldest_deadline
                        ),
                    )
            self._dispatch(segs)

    def _dispatch(
        self, segs: list[tuple[PendingRequest, int, int]]
    ) -> None:
        """Pad into staging, launch async, hand off to completion.

        ``segs`` is the formed batch as ``(request, start, rows)``
        segments (whole requests in bucketed mode; possibly a split head
        or carried remainder in packed mode).  Runs entirely on the
        dispatch worker; never blocks on device compute — only (briefly)
        on a full in-flight window, which is recorded as pipeline stall.
        """
        # A member can settle between its dequeue and here (a hedge twin
        # completing on the other replica): dispatching it would burn
        # bucket rows on an answer nobody is waiting for.
        segs = [s for s in segs if not s[0].done()]
        if not segs:
            return
        batch = [s[0] for s in segs]  # unique: one segment per request
        parts = [r.x[start : start + rows] for r, start, rows in segs]
        total = sum(len(p) for p in parts)
        if self._staging is None:
            # Sized lazily from the first request's row shape so fakes
            # with arbitrary item shapes work; window+1 slots so padding
            # the next batch overlaps a full in-flight window.
            self._staging = StagingPool(
                self.engine.buckets,
                parts[0].shape[1:],
                slots=self.max_inflight + 1,
                dtype=np.float32,
            )
        with span("serving_pad", sink=self._sink, registry=self._registry):
            staged, bucket = self._staging.stage(parts)
        if self.packed:
            from .buckets import segment_ids

            seg_vec = segment_ids([len(p) for p in parts], bucket)
        if self._window.acquire(blocking=False):
            stall_s = 0.0  # free slot: the common, fully overlapped case
        else:
            t0 = time.perf_counter()
            self._window.acquire()
            stall_s = time.perf_counter() - t0
            if self.metrics is not None:
                self.metrics.record_stall(stall_s)
        dtype = batch[0].dtype
        try:
            with span("serving_dispatch", sink=self._sink,
                      registry=self._registry):
                # Dormant fault point (serving/faults.py): chaos schedules
                # inject launch failures exactly where a dying device
                # would produce them.
                fault_point("launch", self.replica)
                # Default-dtype dispatch keeps the bare two-arg call so
                # fake engines (tests) need not grow a dtype kwarg.
                if self.packed:
                    logits = self.engine.launch(
                        staged, total, dtype=dtype, seg_ids=seg_vec
                    )
                elif dtype == self._default_dtype:
                    logits = self.engine.launch(staged, total)
                else:
                    logits = self.engine.launch(staged, total, dtype=dtype)
        except BaseException as e:  # complete every waiter, keep serving
            self._staging.release(staged, bucket)
            self._window.release()
            self.consecutive_launch_failures += 1
            # Pool mode: the work never ran, so the failure is retriable
            # on a surviving replica — surface it as ReplicaDeadError so
            # the handler's resubmission path picks it up.  Single-engine
            # mode has no survivors; the raw error is the client outcome.
            err: BaseException = e
            if self.replica is not None and not isinstance(e, RejectedError):
                err = ReplicaDeadError(
                    f"replica {self.replica} launch failed: "
                    f"{type(e).__name__}: {e}"
                )
                err.__cause__ = e
            # Only requests whose outcome THIS failure decided count on
            # the failed tally — a hedge twin that already answered
            # elsewhere (first-wins) or is still live elsewhere
            # (drop_copy) is not a client-visible failure here.
            failed = sum(
                1 for req in batch
                if req.drop_copy() == 0 and req.set_error(err)
            )
            # Same post-abort guard as the completion worker: a launch
            # that fails AFTER abort unstuck this worker (window
            # released on a dead engine) is the old pipeline's corpse
            # twitching — striking the restarted replica's breaker
            # would re-open a healthy half-open circuit, and these
            # requests were already flushed and retried.
            if self.metrics is not None and not self._aborted.is_set() and failed:
                self.metrics.record_failed(failed)
            if self.on_failure is not None and not self._aborted.is_set():
                try:
                    self.on_failure(len(batch))
                except Exception:
                    pass  # a hook failure must never kill the worker
            return
        self.consecutive_launch_failures = 0
        item = _InFlight(
            batch, segs, logits, staged, bucket, total, stall_s, dtype
        )
        aborted = False
        with self._inflight_lock:
            aborted = self._aborted.is_set()
            if not aborted:
                self._live.add(item)
                self._inflight += 1
                self.peak_inflight = max(self.peak_inflight, self._inflight)
                # Gauge set under the SAME lock as the counter: a set
                # outside it can lose the increment/decrement race and
                # leave a stale depth on /metrics?format=prom (which
                # never recomputes).
                if self.metrics is not None:
                    self.metrics.set_inflight(
                        self._inflight, replica=self.replica
                    )
        if aborted:
            # abort() ran between the launch and this bookkeeping; its
            # _live sweep could not see this batch, so its waiters are
            # completed here (same retriable outcome, no thread waits;
            # a copy with a live hedge twin stays silent as everywhere).
            for req in batch:
                if req.drop_copy() > 0:
                    continue
                req.set_error(ReplicaDeadError(
                    f"replica {self.replica or '?'} aborted by the supervisor"
                ))
            return
        self._completions.put(item)

    # -- completion worker ----------------------------------------------------

    def _complete_loop(self) -> None:
        """Read launched batches back and complete their waiters.

        The ONLY place the pipeline blocks on device results — moving
        this read off the dispatch thread is the whole optimization:
        while np.asarray waits on batch N's compute + D2H, the dispatch
        worker is already coalescing and padding batch N+1.
        """
        while True:
            item = self._completions.get()
            if item is None:
                return
            try:
                with span("serving_complete", sink=self._sink,
                          registry=self._registry):
                    # Dormant fault point: chaos 'hang' clauses stall this
                    # read exactly like a wedged device would; 'fail'
                    # clauses model a poisoned result.
                    fault_point("complete", self.replica)
                    host = np.asarray(item.logits)  # jaxlint: disable=JL009 -- the completion worker IS the sanctioned D2H point; this read overlaps the dispatch thread's next batch
            except BaseException as e:
                err: BaseException = e
                if self.replica is not None and not isinstance(e, RejectedError):
                    # Retriable in pool mode: the batch's RESPONSE never
                    # materialized (first-wins completion keeps a late
                    # duplicate read from ever surfacing), so survivors
                    # may rerun the work (serving/server.py).
                    err = ReplicaDeadError(
                        f"replica {self.replica} completion failed: "
                        f"{type(e).__name__}: {e}"
                    )
                    err.__cause__ = e
                # First-wins + live-copy gate: only requests whose
                # outcome THIS failure decided count (a hedge twin that
                # answered — or is still live — on another replica is
                # not a client-visible failure here).
                failed = sum(
                    1 for req in item.batch
                    if req.drop_copy() == 0 and req.set_error(err)
                )
                # Post-abort, this outcome belongs to a DEAD pipeline:
                # the waiters were already errored and retried on
                # survivors, and the replica's breaker now guards a
                # RESTARTED batcher — a late failure striking it would
                # re-open a healthy half-open circuit and march the
                # supervisor's ladder toward a spurious ejection.
                if self.metrics is not None and not self._aborted.is_set() and failed:
                    self.metrics.record_failed(failed)
                if self.on_failure is not None and not self._aborted.is_set():
                    try:
                        self.on_failure(len(item.batch))
                    except Exception:
                        pass  # a hook failure must never kill the worker
            else:
                done = time.perf_counter()
                # Service-time estimate (launch -> read-back) feeding
                # the deadline-aware batch close: the margin a forming
                # batch reserves out of its oldest member's budget.
                dur = done - item.t_launch
                self._service_ewma_s = (
                    dur if self._service_ewma_s is None
                    else 0.2 * dur + 0.8 * self._service_ewma_s
                )
                # Event schema note: the replica tag appears only in
                # pool mode, so single-engine JSONL stays byte-stable.
                tag = {"replica": self.replica} if self.replica else {}
                # A read that unsticks AFTER an abort is not a success
                # of THIS pipeline: the waiters were errored and retried
                # elsewhere (counting here double-counts the outcome),
                # and on_complete -> record_success would close the
                # restarted replica's half-open circuit with zero real
                # trials.  set_result stays — first-wins discards it for
                # already-errored waiters.
                aborted = self._aborted.is_set()
                offset = 0
                for req, start, rows in item.segments:
                    part = host[offset : offset + rows]
                    offset += rows
                    if rows == req.n:
                        # Whole-request segment: the PR-4 fast path.
                        # First-wins gate doubles as the hedge
                        # cancellation accounting (docs/SERVING.md): the
                        # losing replica's read must not re-count the
                        # request on completed/latency families nor feed
                        # on_complete -> the breaker's success side —
                        # exactly one client outcome, counted exactly
                        # once.
                        won = req.set_result(part, by=self.replica)
                    else:
                        # Packed split: copy this part into the
                        # request's assembly buffer; only the LAST part
                        # completes the waiter (bit-identical rows — the
                        # device computed each row independently of its
                        # batch-mates, pinned in tests).
                        if req.done():
                            continue  # settled elsewhere; swept below
                        entry = self._assembly.get(id(req))
                        if entry is None:
                            entry = [
                                req,
                                np.empty(
                                    (req.n, *part.shape[1:]), part.dtype
                                ),
                                0,
                            ]
                            self._assembly[id(req)] = entry
                        entry[1][start : start + rows] = part
                        entry[2] += rows
                        if entry[2] < req.n:
                            continue  # the remainder is still in flight
                        del self._assembly[id(req)]
                        won = req.set_result(entry[1], by=self.replica)
                    latency_s = done - req.t_submit
                    if not won:
                        continue
                    if self.metrics is not None and not aborted:
                        self.metrics.record_completed(
                            latency_s, dtype=req.dtype, qos=req.qos
                        )
                    if self.on_complete is not None and not aborted:
                        try:
                            self.on_complete(latency_s, req.n)
                        except Exception:
                            # A hook failure must never kill the
                            # completion worker: later batches would
                            # sit in _completions forever and every
                            # subsequent client would 504.
                            pass
                    if self._sink and not aborted:
                        self._sink.emit(
                            "serving_request", n=req.n,
                            latency_s=latency_s,
                            dtype=req.dtype,
                            # Schema note: the qos tag appears only for
                            # non-default classes, so pre-QoS JSONL
                            # consumers see an unchanged record.
                            **({"qos": req.qos}
                               if req.qos != DEFAULT_QOS else {}),
                            **tag,
                        )
            finally:
                self._staging.release(item.staged, item.bucket)
                with self._inflight_lock:
                    self._live.discard(item)
                    # max(): abort() may have zeroed the count already
                    # (its phantom-load sweep); an unsticking worker
                    # must not drive it negative.
                    self._inflight = max(0, self._inflight - 1)
                    if self.metrics is not None:
                        self.metrics.set_inflight(
                            self._inflight, replica=self.replica
                        )
                self._window.release()
            if self._sink:
                self._sink.emit(
                    "serving_batch", real=item.n, bucket=item.bucket,
                    fill_ratio=item.n / item.bucket, stall_s=item.stall_s,
                    dtype=item.dtype,
                    **({"replica": self.replica} if self.replica else {}),
                    # Tagged only in packed mode so pre-PR-19 bucketed
                    # JSONL stays byte-stable (the qos schema note above).
                    **({"packed": True} if self.packed else {}),
                )
            # Drop assembly buffers whose request settled elsewhere (a
            # hedge twin answered, or the sibling batch's failure path
            # errored it) — a dead split must not pin its buffer until
            # shutdown.
            if self._assembly:
                for key in [
                    k for k, e in self._assembly.items() if e[0].done()
                ]:
                    del self._assembly[key]
            # Eager expiry on the completion cadence too: when the
            # dispatch worker is parked on a full in-flight window, this
            # is the thread that still runs — queued requests whose
            # deadline passed must not hold their slots (or circuit
            # trial tokens) until the window frees.
            self.sweep_expired()
