"""Dynamic micro-batching with bounded admission and backpressure.

The serving trade: one 64-sample dispatch costs barely more device time
than a 1-sample dispatch (the forward is launch-bound at these shapes),
so coalescing concurrent requests multiplies throughput — but waiting to
coalesce adds latency.  The batcher resolves it the standard way: take
the first queued request, then keep pulling until the batch would exceed
the top bucket or a **linger deadline** (a few ms) passes, whichever
comes first.  Under load, batches fill before the linger expires and
occupancy approaches 100%; when idle, a lone request pays at most the
linger.

Admission is a **bounded** queue: a full queue rejects immediately
(:class:`RejectedError`, the HTTP 503) instead of queueing unboundedly —
queued-forever requests time out anyway and waste the device work, so
shedding at admission is strictly better (the backpressure contract,
docs/SERVING.md).  Each request also carries a deadline; requests that
expire while queued are completed with :class:`RequestTimeout` (504)
without being dispatched.

Shutdown is a graceful drain: ``stop()`` closes admission (new submits
get 503) and, by default, lets the worker finish everything already
admitted before joining.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .engine import InferenceEngine
from .metrics import ServingMetrics


class RejectedError(RuntimeError):
    """Admission refused (queue full or server draining) — HTTP 503."""


class RequestTimeout(RuntimeError):
    """Deadline expired before a result was produced — HTTP 504."""


class PendingRequest:
    """One admitted request: input rows + deadline + a result slot."""

    __slots__ = ("x", "deadline", "t_submit", "_event", "_value", "_error")

    def __init__(self, x: np.ndarray, deadline: float):
        self.x = x
        self.deadline = deadline
        self.t_submit = time.perf_counter()
        self._event = threading.Event()
        self._value: np.ndarray | None = None
        self._error: BaseException | None = None

    @property
    def n(self) -> int:
        return len(self.x)

    def expired(self, now: float | None = None) -> bool:
        return (now if now is not None else time.perf_counter()) > self.deadline

    # -- completion (worker side) -------------------------------------------

    def set_result(self, value: np.ndarray) -> None:
        self._value = value
        self._event.set()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    # -- consumption (handler side) -----------------------------------------

    def result(self, grace_s: float = 1.0) -> np.ndarray:
        """Block until completed; raises the worker's error if it set one.

        Waits until the request deadline plus ``grace_s`` (the worker
        expires overdue requests itself; the grace only covers a dispatch
        already in flight when the deadline passed).
        """
        timeout = max(0.0, self.deadline - time.perf_counter()) + grace_s
        if not self._event.wait(timeout):
            raise RequestTimeout("request deadline expired")
        if self._error is not None:
            raise self._error
        assert self._value is not None
        return self._value


class MicroBatcher:
    """Coalesce admitted requests into bucket-padded engine dispatches.

    Exactly one worker thread touches the engine (jax dispatch is not
    re-entrant here); HTTP handler threads only ``submit()`` and wait.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        metrics: ServingMetrics | None = None,
        max_batch: int | None = None,
        linger_ms: float = 2.0,
        queue_depth: int = 64,
        timeout_ms: float = 1000.0,
    ):
        top = engine.buckets[-1]
        self.engine = engine
        self.metrics = metrics if metrics is not None else engine.metrics
        self.max_batch = min(max_batch or top, top)
        self.linger_s = linger_ms / 1e3
        self.timeout_s = timeout_ms / 1e3
        self._queue: queue.Queue[PendingRequest] = queue.Queue(maxsize=queue_depth)
        self._closed = threading.Event()
        self._worker: threading.Thread | None = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if self._worker is not None:
            raise RuntimeError("batcher already started")
        self._worker = threading.Thread(
            target=self._run, name="micro-batcher", daemon=True
        )
        self._worker.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Close admission; by default let the worker finish the queue.

        ``drain=False`` abandons queued requests — each is completed with
        :class:`RejectedError` so no handler thread is left hanging.
        """
        self._closed.set()
        if not drain:
            self._flush_rejected()
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        # A submit() racing stop() can land a request AFTER the worker saw
        # the empty queue and exited; without this flush that request would
        # sit unserviced until its client's deadline expired (504 during a
        # "graceful" drain).  Post-join the queue is ours alone.
        self._flush_rejected()

    def _flush_rejected(self) -> None:
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            req.set_error(RejectedError("server shutting down"))
            if self.metrics is not None:
                self.metrics.record_rejected()

    def depth(self) -> int:
        """Current admission-queue depth (the /metrics gauge)."""
        return self._queue.qsize()

    # -- admission (any thread) ----------------------------------------------

    def submit(self, x: np.ndarray, timeout_ms: float | None = None) -> PendingRequest:
        """Admit one request of ``[n, 28, 28, 1]`` rows or reject now.

        Raises :class:`RejectedError` when draining, when the request is
        bigger than one maximal batch (it would never fit a dispatch), or
        when the bounded queue is full — the reject-don't-queue
        backpressure contract.
        """
        x = np.asarray(x, np.float32)
        if self._closed.is_set():
            if self.metrics is not None:
                self.metrics.record_rejected()
            raise RejectedError("server draining; not accepting requests")
        if not 1 <= len(x) <= self.max_batch:
            if self.metrics is not None:
                self.metrics.record_rejected()
            raise RejectedError(
                f"request of {len(x)} samples outside [1, {self.max_batch}]"
            )
        timeout_s = self.timeout_s if timeout_ms is None else timeout_ms / 1e3
        req = PendingRequest(x, deadline=time.perf_counter() + timeout_s)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            if self.metrics is not None:
                self.metrics.record_rejected()
            raise RejectedError(
                f"admission queue full ({self._queue.maxsize} deep)"
            ) from None
        if self.metrics is not None:
            self.metrics.record_admitted()
        return req

    # -- worker ----------------------------------------------------------------

    def _expire(self, req: PendingRequest) -> None:
        req.set_error(RequestTimeout("expired in queue before dispatch"))
        if self.metrics is not None:
            self.metrics.record_timeout()

    def _run(self) -> None:
        carry: PendingRequest | None = None
        while True:
            if carry is not None:
                first, carry = carry, None
            else:
                try:
                    first = self._queue.get(timeout=0.05)
                except queue.Empty:
                    if self._closed.is_set():
                        return
                    continue
            if first.expired():
                self._expire(first)
                continue
            batch = [first]
            total = first.n
            # Linger: coalesce until the batch is full or the deadline
            # passes.  A draining batcher skips the linger — nothing new
            # is being admitted, so waiting only delays shutdown.
            deadline = time.perf_counter() + (
                0.0 if self._closed.is_set() else self.linger_s
            )
            while total < self.max_batch:
                remaining = deadline - time.perf_counter()
                try:
                    nxt = (
                        self._queue.get_nowait()
                        if remaining <= 0
                        else self._queue.get(timeout=remaining)
                    )
                except queue.Empty:
                    break
                if nxt.expired():
                    self._expire(nxt)
                    continue
                if total + nxt.n > self.max_batch:
                    carry = nxt  # doesn't fit; leads the next batch
                    break
                batch.append(nxt)
                total += nxt.n
            self._dispatch(batch)

    def _dispatch(self, batch: list[PendingRequest]) -> None:
        xs = (
            batch[0].x
            if len(batch) == 1
            else np.concatenate([r.x for r in batch])
        )
        try:
            logits = self.engine.predict_logits(xs)
        except BaseException as e:  # complete every waiter, then keep serving
            for req in batch:
                req.set_error(e)
            if self.metrics is not None:
                self.metrics.record_failed(len(batch))
            return
        offset = 0
        done = time.perf_counter()
        for req in batch:
            req.set_result(logits[offset : offset + req.n])
            offset += req.n
            if self.metrics is not None:
                self.metrics.record_completed(done - req.t_submit)
