"""Serving CLI: ``python -m pytorch_mnist_ddp_tpu.serving``.

Startup order matters: the persistent XLA compile cache is enabled
FIRST (utils/compile_cache) so the bucket warmup compiles land in — or
load from — the on-disk cache, meaning a restarted server skips the
warmup compile cost entirely on backends where the cache is usable (it
is deliberately disabled on CPU; see compile_cache.py).  Then the engine
loads the checkpoint, warms every bucket exactly once (sentinel-
verified, printed per bucket), and only then does the HTTP socket open —
a server that accepts traffic before warmup would serve its first
requests at compile latency.

``--warmup-only`` stops after the warmup report: the smoke-test mode CI
and operators use to verify the bucket ladder compiles exactly once per
rung before shipping a config.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m pytorch_mnist_ddp_tpu.serving",
        description="MNIST inference server: dynamic micro-batching over "
        "power-of-two shape buckets on the data-parallel mesh "
        "(docs/SERVING.md)",
    )
    parser.add_argument(
        "--checkpoint", default=None,
        help="trained model to serve: a --save-model file (torch/npz) or a "
        "--save-state archive; omitted = fresh seed-init weights (smoke "
        "runs and load tests)",
    )
    parser.add_argument(
        "--seed", type=int, default=1,
        help="init seed when no --checkpoint is given (default 1, the "
        "reference's)",
    )
    parser.add_argument(
        "--registry", default=None, metavar="DIR",
        help="serve from a model registry directory (serving/registry.py): "
        "load the manifest's default (model, version) entry, route the "
        '/predict "model"/"version" fields through the registry, and '
        "expose POST /admin/{swap,canary,rollback} — zero-downtime "
        "weight swap, deterministic canary split, auto-rollback "
        "(docs/SERVING.md).  Mutually exclusive with --checkpoint",
    )
    parser.add_argument(
        "--canary", type=float, default=None, metavar="PCT",
        help="with --registry: start with a live canary serving the "
        "default model's HIGHEST non-default version to PCT%% of "
        "unpinned traffic (same deterministic payload-hash split as "
        "POST /admin/canary)",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument(
        "--buckets", default=None,
        help="comma-separated batch-size ladder (each a power of two, "
        "divisible by the data-axis size); default: powers of two from "
        "the data-axis size to --max-bucket",
    )
    parser.add_argument(
        "--max-bucket", type=int, default=None,
        help="top of the default bucket ladder (default 128)",
    )
    parser.add_argument(
        "--linger-ms", type=float, default=2.0,
        help="max time the batcher waits to coalesce a non-full batch "
        "(the adaptive controller's ceiling)",
    )
    parser.add_argument(
        "--max-inflight", type=int, default=2,
        help="bound on batches launched but not yet read back; 2 overlaps "
        "batch N+1's host work with batch N's device compute, 1 restores "
        "the serial PR-3 pipeline",
    )
    parser.add_argument(
        "--no-adaptive-linger", action="store_true",
        help="pin the linger at --linger-ms instead of shrinking it toward "
        "0 while the admission queue is deep",
    )
    parser.add_argument(
        "--no-deadline-close", action="store_true",
        help="disable deadline-aware batch close: by default a forming "
        "batch dispatches once the OLDEST member's remaining deadline "
        "budget no longer covers the estimated service time, instead of "
        "honoring the global linger (docs/SERVING.md tail latency)",
    )
    parser.add_argument(
        "--qos-weights", default=None, metavar="CLASS=W,...",
        help="weighted-round-robin service shares for the QoS admission "
        "queue (default interactive=4,batch=1); requests pick a class "
        "with the /predict \"qos\" field, and a full queue sheds the "
        "lowest class first (docs/SERVING.md)",
    )
    parser.add_argument(
        "--hedge", action="store_true",
        help="with --replicas: hedged dispatch — re-submit a straggler "
        "request to a second replica once it has waited past its QoS "
        "class's online p99 (or --hedge-delay-ms), first completion "
        "wins with exactly one client-visible outcome",
    )
    parser.add_argument(
        "--hedge-delay-ms", type=float, default=None, metavar="MS",
        help="fixed hedge delay instead of the per-class p99 digest "
        "(implies --hedge; pool mode only)",
    )
    parser.add_argument(
        "--response-cache", type=int, default=None, metavar="N",
        help="enable the content-addressed response cache with "
        "single-flight dedup, bounded at N entries (serving/cache.py): "
        "deterministic inference means identical (weights, dtype, rows) "
        "answer from cache, and concurrent identical requests coalesce "
        "onto one dispatch; keyed on the weights digest so an "
        "engine swap invalidates.  With --fleet the front caches raw "
        "proxied bodies AND the flag propagates to every backend "
        "(both tiers, docs/SERVING.md).  Off by default",
    )
    parser.add_argument(
        "--telemetry-dir", default=None,
        help="write serving JSONL telemetry (serving_request/serving_batch "
        "events, pad/dispatch/complete spans) into this directory "
        "(docs/OBSERVABILITY.md; summarize with tools/perf_report.py "
        "--telemetry)",
    )
    parser.add_argument(
        "--queue-depth", type=int, default=64,
        help="admission queue bound; a full queue rejects with 503",
    )
    parser.add_argument(
        "--timeout-ms", type=float, default=1000.0,
        help="per-request deadline (queued past it -> 504)",
    )
    parser.add_argument(
        "--bf16", action="store_true",
        help="serve the DEFAULT forward in bfloat16 (params stay fp32; "
        "the log_softmax tail is fp32 either way — models/net.py); for "
        "a gated bf16 variant BESIDE the f32 path use --dtypes",
    )
    parser.add_argument(
        "--dtypes", default="f32",
        help="comma-separated serving variants to warm beside the f32 "
        "default (f32,bf16,int8); each reduced-precision variant must "
        "pass its parity gate (logit tolerance + argmax-identical vs "
        "f32 on a fixed eval slice) before the server starts, and "
        "requests select one with the /predict \"dtype\" field "
        "(docs/SERVING.md)",
    )
    parser.add_argument(
        "--aot-cache", default=None, metavar="DIR",
        help="persist per-(dtype, bucket) serialized AOT executables in "
        "DIR (compile/aot.ExecutableStore): a warm start deserializes "
        "every rung instead of tracing (docs/COMPILE.md)",
    )
    parser.add_argument(
        "--replicas", type=int, default=None, metavar="N",
        help="serve N engine replicas, one per device (0 = one per "
        "visible device), behind the queue-aware router "
        "(docs/SERVING.md scale-out); omitted = the single-engine path",
    )
    parser.add_argument(
        "--replica-shapes", default=None, metavar="SPEC",
        help="with --replicas: comma-separated per-replica shard shape, "
        "e.g. 'tp4,dp,dp,dp,dp' — tp/vtp/ep/pp replicas span disjoint "
        "k-device blocks of the visible mesh and are parity-gated "
        "against the single-device reference at warmup; count must "
        "match the replica count (docs/SERVING.md sharded replicas)",
    )
    parser.add_argument(
        "--router-policy", default="cost",
        choices=("roundrobin", "least-loaded", "cost"),
        help="replica placement policy with --replicas: roundrobin "
        "(load-blind baseline), least-loaded (queue depth + in-flight), "
        "or cost (expected time-to-answer from the per-replica latency "
        "EWMA; falls back to least-loaded until samples exist)",
    )
    parser.add_argument(
        "--fleet", type=int, default=None, metavar="N",
        help="run a multi-PROCESS serving fleet (docs/SERVING.md fleet "
        "section): this process becomes a jax-free front tier on --port "
        "that spawns N backend serving processes (each this same CLI on "
        "--fleet-base-port+i, sharing one AOT cache so replacements "
        "warm-start), routes /predict to them by --router-policy, "
        "liveness-probes and REPLACES dead or wedged backends under a "
        "seeded backoff restart budget, and (with --autoscale) "
        "adds/drains whole backends from the load signal",
    )
    parser.add_argument(
        "--fleet-base-port", type=int, default=None, metavar="PORT",
        help="first backend port with --fleet (default --port + 1; "
        "backend i listens on base+i, a replacement reuses its port)",
    )
    parser.add_argument(
        "--fleet-restart-budget", type=int, default=3,
        help="consecutive failed backend replacements before a backend "
        "is permanently ejected from the fleet",
    )
    parser.add_argument(
        "--fleet-heartbeat-timeout-s", type=float, default=10.0,
        help="a backend whose dispatch-loop heartbeat file is older "
        "than this is treated as wedged and replaced (0 disables; "
        "process death and /readyz probes still apply)",
    )
    parser.add_argument(
        "--fleet-ready-timeout-s", type=float, default=300.0,
        help="bring-up bound per backend (cold warmup on CPU is slow; "
        "warm AOT starts are seconds)",
    )
    parser.add_argument(
        "--autoscale", action="store_true",
        help="with --fleet: add a backend when the smoothed per-backend "
        "backlog breaches --scale-high for --scale-window-s, drain the "
        "newest at --scale-low (drain -> settle -> kill, nothing "
        "lost), with cooldown hysteresis and --scale-min/--scale-max "
        "bounds",
    )
    parser.add_argument(
        "--scale-high", type=float, default=8.0, metavar="DEPTH",
        help="autoscaler high-water mark: smoothed mean backlog "
        "(queue depth + in-flight) per active backend",
    )
    parser.add_argument(
        "--scale-low", type=float, default=1.0, metavar="DEPTH",
        help="autoscaler low-water mark (must be < --scale-high; the "
        "gap is the hysteresis band)",
    )
    parser.add_argument("--scale-min", type=int, default=1)
    parser.add_argument("--scale-max", type=int, default=4)
    parser.add_argument(
        "--scale-window-s", type=float, default=2.0,
        help="a watermark breach must sustain this long before acting",
    )
    parser.add_argument(
        "--scale-cooldown-s", type=float, default=10.0,
        help="minimum quiet time after any scale event",
    )
    parser.add_argument(
        "--request-timeout-s", type=float, default=30.0,
        help="handler-connection socket timeout: a client that connects "
        "and goes silent is closed (or answered 408 mid-body) within "
        "this bound instead of pinning a handler thread forever",
    )
    parser.add_argument(
        "--no-supervise", action="store_true",
        help="with --replicas: disable the replica supervisor "
        "(quarantine / backoff restart / ejection of replicas that "
        "fail, hang, or trip their circuit breaker — docs/ROBUSTNESS.md)",
    )
    parser.add_argument(
        "--stall-timeout-s", type=float, default=5.0,
        help="supervisor completion-stall threshold: a replica whose "
        "oldest in-flight batch is older than this is quarantined "
        "(a wedged device or hung D2H read)",
    )
    parser.add_argument(
        "--restart-budget", type=int, default=3,
        help="consecutive failed supervisor restarts before a replica "
        "is permanently ejected from the pool",
    )
    parser.add_argument(
        "--no-device-stage", action="store_true",
        help="disable committing padded batches to the data-axis "
        "sharding (async device_put) before dispatch; staging is on by "
        "default on single-process meshes (docs/DATA.md)",
    )
    parser.add_argument(
        "--conv-impl", default="conv",
        help="convolution lowering, as in training (models/net.py "
        "CONV_IMPLS)",
    )
    parser.add_argument(
        "--packed", action="store_true",
        help="packed ragged batching (docs/SERVING.md): concatenate "
        "requests into one rows-capacity buffer + segment-id vector "
        "instead of padding each batch to its pow2 bucket — collapses "
        "the executable ladder to the top capacity and drives fill "
        "toward 1.0 (the PR-19 device hot-path floor)",
    )
    parser.add_argument(
        "--fill-wait-ms", type=float, default=None,
        help="packed mode only: how long a forming batch may wait for "
        "more rows before dispatching part-full (replaces the linger "
        "ceiling; the adaptive controller still shrinks it under deep "
        "queue, where batches fill by splitting anyway)",
    )
    parser.add_argument(
        "--int8-impl", default="dot", choices=("dot", "pallas"),
        help="int8 dense-head lowering: 'dot' = reference "
        "lax.dot_general GEMMs, 'pallas' = fused "
        "dequant-matmul-bias-relu-matmul kernel (ops/pallas_infer.py); "
        "'pallas' falls back to 'dot' with a warning off-TPU unless "
        "TPU_MNIST_PALLAS_INTERPRET=1",
    )
    parser.add_argument(
        "--cache-dir", default=None,
        help="persistent XLA compile cache directory (default: the "
        "JAX_COMPILATION_CACHE_DIR env var, else the utils/cache_dir "
        "root); naming one explicitly also enables the cache on the CPU "
        "backend, which is otherwise skipped — same operator-intent "
        "semantics as the trainer CLIs' --compile-cache-dir",
    )
    parser.add_argument(
        "--warmup-only", action="store_true",
        help="compile + verify every bucket, print the sentinel report, "
        "exit without opening the HTTP socket",
    )
    parser.add_argument(
        "--serial-warmup", action="store_true",
        help="warm the bucket ladder one rung at a time instead of "
        "fanning all buckets out over the background compile service "
        "(docs/COMPILE.md); deterministic compile order, slower startup",
    )
    return parser


def main(argv: list[str] | None = None) -> int:
    raw_argv = list(sys.argv[1:]) if argv is None else list(argv)
    args = build_parser().parse_args(raw_argv)

    if args.response_cache is not None and args.response_cache < 1:
        print(f"error: --response-cache must be >= 1, got "
              f"{args.response_cache}")
        return 2
    # Registry flag surface (fail in milliseconds, before any jax
    # import or warmup).  With --fleet both flags propagate to every
    # backend unchanged (they are not in fleet.py's front-only strip
    # lists); the jax-free front itself ignores them.
    if args.registry and args.checkpoint:
        print("error: --registry and --checkpoint are mutually exclusive "
              "(the registry's manifest names the checkpoint)")
        return 2
    if args.canary is not None:
        if not args.registry:
            print("error: --canary needs --registry (the canary version "
                  "comes from the manifest)")
            return 2
        if not 0.0 < args.canary <= 100.0:
            print(f"error: --canary must be in (0, 100], got "
                  f"{args.canary:g}")
            return 2
    if args.fleet is not None:
        # The fleet front is a pure control plane + proxy: no engine, no
        # checkpoint, no jax — it must come up instantly and keep
        # working when a backend (the part that owns devices) is the
        # part that is broken.  Delegate BEFORE any jax import.
        if args.fleet < 1:
            print(f"error: --fleet must be >= 1, got {args.fleet}")
            return 2
        if args.autoscale and args.scale_low >= args.scale_high:
            print(
                f"error: --scale-low {args.scale_low:g} must be < "
                f"--scale-high {args.scale_high:g} (the hysteresis band)"
            )
            return 2
        if args.autoscale and not (
            1 <= args.scale_min <= args.fleet <= args.scale_max
        ):
            # Pre-flight, not after minutes of backend bring-up: the
            # autoscaler constructor would reject these anyway, but only
            # once every backend has already warmed.
            print(
                f"error: need 1 <= --scale-min ({args.scale_min}) <= "
                f"--fleet ({args.fleet}) <= --scale-max ({args.scale_max})"
            )
            return 2
        if args.warmup_only:
            # Passed through, every backend would warm, exit 0, and the
            # front would report an opaque bring-up failure.
            print("error: --warmup-only is a backend concern; run it "
                  "without --fleet")
            return 2
        from .fleet import run_fleet

        return run_fleet(args, raw_argv)

    # Deferred import: utils/__init__ pulls jax, and the fleet branch
    # above must stay jax-free (the front is up in milliseconds and
    # survives a broken jax install — serving/fleet.py).
    from ..utils.compile_cache import enable_persistent_cache

    # Satellite wiring: the cache must be configured before the first jit
    # compile or the warmup programs miss it.  Log the directory actually
    # in use — "it should be cached" bugs are undebuggable without it.
    cache_dir = enable_persistent_cache(
        args.cache_dir, force=args.cache_dir is not None
    )
    if cache_dir:
        print(f"persistent compile cache: {cache_dir}")
    else:
        print(
            "persistent compile cache: disabled "
            "(cpu backend, or cache dir not writable)"
        )

    import jax.numpy as jnp

    from .engine import InferenceEngine
    from .metrics import ServingMetrics
    from .server import make_server

    metrics = ServingMetrics()
    dtypes = [d.strip() for d in args.dtypes.split(",") if d.strip()]
    if args.bf16 and any(d != "f32" for d in dtypes):
        # The gates need an f32 reference; a bf16 DEFAULT forward would
        # anchor them on bf16 error (engine rejects this too — fail at
        # the flag surface with the flag-level fix).
        print(
            "error: --bf16 (bf16 DEFAULT forward) cannot combine with "
            "--dtypes variants — the parity gates would lose their f32 "
            "reference; drop --bf16 and add bf16 to --dtypes instead"
        )
        return 2
    # Flag-surface validation BEFORE the (expensive) engine build +
    # warmup: a config error must fail in milliseconds, not minutes.
    qos_weights = None
    if args.qos_weights:
        from .qos import QOS_CLASSES

        try:
            qos_weights = {
                name.strip(): int(w)
                for name, w in (
                    part.split("=") for part in args.qos_weights.split(",")
                )
            }
        except ValueError:
            print(
                f"error: --qos-weights {args.qos_weights!r} must be "
                "CLASS=INT[,CLASS=INT...] (e.g. interactive=4,batch=1)"
            )
            return 2
        unknown = sorted(set(qos_weights) - set(QOS_CLASSES))
        bad = sorted(n for n, w in qos_weights.items() if w < 1)
        if unknown or bad:
            # A typo'd class name would silently fall out of the weight
            # map and the intended class would clamp to weight 1 — the
            # operator gets WORSE scheduling than the default with zero
            # diagnostic.  Fail at the flag surface instead.
            print(
                f"error: --qos-weights {args.qos_weights!r}: "
                + (f"unknown class(es) {unknown} "
                   f"(have {list(QOS_CLASSES)})" if unknown else "")
                + ("; " if unknown and bad else "")
                + (f"weight(s) must be >= 1 for {bad}" if bad else "")
            )
            return 2
    hedge = args.hedge or args.hedge_delay_ms is not None
    if hedge and (args.replicas is None or args.replicas == 1):
        # --replicas 0 (one per visible device) may still resolve to a
        # single device; the banner below reports the resolved truth.
        print("error: --hedge/--hedge-delay-ms need --replicas >= 2 (a "
              "lone replica has no second replica to hedge onto)")
        return 2
    engine_kwargs = dict(
        buckets=(
            [int(b) for b in args.buckets.split(",")] if args.buckets else None
        ),
        max_bucket=None if args.buckets else args.max_bucket,
        compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        conv_impl=args.conv_impl,
        metrics=metrics,
        dtypes=[d for d in dtypes if d != "f32"],
        aot_cache=args.aot_cache,
        device_stage=False if args.no_device_stage else None,
        packed=args.packed,
        int8_impl=args.int8_impl,
    )
    pool_mode = args.replicas is not None
    if pool_mode:
        # Scale-out (docs/SERVING.md): N per-device engine replicas
        # behind the queue-aware router; 0 = one per visible device.
        from .pool import EnginePool

        factory = EnginePool
        engine_kwargs["replicas"] = args.replicas or None
        if args.replica_shapes:
            engine_kwargs["replica_shapes"] = args.replica_shapes
    else:
        factory = InferenceEngine
        if args.replica_shapes:
            print("error: --replica-shapes needs --replicas (a sharded "
                  "replica is a pool member; docs/SERVING.md)")
            return 2
    registry = entry = canary_version = None
    if args.registry:
        # Registry mode (docs/SERVING.md model registry): the manifest's
        # default alias names what this process serves; the engine pins
        # that version so its Program grid keys under it in the shared
        # AOT store (per-version grids coexist — warm swaps).
        from .registry import ModelRegistry

        registry = ModelRegistry(args.registry)
        try:
            entry = registry.resolve()
            if args.canary is not None:
                candidates = [
                    v for v in registry.versions(entry.model)
                    if v != entry.version
                ]
                if not candidates:
                    print(
                        f"error: --canary needs a second registered "
                        f"version of {entry.model!r}; the manifest only "
                        f"has {entry.version!r}"
                    )
                    return 2
                canary_version = candidates[-1]
            print(
                f"registry {args.registry}: serving "
                f"{entry.model}@{entry.version} "
                f"(digest {entry.digest[:12]})"
            )
            engine_kwargs["version"] = entry.version
            engine = factory(registry.load(entry), **engine_kwargs)
        except ValueError as e:
            print(f"error: --registry {args.registry}: {e}")
            return 2
    elif args.checkpoint:
        print(f"loading checkpoint {args.checkpoint}")
        engine = factory.from_checkpoint(args.checkpoint, **engine_kwargs)
    else:
        print(
            f"no --checkpoint; serving fresh seed-{args.seed} weights "
            "(smoke/load-test mode)"
        )
        engine = factory.from_seed(args.seed, **engine_kwargs)

    from ..obs.events import open_sink
    from ..obs.spans import span

    sink = open_sink(args.telemetry_dir)
    if sink:
        print(f"serving telemetry: {sink.path}")

    if pool_mode:
        print(
            f"warming buckets {list(engine.buckets)} x dtypes "
            f"{list(engine.dtypes)} x {engine.n_replicas} replicas "
            f"(devices {[str(d) for d in engine.devices]})"
            + (" (BatchNorm checkpoint)" if engine.use_bn else "")
            + (f" (shared AOT cache {args.aot_cache})" if args.aot_cache else "")
        )
    else:
        print(
            f"warming buckets {list(engine.buckets)} x dtypes "
            f"{list(engine.dtypes)} "
            f"{'serially' if args.serial_warmup else 'concurrently'} on a "
            f"{engine.mesh.devices.size}-device mesh"
            + (" (BatchNorm checkpoint)" if engine.use_bn else "")
            + (f" (AOT cache {args.aot_cache})" if args.aot_cache else "")
        )
    # The warmup span + the compile service's per-bucket compile spans
    # land in the JSONL telemetry (and span_duration_seconds on the
    # registry /metrics serves), so cold-start cost is observable.
    with span("warmup", sink=sink, registry=metrics.registry):
        if pool_mode:
            engine.warmup(
                on_rung=lambda replica, dtype, bucket, compiles: print(
                    f"  [{replica}] {dtype:>4s} bucket {bucket:4d}: ready "
                    f"({compiles} traces total)", flush=True
                ),
                parallel=not args.serial_warmup,
                sink=sink,
            )
        else:
            engine.warmup(
                on_rung=lambda dtype, bucket, compiles: print(
                    f"  {dtype:>4s} bucket {bucket:4d}: ready "
                    f"({compiles} traces total)", flush=True
                ),
                parallel=not args.serial_warmup,
                sink=sink,
            )
    n_replicas = engine.n_replicas if pool_mode else 1
    if args.aot_cache:
        # AOT mode: executables deserialize (or compile+persist) outside
        # the jit cache — there is no second-pass sweep to claim, and
        # zero traces is the success condition.
        print(
            "warmup verified: "
            f"{n_replicas * len(engine.buckets) * len(engine.dtypes)} "
            f"AOT executables ready ({len(engine.buckets)} buckets x "
            f"{len(engine.dtypes)} dtypes"
            + (f" x {n_replicas} replicas" if pool_mode else "")
            + f"), {engine.compile_count()} traces"
        )
    else:
        print(
            f"warmup verified: {engine.compile_count()} traces for "
            f"{len(engine.buckets)} buckets x {len(engine.dtypes)} dtypes"
            + (f" x {n_replicas} replicas" if pool_mode else "")
            + ", second pass hit the cache (sentinel-enforced)"
        )
    # Parity gates (docs/SERVING.md): every reduced-precision variant
    # must be argmax-identical to f32 within its logit tolerance on the
    # fixed eval slice, or the server REFUSES to start — serving an
    # unverified variant is the failure mode the gate exists to prevent.
    gates = engine.verify_parity(sink=sink)
    for name, result in gates.items():
        print(
            f"parity gate [{name}]: "
            + ("PASS" if result["passed"] else "FAIL")
            + f" (max|dlogit| {result['max_abs_logit_diff']:.2e} <= "
            f"{result['tolerance']:g}, argmax_identical="
            f"{result['argmax_identical']}, {result['rows']} rows)"
        )
    failed = [name for name, r in gates.items() if not r["passed"]]
    if failed:
        print(
            f"refusing to serve: variants {failed} failed their parity "
            "gate (near-untrained weights put real ties inside the "
            "quantization error; serve a trained checkpoint, or drop "
            "the variant from --dtypes)"
        )
        sink.close()
        return 1
    if args.warmup_only:
        sink.close()
        return 0
    # Fleet liveness (docs/SERVING.md): when a fleet front spawned this
    # backend it exported SERVE_HEARTBEAT_FILE; the batcher dispatch
    # loop(s) beat it, so a wedged loop is detectable by mtime age.
    # Flagless runs build nothing.
    from ..liveness import Heartbeat
    from .fleet import ENV_FLEET_HEARTBEAT_FILE

    hb = Heartbeat.from_env(ENV_FLEET_HEARTBEAT_FILE)
    batcher_kwargs = dict(
        linger_ms=args.linger_ms,
        queue_depth=args.queue_depth,
        timeout_ms=args.timeout_ms,
        max_inflight=args.max_inflight,
        adaptive_linger=not args.no_adaptive_linger,
        deadline_aware=not args.no_deadline_close,
        qos_weights=qos_weights,
        heartbeat=hb.beat if hb is not None else None,
        fill_wait_ms=args.fill_wait_ms,
    )
    rollout = None
    if registry is not None:
        from .rollout import RolloutController

        rollout = RolloutController(
            registry, engine, metrics=metrics, sink=sink,
        )
    if pool_mode:
        router = engine.start(
            router_policy=args.router_policy, sink=sink,
            supervise=not args.no_supervise,
            supervisor_kwargs=dict(
                stall_timeout_s=args.stall_timeout_s,
                restart_budget=args.restart_budget,
            ),
            hedge=hedge,
            hedge_delay_ms=args.hedge_delay_ms,
            **batcher_kwargs,
        )
        server = make_server(
            engine, metrics, host=args.host, port=args.port, batcher=router,
            request_timeout_s=args.request_timeout_s,
            response_cache=args.response_cache, sink=sink, rollout=rollout,
        )
    else:
        server = make_server(
            engine, metrics, host=args.host, port=args.port,
            sink=sink, request_timeout_s=args.request_timeout_s,
            response_cache=args.response_cache, rollout=rollout,
            **batcher_kwargs,
        )
    if rollout is not None and canary_version is not None:
        # Startup canary (--canary PCT): same path as POST /admin/canary
        # — pinned variants installed (zero traces), breaker armed, the
        # divergence probe already run.
        rollout.start_canary(canary_version, args.canary)
        print(
            f"canary: {entry.model}@{canary_version} at "
            f"{args.canary:g}% of unpinned traffic (deterministic "
            "payload-hash split, auto-rollback armed)"
        )
    if args.response_cache:
        # Printed only when the flag is set: flagless stdout stays
        # byte-identical (the PR-4 contract).
        print(
            f"response cache: {args.response_cache} entries "
            f"(weights digest {engine.weights_digest[:12]}, "
            "single-flight dedup on)"
        )
    host, port = server.server_address[:2]
    print(
        f"serving on http://{host}:{port} (POST /predict, GET /metrics, "
        "GET /healthz liveness, GET /readyz readiness; "
        + (f"{engine.n_replicas} replicas, router policy "
           f"{args.router_policy}, supervisor "
           f"{'off' if args.no_supervise else 'on'}, hedging "
           # Report the RESOLVED truth: the router silently disables
           # hedging on a 1-replica pool (--replicas 0 on a 1-device
           # host), and a banner claiming "on" would mislabel the A/B.
           + ("off, " if not (hedge and engine.n_replicas > 1) else (
               f"on ({args.hedge_delay_ms:g} ms), "
               if args.hedge_delay_ms is not None else "on (p99 digest), "
           ))
           + "per-replica "
           if pool_mode else "")
        + f"in-flight window {args.max_inflight}, adaptive linger "
        f"{'off' if args.no_adaptive_linger else 'on'}, deadline close "
        f"{'off' if args.no_deadline_close else 'on'})"
    )

    def _shutdown(signum, frame):
        # serve_forever must be unblocked from another thread; the drain
        # itself runs below, after the accept loop exits.
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    try:
        server.serve_forever()
    finally:
        # Graceful drain: stop accepting, finish everything admitted,
        # then report.  (Handler threads for in-flight requests are
        # daemons; their waiters complete during the drain.)
        print("draining admitted requests and the in-flight window...")
        if pool_mode:
            engine.stop(drain=True)  # supervisor first, then the router
        else:
            server.batcher.stop(drain=True)
        server.server_close()
        sink.close()
        print(metrics.report_lines(
            queue_depth=server.batcher.depth(),
            compiles=engine.compile_count(),
            buckets=engine.buckets,
            inflight=server.batcher.inflight(),
            max_inflight=server.batcher.max_inflight,
            linger_ms=server.batcher.current_linger_ms,
        ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
