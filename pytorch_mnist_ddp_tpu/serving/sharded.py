"""Sharded serving replicas: the shard-kind registry.

One logical serving replica can span a k-device mesh (parallel/mesh.py
``replica_mesh``): tensor parallel for the CNN and ViT families, expert
parallel for MoE, pipeline parallel for depth.  This module is the single
table the engine (serving/engine.py) consults per ``shard_kind`` — which
predict-step builder to jit, how to place the host params onto the
replica mesh, which single-device forward anchors the parity gate, and
how tight that gate is.  Keeping the table OUT of the engine keeps the
engine's variant/sentinel/Program machinery shard-agnostic: a sharded
engine differs from a DP engine only in its mesh, its placed tree, and
its default forward.

Parity expectations (measured on this repo's models, pinned by
tests/test_sharded.py):

- **tp / vtp**: the row-parallel psum re-associates the reduction over
  the sharded contraction dim, so outputs are ~1e-7 from the
  single-device forward — gated at 1e-5 + argmax-identical.
- **pp**: the pipeline runs the exact same op sequence per microbatch
  (conv stack then dense head), so outputs are bit-identical — gated at
  0.0.
- **ep**: per-token expert math is slot-order independent, so with no
  capacity drops outputs are bit-identical; capacity is per routing
  GROUP (each device's row shard) versus the dense forward's one global
  group, so at the capacity edge the two may drop different tokens and
  the gate legitimately refuses — serve EP with capacity-factor headroom
  (docs/SERVING.md).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

from ..models.vit import ViTConfig
from ..parallel.mesh import SHARD_KINDS, replica_mesh  # noqa: F401 (re-export)

# Parity-gate tolerance (max |logp_sharded - logp_reference|) per kind,
# plus argmax-identity on every row — the verify_parity discipline
# applied to the shard topology instead of a dtype.
SHARDED_PARITY_TOL = {"tp": 1e-5, "vtp": 1e-5, "pp": 0.0, "ep": 1e-5}

# The ViT/MoE configs a sharded engine serves when the caller doesn't
# pin one (from_seed smoke paths).  EP's capacity factor is 4.0, NOT the
# training default 2.0: serving routes untrained-to-lightly-trained
# distributions whose gate imbalance would drop tokens at 2.0, and a
# dropped token is a parity failure by design (see module docstring).
DEFAULT_VIT_CFG = ViTConfig()
DEFAULT_MOE_CFG = ViTConfig(num_experts=4, capacity_factor=4.0)


def default_vit_cfg(kind: str) -> ViTConfig:
    return DEFAULT_MOE_CFG if kind == "ep" else DEFAULT_VIT_CFG


def validate_family(kind: str, params: dict) -> None:
    """Refuse a param tree from the wrong model family LOUDLY at
    construction — the alternative is a shape error deep inside a
    shard_map trace."""
    is_vit = "blocks" in params
    if kind in ("tp", "pp"):
        if is_vit or "fc1" not in params:
            raise ValueError(
                f"shard kind {kind!r} serves the CNN family "
                "(conv1/conv2/fc1/fc2 params); got a "
                f"{'ViT' if is_vit else 'foreign'} tree"
            )
    elif kind in ("vtp", "ep"):
        if not is_vit:
            raise ValueError(
                f"shard kind {kind!r} serves the ViT family "
                "(blocks/<i> params); got a foreign tree"
            )
        if kind == "ep" and "moe" not in params["blocks"]["0"]:
            raise ValueError(
                "shard kind 'ep' serves the MoE-ViT family; the given "
                "ViT tree has dense MLP blocks (use 'vtp')"
            )
        if kind == "vtp" and "moe" in params["blocks"]["0"]:
            raise ValueError(
                "shard kind 'vtp' serves the dense ViT family; the "
                "given tree has MoE blocks (use 'ep')"
            )


def seed_params(kind: str, key, vit_cfg: ViTConfig | None = None) -> dict:
    """Fresh reference-init params of the family ``kind`` serves — the
    no-checkpoint smoke path (engine.from_seed / pool.from_seed)."""
    if kind in ("vtp", "ep"):
        from ..models.vit import init_vit_params

        return init_vit_params(key, vit_cfg or default_vit_cfg(kind))
    from ..models.net import init_params

    return init_params(key)


def place_params(kind: str, params: dict, mesh, vit_cfg: ViTConfig | None):
    """Place host params onto the replica mesh with the kind's specs."""
    from ..parallel.mesh import place_tree

    if kind == "tp":
        from ..parallel.tp import param_specs

        return place_tree(params, param_specs(), mesh)
    if kind == "vtp":
        from ..parallel.tp_vit import vit_tp_param_specs

        return place_tree(params, vit_tp_param_specs(vit_cfg), mesh)
    if kind == "ep":
        from ..parallel.ep import ep_param_specs

        return place_tree(params, ep_param_specs(vit_cfg), mesh)
    if kind == "pp":
        from ..parallel.ddp import replicate_params

        return replicate_params(params, mesh)
    raise ValueError(f"unknown shard kind {kind!r}")


def build_predict_fn(
    kind: str,
    mesh,
    *,
    vit_cfg: ViTConfig | None = None,
    pp_microbatches: int = 2,
    packed: bool = False,
):
    """The kind's jitted serving forward.

    Unpacked: ``fn(params, x) -> logp`` (``(logp, expert_load)`` for
    ``ep``).  Packed adds the segment-id vector and masks padding rows
    to exactly 0.0, the ``make_packed_predict_step`` contract — the mask
    composes OUTSIDE the shard_map (on the already-gathered logp, with
    ``seg_ids`` placed against the sharded data axis), so one wrapper
    serves every kind."""
    import jax.numpy as jnp

    if kind == "tp":
        from ..parallel.tp import make_tp_predict_step

        base = make_tp_predict_step(mesh)
    elif kind == "vtp":
        from ..parallel.tp_vit import make_vit_tp_predict_step

        base = make_vit_tp_predict_step(mesh, vit_cfg)
    elif kind == "ep":
        from ..parallel.ep import make_ep_predict_step

        base = make_ep_predict_step(mesh, vit_cfg)
    elif kind == "pp":
        from ..parallel.pp import make_pp_predict_step

        base = make_pp_predict_step(mesh, num_micro=pp_microbatches)
    else:
        raise ValueError(f"unknown shard kind {kind!r}")
    if not packed:
        return base
    if kind == "ep":

        def packed_fn(params, x, seg_ids):
            logp, load = base(params, x)
            return jnp.where(seg_ids[:, None] >= 0, logp, 0.0), load

    else:

        def packed_fn(params, x, seg_ids):
            logp = base(params, x)
            return jnp.where(seg_ids[:, None] >= 0, logp, 0.0)

    return jax.jit(packed_fn)


def reference_fn(kind: str, vit_cfg: ViTConfig | None):
    """The single-device forward the sharded parity gate compares
    against: ``ref(host_params, x) -> logp`` — the same functions the
    DP engine / single-device eval paths serve, jitted on the default
    device.  Gate-time only (one extra compile per gated engine), never
    on the dispatch path."""
    if kind in ("tp", "pp"):
        from ..models.net import Net

        model = Net()

        def fwd(params, x):
            return model.apply({"params": params}, x, train=False)

    elif kind == "vtp":
        from ..models.vit import vit_forward

        cfg = vit_cfg

        def fwd(params, x):
            return vit_forward(params, x, cfg)

    elif kind == "ep":
        from ..models.vit import vit_moe_forward

        cfg = vit_cfg

        def fwd(params, x):
            return vit_moe_forward(params, x, cfg)[0]

    else:
        raise ValueError(f"unknown shard kind {kind!r}")
    return jax.jit(fwd)


def expert_imbalance(load: np.ndarray) -> float:
    """max/mean of the per-expert kept-token counts — 1.0 is perfectly
    balanced, E is total collapse onto one expert.  The scalar
    perf_report and the SLO narrative quote."""
    load = np.asarray(load, np.float64)
    mean = float(load.mean())
    if mean <= 0.0:
        return 0.0
    return float(load.max() / mean)


def shard_devices(mesh) -> list[Any]:
    """The replica's device list in mesh order (the
    ``serving_shard_devices`` gauge value is its length)."""
    return list(mesh.devices.flat)
