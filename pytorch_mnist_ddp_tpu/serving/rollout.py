"""Rollout control: zero-downtime weight swap, canary split, rollback.

The registry (serving/registry.py) is the durable catalog; this module
is the live-traffic half — the state machine that moves a fleet from
version A to version B without dropping, duplicating, or TEARING a
request (a response computed partly on old weights, partly on new).

Three verbs, all admin-triggered (server.py ``/admin/*``, forwarded
per-backend by the fleet tier):

**swap(version)** — republish the primary served weights in place.  The
engine reassigns each variant's weight reference atomically
(engine.publish_weights: a dispatch reads the reference exactly once,
so in-flight batches complete on the old tree and the next dispatch
reads the new one — bit-coherent by construction), the response cache's
generation is bumped with the new digest so no stale logits serve, and
the registry's default alias moves in one atomic manifest write.  Zero
compiles: executables are shape-keyed and take weights as call
arguments, and per-version Program grids share those shapes.

**start_canary(version, pct)** — serve VERSION to a deterministic
``pct``% slice of unpinned traffic beside the primary.  The engine
installs ``{dtype}@{version}`` twins (engine.install_version — shared
sentinels and Program grids, zero traces; the batcher coalesces by
variant key, so no batch ever mixes versions).  Assignment is
:func:`canary_assignment` — a seeded blake2b over the request payload,
so the split is reproducible across replicas, restarts, and the
load generator's own bookkeeping (tools/serve_loadgen.py recomputes the
EXACT expected split).  Explicit ``version`` pins bypass the split.

**rollback(reason)** — remove the canary variants and return all
traffic to the primary.  Fired by the operator, or AUTOMATICALLY by the
canary's own :class:`~.circuit.CircuitBreaker` when its error rate
trips the budget, or by the parity-drift probe
(engine.version_divergence) exceeding ``divergence_budget``.  Emits the
``rollback`` event either way — an unexplained traffic shift is an
incident, an evented one is a log line.

Observability: ``serving_model_requests_total{model=,version=}`` and
``serving_model_latency_seconds{...}`` per served route, plus
``model_swap`` / ``canary_step`` / ``rollback`` events
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import hashlib

from ..analysis.lockwatch import make_lock
from .circuit import CIRCUIT_OPEN, CircuitBreaker
from .engine import VERSION_SEP
from .registry import RegistryError

# Default canary-assignment seed.  Fixed (not random) so every replica
# of a fleet — and the load generator auditing the split — agrees on
# the assignment of every payload without coordination.
CANARY_SEED = 20260806


class RolloutError(RegistryError):
    """A rollout transition that cannot proceed (no canary active,
    version not loaded, cross-model canary).  Subclasses RegistryError
    -> ValueError, so the server's 400 mapping already handles it."""


def canary_assignment(
    payload: bytes, pct: float, seed: int = CANARY_SEED
) -> bool:
    """Deterministically assign a request payload to the canary slice.

    Seeded blake2b over the raw payload bytes -> uniform fraction of
    2**64; True when it lands below ``pct``/100.  Properties the rollout
    depends on: the same payload routes the SAME way on every replica
    (a fleet splits coherently with no shared state), raising ``pct``
    only GROWS the slice (a request in the 5% slice is in the 25% one,
    so a canary ramp never flip-flops users), and the split is exactly
    reproducible offline (tools/serve_loadgen.py verifies it to the
    request)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(int(seed).to_bytes(8, "little", signed=True))
    h.update(payload)
    fraction = int.from_bytes(h.digest(), "little") / 2.0**64
    return fraction < float(pct) / 100.0


class Route:
    """One resolved routing decision for one request."""

    __slots__ = ("model", "version", "canary", "pinned")

    def __init__(self, model, version, canary=False, pinned=False):
        self.model = model
        self.version = version
        self.canary = canary    # served by a version-pinned variant
        self.pinned = pinned    # client named the version explicitly

    def dtype_key(self, dtype: str) -> str:
        """The engine variant key this route dispatches on: the base
        dtype for the primary, ``{dtype}@{version}`` for the canary —
        which is also what keeps canary rows out of primary batches
        (the batcher coalesces by key) and canary responses out of
        primary cache entries (the key joins the cache key)."""
        return (
            f"{dtype}{VERSION_SEP}{self.version}" if self.canary else dtype
        )


class RolloutController:
    """The per-process rollout state machine over (registry, engine).

    Thread-safety: route()/observe() run on every request thread while
    swap/canary/rollback arrive on admin threads; all shared state
    lives under one lock, and the engine/cache calls inside transitions
    are themselves atomic at the reference-swap level, so request
    threads never observe a half-applied transition.
    """

    def __init__(
        self,
        registry,
        engine,
        *,
        cache=None,
        metrics=None,
        sink=None,
        seed: int = CANARY_SEED,
        failure_threshold: int = 3,
        divergence_budget: float | None = None,
    ):
        self.registry = registry
        self.engine = engine
        self.cache = cache
        self.metrics = metrics
        self.sink = sink
        self.seed = int(seed)
        self.failure_threshold = int(failure_threshold)
        # Max |dlogit| the canary may drift from the primary on the
        # fixed parity slice before auto-rollback.  None (default) =
        # probe-only: a genuinely retrained version LEGITIMATELY moves
        # logits, so an always-on budget would roll back every real
        # update.  Set a budget when the rollout is a should-be-
        # equivalent artifact (requantization, recompression, a format
        # migration) — there, drift past the budget means the artifact
        # is not the model that was validated.
        self.divergence_budget = (
            None if divergence_budget is None else float(divergence_budget)
        )
        self._lock = make_lock("rollout.state")
        entry = registry.resolve()
        self._model = entry.model
        self._version = entry.version
        self._canary_version: str | None = None
        self._canary_pct = 0.0
        self._breaker: CircuitBreaker | None = None
        if metrics is not None:
            metrics.ensure_model(entry.model, entry.version)

    # -- request path ---------------------------------------------------------

    def route(
        self,
        model: str | None = None,
        version: str | None = None,
        payload: bytes | None = None,
    ) -> Route:
        """Resolve one request's (model, version) fields to a served
        route.  Absent fields resolve through the registry's default
        aliases — byte-identical to pre-registry behavior.  An explicit
        ``version`` pins (bypassing the canary split); an absent one
        joins the deterministic split when a canary is live."""
        entry = self.registry.resolve(model, version)
        with self._lock:
            if entry.model != self._model:
                raise RolloutError(
                    f"model {entry.model!r} is registered but not "
                    f"loaded; this process serves {self._model!r}"
                )
            if version is not None:
                if entry.version == self._version:
                    return Route(entry.model, entry.version, pinned=True)
                if entry.version == self._canary_version:
                    return Route(
                        entry.model, entry.version, canary=True, pinned=True
                    )
                raise RolloutError(
                    f"version {entry.version!r} of {entry.model!r} is "
                    "registered but not serving; swap to it or start a "
                    "canary first"
                )
            if (
                self._canary_version is not None
                and self._canary_pct > 0.0
                and payload is not None
                and canary_assignment(payload, self._canary_pct, self.seed)
            ):
                return Route(
                    entry.model, self._canary_version, canary=True
                )
            return Route(entry.model, self._version)

    def observe(self, route: Route, ok: bool, latency_s: float) -> None:
        """One request's outcome on its route: lands the per-route
        metric families, feeds the canary breaker, and fires
        auto-rollback the moment the breaker opens."""
        if self.metrics is not None:
            self.metrics.record_model_request(
                route.model, route.version, latency_s
            )
        if not route.canary:
            return
        with self._lock:
            breaker = (
                self._breaker
                if route.version == self._canary_version
                else None
            )
        if breaker is None:
            return
        if ok:
            breaker.record_success()
        else:
            breaker.record_failure()
            if breaker.state == CIRCUIT_OPEN:
                try:
                    self.rollback(reason="canary_error_budget")
                except RolloutError:
                    pass  # a racing observer already rolled back

    # -- transitions ----------------------------------------------------------

    def swap(self, version: str, model: str | None = None) -> dict:
        """Zero-downtime weight swap: load VERSION through the registry
        (digest-verified), republish the engine's primary weights in
        place, bump the response-cache generation, move the durable
        default alias, and promote/retire any same-version canary —
        under live traffic, zero dropped or torn requests, zero new
        traces."""
        with self._lock:
            active_model = self._model
        entry = self.registry.resolve(model or active_model, version)
        if entry.model != active_model:
            raise RolloutError(
                f"cannot swap to model {entry.model!r}; this process "
                f"serves {active_model!r}"
            )
        variables = self.registry.load(entry)
        digest = self.engine.publish_weights(variables, version=version)
        if self.cache is not None:
            self.cache.invalidate(digest)
        self.registry.set_default(entry.model, version)
        with self._lock:
            src = self._version
            self._version = version
            promoted = self._canary_version == version
            if promoted:
                self._canary_version = None
                self._canary_pct = 0.0
                self._breaker = None
        if promoted:
            # The pinned twins now duplicate the primary; retire them.
            self.engine.remove_version(version)
        if self.metrics is not None:
            self.metrics.ensure_model(entry.model, version)
        if self.sink:
            self.sink.emit(
                "model_swap", model=entry.model, src=src, dst=version,
                digest=digest, promoted=promoted,
            )
        return self.describe()

    def start_canary(
        self, version: str, pct: float, model: str | None = None
    ) -> dict:
        """Install VERSION as a canary serving ``pct``% of unpinned
        traffic.  With a ``divergence_budget`` configured, the
        parity-drift probe runs immediately after the install — a
        corrupt-but-loadable artifact rolls back before it has served a
        single split request."""
        pct = float(pct)
        if not 0.0 < pct <= 100.0:
            raise RolloutError(
                f"canary pct must be in (0, 100], got {pct}"
            )
        with self._lock:
            active_model = self._model
            active_version = self._version
            live_canary = self._canary_version
        if live_canary is not None and live_canary != version:
            raise RolloutError(
                f"canary {live_canary!r} is already live; "
                "promote or roll it back first"
            )
        entry = self.registry.resolve(model or active_model, version)
        if entry.model != active_model:
            raise RolloutError(
                f"cannot canary model {entry.model!r}; this process "
                f"serves {active_model!r}"
            )
        if entry.version == active_version:
            raise RolloutError(
                f"version {version!r} is already the primary"
            )
        fresh = version != live_canary
        if fresh:
            variables = self.registry.load(entry)
            self.engine.install_version(version, variables)
        with self._lock:
            self._canary_version = version
            self._canary_pct = pct
            if fresh:
                self._breaker = CircuitBreaker(
                    f"canary:{entry.model}@{version}",
                    failure_threshold=self.failure_threshold,
                    registry=(
                        self.metrics.registry
                        if self.metrics is not None
                        else None
                    ),
                    sink=self.sink,
                )
        if self.metrics is not None:
            self.metrics.ensure_model(entry.model, version)
        if self.sink:
            self.sink.emit(
                "canary_step", model=entry.model, version=version, pct=pct,
            )
        if fresh:
            self.check_divergence()
        return self.describe()

    def check_divergence(self) -> dict | None:
        """Parity-drift probe: primary f32 vs the canary's pinned f32
        on the fixed parity slice (zero new traces).  With a
        ``divergence_budget`` set, drift past it (or an argmax flip)
        auto-rolls back; without one the probe is informational.
        Returns the probe record, or None when no canary is live."""
        with self._lock:
            version = self._canary_version
        if version is None:
            return None
        probe = self.engine.version_divergence(version)
        drifted = self.divergence_budget is not None and (
            probe["max_abs_logit_diff"] > self.divergence_budget
            or not probe["argmax_identical"]
        )
        if self.sink:
            self.sink.emit(
                "canary_divergence", drifted=drifted,
                budget=self.divergence_budget, **probe,
            )
        if drifted:
            try:
                self.rollback(reason="parity_drift")
            except RolloutError:
                pass  # a racing observer already rolled back
        return dict(probe, drifted=drifted)

    def rollback(self, reason: str = "operator") -> dict:
        """Retire the live canary and return ALL traffic to the
        primary.  Unpinned requests re-route on the very next
        route() call; in-flight canary batches complete normally (the
        batcher holds its own variant reference)."""
        with self._lock:
            version = self._canary_version
            if version is None:
                raise RolloutError("no canary is live")
            model = self._model
            self._canary_version = None
            self._canary_pct = 0.0
            self._breaker = None
        self.engine.remove_version(version)
        if self.cache is not None:
            # Canary entries are keyed under the pinned variant key and
            # so can never serve primary traffic — the bump just sheds
            # them (and evidences the transition on cache_invalidate).
            self.cache.invalidate(self.engine.weights_digest)
        if self.sink:
            self.sink.emit(
                "rollback", model=model, version=version, reason=reason,
            )
        return self.describe()

    def set_canary_pct(self, pct: float) -> dict:
        """Ramp the live canary's traffic share (0 pauses the split
        without uninstalling the variants)."""
        pct = float(pct)
        if not 0.0 <= pct <= 100.0:
            raise RolloutError(
                f"canary pct must be in [0, 100], got {pct}"
            )
        with self._lock:
            if self._canary_version is None:
                raise RolloutError("no canary is live")
            self._canary_pct = pct
            model, version = self._model, self._canary_version
        if self.sink:
            self.sink.emit(
                "canary_step", model=model, version=version, pct=pct,
            )
        return self.describe()

    # -- status ---------------------------------------------------------------

    def describe(self) -> dict:
        """The admin/healthz rollout block."""
        with self._lock:
            return {
                "model": self._model,
                "version": self._version,
                "weights_digest": self.engine.weights_digest,
                "canary": (
                    {
                        "version": self._canary_version,
                        "pct": self._canary_pct,
                        "circuit": (
                            self._breaker.state if self._breaker else None
                        ),
                    }
                    if self._canary_version is not None
                    else None
                ),
            }
