"""Serving metrics: queue depth, occupancy, latency percentiles, waste.

The training side's observability contract (utils/logging.py) is
string-returning helpers with the caller deciding where they print; this
module follows it — :meth:`ServingMetrics.report_lines` renders, callers
print.  Counters are updated from the HTTP handler threads and the
batcher worker concurrently, so every mutation takes the one lock; reads
snapshot under the same lock and format outside it.

Latencies are kept in a bounded ring (newest ``reservoir`` observations)
— serving metrics must not grow without bound over a long-lived process,
and tail percentiles over the recent window are what an operator acts
on anyway.
"""

from __future__ import annotations

import threading
import time
from collections import deque


def percentile(sorted_values: list[float], q: float) -> float:
    """Nearest-rank percentile over an ascending-sorted list (no numpy
    interpolation surprises in operator-facing numbers)."""
    if not sorted_values:
        return 0.0
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"percentile out of range: {q}")
    rank = max(1, int(-(-q * len(sorted_values) // 100)))  # ceil, 1-based
    return sorted_values[min(rank, len(sorted_values)) - 1]


class ServingMetrics:
    """Counters + latency reservoir for one serving process."""

    def __init__(self, reservoir: int = 8192):
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._latencies: deque[float] = deque(maxlen=reservoir)
        self.admitted = 0
        self.completed = 0
        self.rejected = 0       # admission-queue backpressure (503)
        self.timed_out = 0      # deadline expired before dispatch (504)
        self.failed = 0         # engine/dispatch errors (500)
        self.batches = 0
        self.samples_real = 0   # real samples dispatched
        self.samples_padded = 0  # bucket slots dispatched (real + padding)

    # -- recording (any thread) ---------------------------------------------

    def record_admitted(self, n: int = 1) -> None:
        with self._lock:
            self.admitted += n

    def record_rejected(self, n: int = 1) -> None:
        with self._lock:
            self.rejected += n

    def record_timeout(self, n: int = 1) -> None:
        with self._lock:
            self.timed_out += n

    def record_failed(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n

    def record_batch(self, real: int, bucket: int) -> None:
        """One engine dispatch: ``real`` live samples padded to ``bucket``."""
        with self._lock:
            self.batches += 1
            self.samples_real += real
            self.samples_padded += bucket

    def record_completed(self, latency_s: float) -> None:
        """One request finished; ``latency_s`` spans submit -> result set."""
        with self._lock:
            self.completed += 1
            self._latencies.append(latency_s)

    # -- reading -------------------------------------------------------------

    def snapshot(
        self,
        queue_depth: int | None = None,
        compiles: int | None = None,
        buckets: tuple[int, ...] | None = None,
    ) -> dict:
        """One consistent dict of everything (the /metrics payload).

        ``queue_depth``/``compiles``/``buckets`` are owned by the batcher
        and engine; callers pass the current values so this module stays
        free of back-references.
        """
        with self._lock:
            lat = sorted(self._latencies)
            uptime = time.perf_counter() - self._t0
            occupancy = (
                100.0 * self.samples_real / self.samples_padded
                if self.samples_padded
                else 0.0
            )
            snap = {
                "uptime_s": uptime,
                "requests": {
                    "admitted": self.admitted,
                    "completed": self.completed,
                    "rejected": self.rejected,
                    "timed_out": self.timed_out,
                    "failed": self.failed,
                },
                "batches": self.batches,
                "samples": {
                    "real": self.samples_real,
                    "dispatched": self.samples_padded,
                },
                "batch_occupancy_pct": occupancy,
                "padding_waste_pct": 100.0 - occupancy if self.batches else 0.0,
                "throughput_rps": self.completed / uptime if uptime > 0 else 0.0,
                "samples_per_s": (
                    self.samples_real / uptime if uptime > 0 else 0.0
                ),
                "latency_ms": {
                    "count": len(lat),
                    "p50": 1e3 * percentile(lat, 50),
                    "p95": 1e3 * percentile(lat, 95),
                    "p99": 1e3 * percentile(lat, 99),
                    "mean": 1e3 * sum(lat) / len(lat) if lat else 0.0,
                    "max": 1e3 * lat[-1] if lat else 0.0,
                },
            }
        if queue_depth is not None:
            snap["queue_depth"] = queue_depth
        if compiles is not None:
            snap["compiles"] = compiles
        if buckets is not None:
            snap["buckets"] = list(buckets)
        return snap

    def report_lines(self, **snapshot_kwargs) -> str:
        """Human-readable multi-line summary (caller prints; see module
        docstring for the convention)."""
        s = self.snapshot(**snapshot_kwargs)
        r, lat = s["requests"], s["latency_ms"]
        lines = [
            "serving metrics "
            f"(uptime {s['uptime_s']:.1f}s, {s['throughput_rps']:.1f} req/s, "
            f"{s['samples_per_s']:.1f} samples/s):",
            f"  requests: {r['completed']} ok / {r['rejected']} rejected / "
            f"{r['timed_out']} timed out / {r['failed']} failed "
            f"(admitted {r['admitted']})",
            f"  batches: {s['batches']} dispatched, occupancy "
            f"{s['batch_occupancy_pct']:.1f}%, padding waste "
            f"{s['padding_waste_pct']:.1f}%",
            f"  latency: p50 {lat['p50']:.2f} ms, p95 {lat['p95']:.2f} ms, "
            f"p99 {lat['p99']:.2f} ms, max {lat['max']:.2f} ms "
            f"over {lat['count']} requests",
        ]
        if "queue_depth" in s:
            lines.append(f"  queue depth: {s['queue_depth']}")
        if "compiles" in s:
            lines.append(
                f"  compiles: {s['compiles']}"
                + (f" (buckets {s['buckets']})" if "buckets" in s else "")
            )
        return "\n".join(lines)
