"""Serving metrics: queue depth, occupancy, latency percentiles, waste.

Rebuilt (PR 3) on the shared telemetry registry (obs/registry.py): every
counter and the latency reservoir are named registry metrics, so the
same numbers back BOTH ``/metrics`` surfaces — the JSON snapshot below
and the Prometheus text exposition (``?format=prom``; obs/export.py) —
plus the ``jax_compiles_total`` counter the engine's RecompileSentinel
reports into the same registry.

The training side's observability contract (utils/logging.py) still
holds — :meth:`ServingMetrics.report_lines` renders, callers print.
Mutations arrive from the HTTP handler threads and the batcher worker
concurrently; the registry's one lock covers every metric, so reads are
a consistent cut.

Latencies keep the bounded-reservoir semantics (newest ``reservoir``
observations): serving metrics must not grow without bound over a
long-lived process, and tail percentiles over the recent window are
what an operator acts on anyway.  Percentiles are the repo-shared
linear interpolation — previously this module ceil'd a nearest rank
while StepStats rounded an index, two different "p95"s.
"""

from __future__ import annotations

import time

from ..analysis import lockwatch
from ..obs.registry import Registry
from ..obs.registry import percentile as percentile  # noqa: F401 - shared impl, re-exported

_OUTCOMES = ("admitted", "completed", "rejected", "timed_out", "failed")


class ServingMetrics:
    """Counters + latency reservoir for one serving process, all living
    in ``self.registry`` (shareable with the engine's sentinel)."""

    def __init__(self, reservoir: int = 8192, registry: Registry | None = None):
        self.registry = registry if registry is not None else Registry()
        # Under JAXLINT_LOCKWATCH=1 the traced-lock acquisition counters
        # (lock_acquisitions_total{site=}, lock_hold_seconds) land in the
        # same registry as the serving series; no-op otherwise.
        lockwatch.attach(self.registry)
        self._t0 = time.perf_counter()
        self._requests = {
            outcome: self.registry.counter(
                "serving_requests_total",
                help="requests by lifecycle outcome "
                "(admitted intake; completed/rejected/timed_out/failed exits)",
                outcome=outcome,
            )
            for outcome in _OUTCOMES
        }
        self._batches = self.registry.counter(
            "serving_batches_total", help="engine dispatches"
        )
        self._samples_real = self.registry.counter(
            "serving_samples_total",
            help="samples by kind (real = live rows, dispatched = bucket "
            "slots incl. padding)",
            kind="real",
        )
        self._samples_padded = self.registry.counter(
            "serving_samples_total",
            help="",
            kind="dispatched",
        )
        self._latency = self.registry.histogram(
            "serving_request_latency_seconds",
            help="request latency, submit -> result set (reservoir window)",
            reservoir=reservoir,
        )
        # Pipeline surface (ISSUE 4): per-dispatch fill/waste plus the
        # stall the dispatch thread pays waiting for an in-flight slot.
        self._fill = self.registry.histogram(
            "serving_batch_fill_ratio",
            help="live rows / dispatched rows per dispatch (1.0 = no "
            "padding).  The denominator is what the DEVICE computed: the "
            "pow2 bucket in padded mode, the rows-capacity in packed mode "
            "— a packed batch with a padded tail must NOT read as 100% "
            "fill (PR-19 accounting contract, pinned in tests)",
            reservoir=reservoir,
        )
        self._padding_rows = self.registry.histogram(
            "serving_padding_waste_rows",
            help="padding rows per dispatch (bucket slots or packed "
            "rows-capacity, minus live rows)",
            reservoir=reservoir,
        )
        self._stall = self.registry.histogram(
            "serving_pipeline_stall_seconds",
            help="dispatch-thread wait for a free in-flight window slot",
            reservoir=reservoir,
        )
        self._inflight = self.registry.gauge(
            "serving_inflight_batches",
            help="batches launched on the device, result not yet read back",
        )
        # Failure-aware retry tally (docs/ROBUSTNESS.md): handler-side
        # resubmissions of never-executed requests after a replica
        # flush/abort.  Deliberately NOT an outcome in the requests
        # family — a retried request still exits through exactly one of
        # completed/rejected/timed_out/failed.
        self._retries = self.registry.counter(
            "serving_request_retries_total",
            help="transparent handler resubmissions after a replica "
            "drain race or death (pool mode); the client saw no error",
        )
        # Per-dtype request surface (ISSUE 6): reduced-precision serving
        # variants get their own count + latency families so the
        # quantization win is visible per dtype on /metrics and in the
        # Prometheus exposition (docs/OBSERVABILITY.md).
        self._reservoir = reservoir
        self._dtype_count: dict[str, object] = {}
        self._dtype_latency: dict[str, object] = {}
        # Per-QoS-class surface (ISSUE 11, docs/SERVING.md tail
        # latency): request count + latency per scheduling class, the
        # load-shed tally, and the hedged-dispatch outcome tally.  The
        # batcher pre-registers its classes (ensure_qos) so the families
        # are scrapeable from the first exposition.
        self._qos_count: dict[str, object] = {}
        self._qos_latency: dict[str, object] = {}
        self._shed: dict[str, object] = {}
        self._hedges: dict[str, object] = {}
        # Host hot path (docs/SERVING.md wire protocol + response
        # cache): per-wire-format request counts, wire byte totals, and
        # the cache outcome tally.  Registered by ensure_wire (the
        # server, at construction) / ensure_cache (the ResponseCache,
        # only when --response-cache enables the tier) so short CI
        # smokes scrape fully-born families.
        self._wire_requests: dict[str, object] = {}
        self._wire_bytes: dict[str, object] = {}
        self._cache: dict[str, object] = {}
        # Registry/rollout surface (ISSUE 17, docs/SERVING.md model
        # registry): request count + latency per served (model, version)
        # so a canary's share and its latency are separable from the
        # primary's on the same exposition.  The rollout controller
        # pre-registers its routes (ensure_model) for the same
        # scrapeable-from-first-exposition contract as ensure_qos.
        self._model_count: dict[tuple[str, str], object] = {}
        self._model_latency: dict[tuple[str, str], object] = {}
        # Sharded-replica surface (ISSUE 20, docs/SERVING.md sharded
        # replicas): per-replica mesh width and per-expert routed-token
        # load for EP replicas.  Registered by record_shard_devices
        # (the pool, at construction) / ensure_expert_load (the EP
        # engine's first recorded dispatch, or the pool pre-registering
        # so CI greps a short smoke's dump).
        self._expert_load: dict[str, object] = {}

    # -- counter views (back-compat attribute surface) ------------------------

    @property
    def admitted(self) -> int:
        return self._requests["admitted"].value

    @property
    def completed(self) -> int:
        return self._requests["completed"].value

    @property
    def rejected(self) -> int:
        return self._requests["rejected"].value

    @property
    def timed_out(self) -> int:
        return self._requests["timed_out"].value

    @property
    def failed(self) -> int:
        return self._requests["failed"].value

    @property
    def retried(self) -> int:
        return self._retries.value

    @property
    def batches(self) -> int:
        return self._batches.value

    @property
    def samples_real(self) -> int:
        return self._samples_real.value

    @property
    def samples_padded(self) -> int:
        return self._samples_padded.value

    # -- recording (any thread) ---------------------------------------------

    def record_admitted(self, n: int = 1) -> None:
        self._requests["admitted"].inc(n)

    def record_rejected(self, n: int = 1) -> None:
        self._requests["rejected"].inc(n)

    def record_timeout(self, n: int = 1) -> None:
        self._requests["timed_out"].inc(n)

    def record_failed(self, n: int = 1) -> None:
        self._requests["failed"].inc(n)

    def record_retry(self, n: int = 1) -> None:
        self._retries.inc(n)

    def record_batch(self, real: int, bucket: int) -> None:
        """One engine dispatch: ``real`` live samples padded to ``bucket``.

        ``real`` is LIVE rows — client rows, never staging copies —
        and ``bucket`` is the rows the device computed (the pow2 rung,
        or the packed rows-capacity).  The engine passes exactly these
        (engine.launch), so fill/waste stay honest in both modes: a
        packed buffer whose tail is padding reports its true fill, not
        100% (the formula a buffer-length caller would corrupt)."""
        self._batches.inc()
        self._samples_real.inc(real)
        self._samples_padded.inc(bucket)
        self._fill.observe(real / bucket if bucket else 0.0)
        self._padding_rows.observe(bucket - real)

    def record_stall(self, stall_s: float) -> None:
        """Dispatch thread blocked ``stall_s`` on a full in-flight window."""
        self._stall.observe(stall_s)

    def set_inflight(self, depth: int, replica: str | None = None) -> None:
        """Current launched-not-yet-completed batch count (gauge).

        With ``replica`` (pool mode, serving/router.py) the count lands
        on the labeled ``serving_replica_inflight{replica=}`` family
        INSTEAD of the plain gauge — N batchers sharing one metrics
        object would otherwise race each other's unlabeled writes into
        a meaningless last-writer value.  The labeled family (or a sum
        over it) is therefore the pool's Prometheus surface; the
        unlabeled gauge stays 0 there, and the router-computed
        aggregate appears only in the JSON snapshot's
        ``pipeline.inflight`` field."""
        if replica is None:
            self._inflight.set(depth)
            return
        self.registry.gauge(
            "serving_replica_inflight",
            help="per-replica batches launched on the device, result not "
            "yet read back (pool mode)",
            replica=replica,
        ).set(depth)

    def ensure_qos(self, qos: str) -> None:
        """Pre-register one QoS class's count/latency/shed families so
        they render on the exposition before the first observation (CI
        greps a short smoke's dump; lazily-born families are flaky)."""
        if qos in self._qos_count:
            return
        with self.registry.locked():
            self._qos_count[qos] = self.registry.counter(
                "serving_qos_requests_total",
                help="completed requests per QoS class",
                qos=qos,
            )
            self._qos_latency[qos] = self.registry.histogram(
                "serving_qos_latency_seconds",
                help="request latency per QoS class (reservoir window)",
                reservoir=self._reservoir,
                qos=qos,
            )
            self._shed[qos] = self.registry.counter(
                "serving_shed_total",
                help="requests load-shed from the admission queue per "
                "QoS class (lowest class first under pressure)",
                qos=qos,
            )

    def ensure_fleet(self) -> None:
        """Pre-register the fleet-tier families (serving/fleet.py) so a
        short smoke's exposition carries them before the first scale
        event or restart — same scrapeable-from-first-exposition
        rationale as :meth:`ensure_qos`.  The per-backend restart
        counters register as each backend joins (Fleet._register);
        here live the backend-agnostic families."""
        for direction in ("up", "down"):
            self.registry.counter(
                "fleet_scale_events_total",
                help="autoscaler actions by direction",
                direction=direction,
            )

    def ensure_hedges(self) -> None:
        """Pre-register the hedge outcome family (the router's hedger
        calls this once when hedging is enabled) — same scrapeable-from-
        first-exposition rationale as :meth:`ensure_qos`."""
        for outcome in ("won", "lost", "cancelled"):
            self._hedges[outcome] = self.registry.counter(
                "serving_hedges_total",
                help="hedged dispatches by outcome: won = the hedge's "
                "completion was the client-visible one, lost = the "
                "primary answered first, cancelled = a due hedge was "
                "abandoned before or without a decisive dispatch",
                outcome=outcome,
            )

    def ensure_wire(self) -> None:
        """Pre-register the wire-protocol families (docs/SERVING.md
        binary wire path) — both formats and both byte directions exist
        from the first exposition, same rationale as
        :meth:`ensure_qos`."""
        if self._wire_requests:
            return
        with self.registry.locked():
            for fmt in ("json", "binary"):
                self._wire_requests[fmt] = self.registry.counter(
                    "serving_wire_requests_total",
                    help="/predict requests by wire format (json = the "
                    "default text protocol, binary = "
                    "application/x-mnist-f32)",
                    format=fmt,
                )
            for direction in ("in", "out"):
                self._wire_bytes[direction] = self.registry.counter(
                    "serving_wire_bytes_total",
                    help="/predict payload bytes by direction (request "
                    "bodies in, response bodies out)",
                    direction=direction,
                )

    def ensure_cache(self) -> None:
        """Pre-register the response-cache outcome family
        (serving/cache.py; only called when --response-cache enables
        the tier, so cache-off expositions are unchanged)."""
        if self._cache:
            return
        with self.registry.locked():
            for outcome in ("hit", "miss", "coalesced"):
                self._cache[outcome] = self.registry.counter(
                    "serving_cache_total",
                    help="response-cache lookups by outcome (hit = "
                    "served from cache, miss = claimed the dispatch, "
                    "coalesced = joined an identical in-flight request)",
                    outcome=outcome,
                )

    def ensure_model(self, model: str, version: str) -> None:
        """Pre-register one (model, version) route's count/latency
        families (the rollout controller calls this when a route becomes
        servable: engine load, swap target, canary start) — same
        scrapeable-from-first-exposition rationale as
        :meth:`ensure_qos`: CI greps ``serving_model_requests_total``
        out of a short smoke's dump before traffic may have split."""
        key = (model, version)
        if key in self._model_count:
            return
        # Both families land under the registry lock — a scrape racing
        # the first registration must never see the counter without its
        # latency twin (same invariant as record_completed's dtypes).
        with self.registry.locked():
            self._model_count[key] = self.registry.counter(
                "serving_model_requests_total",
                help="completed requests per served (model, version) "
                "registry route",
                model=model,
                version=version,
            )
            self._model_latency[key] = self.registry.histogram(
                "serving_model_latency_seconds",
                help="request latency per served (model, version) "
                "registry route (reservoir window)",
                reservoir=self._reservoir,
                model=model,
                version=version,
            )

    def record_shard_devices(self, replica: str, devices: int) -> None:
        """Devices in REPLICA's mesh (1 = plain DP, k = a TP/EP/PP
        replica spanning k devices) — the pool sets these once at
        construction, so the topology is scrapeable from the first
        exposition."""
        self.registry.gauge(
            "serving_shard_devices",
            help="devices in each replica's mesh (1 = plain DP, k = a "
            "sharded TP/EP/PP replica spanning k devices)",
            replica=replica,
        ).set(devices)

    def ensure_expert_load(self, num_experts: int) -> None:
        """Pre-register the per-expert load gauges so an EP pool's
        exposition carries the family before the first recorded
        dispatch — same scrapeable-from-first-exposition rationale as
        :meth:`ensure_qos`."""
        if len(self._expert_load) >= num_experts:
            return
        with self.registry.locked():
            for e in range(num_experts):
                key = str(e)
                if key not in self._expert_load:
                    self._expert_load[key] = self.registry.gauge(
                        "serving_expert_load",
                        help="tokens routed to (and kept by) each expert "
                        "in the most recent EP dispatch; max/mean across "
                        "experts is the imbalance factor",
                        expert=key,
                    )

    def record_expert_load(self, loads) -> None:
        """Per-expert kept-token counts from one EP dispatch (the
        engine's one-batch-lagged readback)."""
        loads = [float(v) for v in loads]
        self.ensure_expert_load(len(loads))
        for e, val in enumerate(loads):
            self._expert_load[str(e)].set(val)

    def expert_load_snapshot(self) -> dict[str, float]:
        """Current per-expert load gauge values ({} when the pool has no
        EP replica) — the pool's shutdown telemetry reads this so the
        imbalance factor lands in the JSONL stream, not only in a
        Prometheus scrape."""
        return {k: g.value for k, g in sorted(self._expert_load.items())}

    def record_model_request(
        self, model: str, version: str, latency_s: float
    ) -> None:
        """One request served by registry route (model, version)."""
        key = (model, version)
        if key not in self._model_count:
            self.ensure_model(model, version)
        self._model_count[key].inc()
        self._model_latency[key].observe(latency_s)

    def record_wire(self, fmt: str, bytes_in: int = 0, bytes_out: int = 0) -> None:
        """One /predict exchange on wire format ``fmt`` moving
        ``bytes_in``/``bytes_out`` payload bytes."""
        self.ensure_wire()
        self._wire_requests[fmt].inc()
        if bytes_in:
            self._wire_bytes["in"].inc(bytes_in)
        if bytes_out:
            self._wire_bytes["out"].inc(bytes_out)

    def record_cache(self, outcome: str) -> None:
        self.ensure_cache()
        self._cache[outcome].inc()

    def record_shed(self, qos: str) -> None:
        """One request evicted from the admission queue to admit a
        higher class under pressure (serving/qos.py)."""
        self.ensure_qos(qos)
        self._shed[qos].inc()

    def record_hedge(self, outcome: str) -> None:
        if outcome not in self._hedges:
            self.ensure_hedges()  # registers the full outcome set once
        self._hedges[outcome].inc()

    def qos_p99_s(self, qos: str, min_samples: int = 20) -> float | None:
        """Online per-class p99 (seconds) from the latency reservoir —
        the hedger's delay digest.  None until ``min_samples``
        observations exist: hedging on a cold estimate would fire on
        noise."""
        hist = self._qos_latency.get(qos)
        if hist is None:
            return None
        window = hist.values()
        if len(window) < min_samples:
            return None
        return percentile(sorted(window), 99)

    def record_completed(
        self,
        latency_s: float,
        dtype: str | None = None,
        qos: str | None = None,
    ) -> None:
        """One request finished; ``latency_s`` spans submit -> result set.
        ``dtype`` additionally lands the request on the per-variant
        count/latency families, ``qos`` on the per-class ones."""
        self._requests["completed"].inc()
        self._latency.observe(latency_s)
        if qos is not None:
            self.ensure_qos(qos)
            self._qos_count[qos].inc()
            self._qos_latency[qos].observe(latency_s)
        if dtype is None:
            return
        counter = self._dtype_count.get(dtype)
        if counter is None:
            # Both dict entries land under the registry lock (reentrant):
            # snapshot() iterates these dicts while holding it, and a
            # scrape racing the first completion of a dtype must never
            # see the counter without its latency twin.
            with self.registry.locked():
                counter = self._dtype_count[dtype] = self.registry.counter(
                    "serving_dtype_requests_total",
                    help="completed requests per serving dtype variant",
                    dtype=dtype,
                )
                self._dtype_latency[dtype] = self.registry.histogram(
                    "serving_dtype_latency_seconds",
                    help="request latency per serving dtype variant "
                    "(reservoir window)",
                    reservoir=self._reservoir,
                    dtype=dtype,
                )
        counter.inc()
        self._dtype_latency[dtype].observe(latency_s)

    # -- reading -------------------------------------------------------------

    def snapshot(
        self,
        queue_depth: int | None = None,
        compiles: int | None = None,
        buckets: tuple[int, ...] | None = None,
        inflight: int | None = None,
        max_inflight: int | None = None,
        linger_ms: float | None = None,
        replicas: dict | None = None,
    ) -> dict:
        """One consistent dict of everything (the /metrics JSON payload).

        ``queue_depth``/``compiles``/``buckets`` are owned by the batcher
        and engine; callers pass the current values so this module stays
        free of back-references.  Passed values are also mirrored into
        registry gauges, so the Prometheus exposition carries them too.

        All reads happen under the registry-wide lock (reentrant), so the
        snapshot is a consistent cut — a record_batch landing mid-read
        cannot skew occupancy by tearing real vs dispatched.
        """
        with self.registry.locked():
            lat = sorted(self._latency.values())
            by_dtype = {
                name: (
                    self._dtype_count[name].value,
                    sorted(self._dtype_latency[name].values()),
                )
                for name in self._dtype_count
            }
            by_qos = {
                name: (
                    self._qos_count[name].value,
                    sorted(self._qos_latency[name].values()),
                    self._shed[name].value,
                )
                for name in self._qos_count
            }
            hedges = {
                outcome: counter.value
                for outcome, counter in self._hedges.items()
            }
            cache = {
                outcome: counter.value
                for outcome, counter in self._cache.items()
            }
            wire = {
                fmt: counter.value
                for fmt, counter in self._wire_requests.items()
            }
            wire_bytes = {
                direction: counter.value
                for direction, counter in self._wire_bytes.items()
            }
            fills = self._fill.values()
            stalls = sorted(self._stall.values())
            stall_count, stall_sum = self._stall.count, self._stall.sum
            completed = self.completed
            samples_real = self.samples_real
            samples_padded = self.samples_padded
            batches = self.batches
            requests = {
                "admitted": self.admitted,
                "completed": completed,
                "rejected": self.rejected,
                "timed_out": self.timed_out,
                "failed": self.failed,
            }
            retried = self.retried
        uptime = time.perf_counter() - self._t0
        occupancy = (
            100.0 * samples_real / samples_padded if samples_padded else 0.0
        )
        throughput = completed / uptime if uptime > 0 else 0.0
        snap = {
            "uptime_s": uptime,
            "requests": requests,
            # Top-level, not inside "requests": a retry is not a request
            # outcome (the retried request still exits through one).
            "retries": retried,
            "batches": batches,
            "samples": {
                "real": samples_real,
                "dispatched": samples_padded,
            },
            "batch_occupancy_pct": occupancy,
            "padding_waste_pct": 100.0 - occupancy if batches else 0.0,
            "throughput_rps": throughput,
            "samples_per_s": samples_real / uptime if uptime > 0 else 0.0,
            "latency_ms": {
                "count": len(lat),
                "p50": 1e3 * percentile(lat, 50),
                "p95": 1e3 * percentile(lat, 95),
                "p99": 1e3 * percentile(lat, 99),
                "mean": 1e3 * sum(lat) / len(lat) if lat else 0.0,
                "max": 1e3 * lat[-1] if lat else 0.0,
            },
            "pipeline": {
                "fill_ratio_mean": sum(fills) / len(fills) if fills else 0.0,
                "stalls": stall_count,
                "stall_s_total": stall_sum,
                "stall_ms_p95": 1e3 * percentile(stalls, 95),
            },
        }
        if by_dtype:
            snap["dtypes"] = {
                name: {
                    "requests": count,
                    "p50_ms": 1e3 * percentile(window, 50),
                    "p95_ms": 1e3 * percentile(window, 95),
                    "p99_ms": 1e3 * percentile(window, 99),
                }
                for name, (count, window) in sorted(by_dtype.items())
            }
        if by_qos:
            # The tail-latency surface (docs/SERVING.md): per-class
            # percentiles + shed counts, and hedge outcomes when the
            # router's hedger is on.  Classes appear as soon as a
            # batcher registers them (ensure_qos), count 0 until served.
            snap["qos"] = {
                name: {
                    "requests": count,
                    "shed": shed,
                    "p50_ms": 1e3 * percentile(window, 50),
                    "p95_ms": 1e3 * percentile(window, 95),
                    "p99_ms": 1e3 * percentile(window, 99),
                }
                for name, (count, window, shed) in sorted(by_qos.items())
            }
        if hedges:
            snap["hedges"] = dict(sorted(hedges.items()))
        if cache:
            # Present only when the response-cache tier is enabled
            # (--response-cache; serving/cache.py registers the family),
            # so cache-off snapshots stay byte-identical.
            lookups = sum(cache.values())
            snap["cache"] = {
                **dict(sorted(cache.items())),
                "hit_rate": cache.get("hit", 0) / lookups if lookups else 0.0,
            }
        if wire.get("binary"):
            # The wire block appears once a BINARY request has been
            # seen: JSON-only traffic keeps the pre-wire snapshot (and
            # the shutdown report) byte-identical, while the Prometheus
            # exposition carries both formats from the first scrape.
            snap["wire"] = {
                "requests": dict(sorted(wire.items())),
                "bytes": dict(sorted(wire_bytes.items())),
            }
        gauges = [
            ("serving_uptime_seconds", "process uptime", uptime),
            ("serving_batch_occupancy_pct", "real samples / dispatched slots",
             occupancy),
            ("serving_throughput_rps", "completed requests per second",
             throughput),
        ]
        if queue_depth is not None:
            snap["queue_depth"] = queue_depth
            gauges.append(
                ("serving_queue_depth", "admission queue depth", queue_depth)
            )
        if inflight is not None:
            # JSON field only — the gauge itself is maintained by the
            # batcher under its in-flight lock; setting it here from this
            # unlocked read could overwrite a newer value with a stale one.
            snap["pipeline"]["inflight"] = inflight
        if max_inflight is not None:
            snap["pipeline"]["max_inflight"] = max_inflight
        if linger_ms is not None:
            snap["pipeline"]["linger_ms"] = linger_ms
        if replicas is not None:
            # Pool mode (serving/router.py): per-replica live state, as
            # provided by the router's replica_stats() — queue depth,
            # in-flight, EWMA latency, drain state per replica.
            snap["replicas"] = replicas
        if compiles is not None:
            snap["compiles"] = compiles
        if buckets is not None:
            snap["buckets"] = list(buckets)
        for name, help_text, value in gauges:
            self.registry.gauge(name, help=help_text).set(value)
        return snap

    def report_lines(self, **snapshot_kwargs) -> str:
        """Human-readable multi-line summary (caller prints; see module
        docstring for the convention)."""
        s = self.snapshot(**snapshot_kwargs)
        r, lat = s["requests"], s["latency_ms"]
        lines = [
            "serving metrics "
            f"(uptime {s['uptime_s']:.1f}s, {s['throughput_rps']:.1f} req/s, "
            f"{s['samples_per_s']:.1f} samples/s):",
            f"  requests: {r['completed']} ok / {r['rejected']} rejected / "
            f"{r['timed_out']} timed out / {r['failed']} failed "
            f"(admitted {r['admitted']})",
            f"  batches: {s['batches']} dispatched, occupancy "
            f"{s['batch_occupancy_pct']:.1f}%, padding waste "
            f"{s['padding_waste_pct']:.1f}%",
            f"  latency: p50 {lat['p50']:.2f} ms, p95 {lat['p95']:.2f} ms, "
            f"p99 {lat['p99']:.2f} ms, max {lat['max']:.2f} ms "
            f"over {lat['count']} requests",
        ]
        if "queue_depth" in s:
            lines.append(f"  queue depth: {s['queue_depth']}")
        pipe = s["pipeline"]
        if pipe["stalls"] or "inflight" in pipe:
            lines.append(
                "  pipeline: "
                + (f"in-flight {pipe['inflight']}"
                   + (f"/{pipe['max_inflight']}" if "max_inflight" in pipe
                      else "")
                   + ", " if "inflight" in pipe else "")
                + (f"linger {pipe['linger_ms']:.2f} ms, "
                   if "linger_ms" in pipe else "")
                + f"mean fill {100.0 * pipe['fill_ratio_mean']:.1f}%, "
                f"{pipe['stalls']} stalls "
                f"({pipe['stall_s_total']:.3f} s total, "
                f"p95 {pipe['stall_ms_p95']:.2f} ms)"
            )
        for name, q in s.get("qos", {}).items():
            lines.append(
                f"  qos [{name}]: {q['requests']} ok, {q['shed']} shed, "
                f"p50 {q['p50_ms']:.2f} ms / p95 {q['p95_ms']:.2f} ms / "
                f"p99 {q['p99_ms']:.2f} ms"
            )
        if s.get("hedges"):
            h = s["hedges"]
            placed = h.get("won", 0) + h.get("lost", 0)
            lines.append(
                f"  hedges: {h.get('won', 0)} won / {h.get('lost', 0)} lost "
                f"/ {h.get('cancelled', 0)} cancelled"
                + (f" (win rate {h.get('won', 0) / placed:.1%})"
                   if placed else "")
            )
        if "cache" in s:
            c = s["cache"]
            lines.append(
                f"  cache: {c.get('hit', 0)} hit / {c.get('miss', 0)} miss "
                f"/ {c.get('coalesced', 0)} coalesced "
                f"(hit rate {c['hit_rate']:.1%})"
            )
        if "wire" in s:
            w = s["wire"]
            lines.append(
                f"  wire: {w['requests'].get('binary', 0)} binary / "
                f"{w['requests'].get('json', 0)} json requests, "
                f"{w['bytes'].get('in', 0)} B in / "
                f"{w['bytes'].get('out', 0)} B out"
            )
        if "compiles" in s:
            lines.append(
                f"  compiles: {s['compiles']}"
                + (f" (buckets {s['buckets']})" if "buckets" in s else "")
            )
        return "\n".join(lines)
