"""Content-addressed response cache with single-flight dedup.

Inference here is deterministic: the same (model weights, served dtype,
input rows) always produces the same logits — the property every parity
gate and byte-identity test in this repo already leans on.  So repeated
identical work is pure host+device waste, and it is common waste: retry
storms, hedged clients, dashboards re-probing a canary row, zipf-shaped
request popularity.  This module deletes it at two points
(docs/SERVING.md):

- the serving admission point (serving/server.py): keyed on
  ``(model digest, dtype, payload hash)`` where the payload hash covers
  the MODEL-READY float32 rows — so a JSON request and a binary-wire
  request carrying the same pixels hit the same entry;
- the fleet front (serving/fleet.py): keyed on the raw proxied body
  (content-type ++ bytes), so a hit answers without touching a backend.

**Single-flight**: a miss CLAIMS the key; concurrent identical requests
JOIN the claimant's in-flight computation instead of dispatching their
own copy — one device dispatch, N waiters.  The claimant completes or
fails the flight; a failure wakes every joiner with the same error
(each maps it to its own client outcome — exactly one outcome per
waiter, the PR-8 first-wins discipline one level up) and the entry is
DROPPED, never cached: a killed dispatch must not become a stale fill
that later requests read as truth.  Joiners additionally wait only
their OWN deadline budget; a slow flight 504s the joiner without
disturbing the claimant.

**Invalidation**: the key embeds a ``model_digest`` (the engine's
weights digest, serving/engine.py) plus a local generation counter
bumped by :meth:`invalidate` — any engine/weights swap makes every old
key unreachable, and the LRU bound retires the dead entries.  The
whole tier is OFF by default (``--response-cache N`` enables it with an
N-entry bound); with it off, not a single code path changes.

Values are opaque to this module (the server caches logits arrays, the
front caches ``(status, content_type, body)`` tuples), so one
implementation serves both tiers.  stdlib-only; no jax, no numpy.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

from ..analysis.lockwatch import make_lock

# claim() outcomes (also the serving_cache_total{outcome=} label values;
# docs/OBSERVABILITY.md).
HIT = "hit"
MISS = "miss"
COALESCED = "coalesced"
CACHE_OUTCOMES = (HIT, MISS, COALESCED)


class FlightTimeout(TimeoutError):
    """A joiner's own deadline expired before the claimed flight
    resolved — the joiner's 504, not a verdict on the flight."""


class Flight:
    """One in-flight computation a claimant owns and joiners await."""

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: BaseException | None = None

    def _resolve(self, value, error) -> None:
        # First writer wins; the cache's claim/complete discipline means
        # there is only ever one writer, but a double-complete from a
        # buggy caller must not clobber what joiners already read.
        if self._event.is_set():
            return
        self._value = value
        self._error = error
        self._event.set()

    def result(self, timeout_s: float | None = None):
        """Block until the claimant resolves the flight; re-raises the
        claimant's error verbatim so the joiner's status mapping treats
        it exactly like its own failure (one outcome per waiter)."""
        if not self._event.wait(timeout_s):
            raise FlightTimeout(
                "deadline expired waiting on a coalesced in-flight request"
            )
        if self._error is not None:
            raise self._error
        return self._value


def payload_digest(*parts) -> str:
    """Stable content address for request payload bytes (blake2b-128:
    fast, stdlib, and 128 bits is far past birthday range for any
    realistic cache population).  ``parts`` are any buffer-protocol
    objects (bytes, a contiguous array's memoryview) — hashed in place,
    never copied."""
    h = hashlib.blake2b(digest_size=16)
    for part in parts:
        h.update(part)
    return h.hexdigest()


class ResponseCache:
    """Bounded-LRU deterministic-response cache with single-flight.

    ``capacity`` bounds COMPLETED entries (an in-flight claim is not
    evictable — joiners hold it; the handler-thread bound already caps
    how many can exist).  ``metrics`` (ServingMetrics) receives the
    ``serving_cache_total{outcome=}`` counts; ``sink`` gets a
    ``cache_hit`` event per served-from-cache response.  ``scope``
    labels events ("server" admission tier vs "front" fleet tier).
    """

    def __init__(
        self,
        capacity: int,
        model_digest: str = "",
        metrics=None,
        sink=None,
        scope: str = "server",
    ):
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.model_digest = model_digest
        self.metrics = metrics
        self.sink = sink
        self.scope = scope
        self._generation = 0
        self._lock = make_lock("cache.response")
        self._done: OrderedDict[tuple, object] = OrderedDict()
        self._pending: dict[tuple, Flight] = {}
        if metrics is not None:
            # Scrapeable-from-first-exposition (the CI grep contract):
            # all three outcome series exist before the first request.
            metrics.ensure_cache()

    # -- keys ------------------------------------------------------------------

    def key(self, *payload_parts, dtype: str = "f32") -> tuple:
        """The content address: (generation, model digest, dtype,
        payload hash).  Generation + digest make every entry from a
        previous engine/weights unreachable after a swap.  Multiple
        buffer-protocol ``payload_parts`` hash in sequence without
        being concatenated — no payload-sized copy at either tier."""
        digest = payload_digest(*payload_parts)
        # Generation and model digest mutate together under the lock in
        # invalidate(); reading them lock-free could mint a chimera key
        # (old generation, new digest) mid-swap that wrongly misses —
        # or, worse, collides with — a post-swap fill.
        with self._lock:
            return (self._generation, self.model_digest, dtype, digest)

    # -- the single-flight protocol -------------------------------------------

    def claim(self, key: tuple):
        """Look up ``key``; returns one of

        - ``(HIT, value)`` — a completed entry (LRU-refreshed);
        - ``(COALESCED, flight)`` — another request holds the claim;
          call ``flight.result(my_remaining_budget)``;
        - ``(MISS, flight)`` — the caller now OWNS the flight and must
          call :meth:`complete` or :meth:`fail` on every exit path (a
          leaked claim would coalesce future identical requests onto a
          flight that never resolves).
        """
        with self._lock:
            if key in self._done:
                self._done.move_to_end(key)
                value = self._done[key]
                outcome = HIT
            elif key in self._pending:
                value = self._pending[key]
                outcome = COALESCED
            else:
                value = self._pending[key] = Flight()
                outcome = MISS
        if self.metrics is not None:
            self.metrics.record_cache(outcome)
        if outcome == HIT and self.sink:
            self.sink.emit("cache_hit", scope=self.scope)
        return outcome, value

    def complete(self, key: tuple, flight: Flight, value, store: bool = True) -> None:
        """Resolve a claimed flight with ``value`` and wake every
        joiner; ``store=False`` delivers without filling (the front
        caches only 200s — a 503 is an outcome for current waiters, not
        a fact about the payload)."""
        with self._lock:
            if self._pending.get(key) is flight:
                del self._pending[key]
            if store and key[0] == self._generation:
                # A fill racing invalidate() must lose: its value was
                # computed against the pre-swap model.
                self._done[key] = value
                while len(self._done) > self.capacity:
                    self._done.popitem(last=False)
        flight._resolve(value, None)

    def fail(self, key: tuple, flight: Flight, error: BaseException) -> None:
        """Resolve a claimed flight with ``error``: every joiner raises
        it as its own, and NOTHING is cached — the
        never-a-stale-fill rule."""
        with self._lock:
            if self._pending.get(key) is flight:
                del self._pending[key]
        flight._resolve(None, error)

    # -- lifecycle -------------------------------------------------------------

    def invalidate(self, model_digest: str | None = None) -> None:
        """Engine/weights swap: drop every completed entry and bump the
        generation so in-flight fills from the old world cannot land.
        ``model_digest`` updates the key component when the new weights'
        digest is known (a swap to identical weights still invalidates —
        correctness over hit rate)."""
        with self._lock:
            self._generation += 1
            generation = self._generation
            if model_digest is not None:
                self.model_digest = model_digest
            self._done.clear()
        if self.sink:
            self.sink.emit(
                "cache_invalidate", scope=self.scope,
                generation=generation,
            )

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "entries": len(self._done),
                "pending": len(self._pending),
                "generation": self._generation,
            }
