"""Multi-process serving fleet: front router, control plane, autoscaler.

``EnginePool`` tops out at one process's devices, and the scale-out
sweep showed the single process going host-bound (~60 rps flat on the CI
box): the next order of magnitude comes from MORE PROCESSES.  This
module composes the primitives the repo already has into a fleet tier:

- **Backend** — one serving process (EnginePool + supervisor + QoS
  batcher, the whole PR-4..11 stack) listening on its own port, reached
  over a keep-alive HTTP connection pool with per-attempt timeouts.
- **FleetRouter** — the PR-7 placement policies (roundrobin /
  least-loaded / cost) lifted from in-process replicas to network
  backends, fed from each backend's polled ``/metrics`` snapshot (queue
  depth, in-flight) plus a front-measured latency EWMA, with per-backend
  circuit breakers (serving/circuit.py) and at most ONE attempt per
  backend on the remaining deadline — exactly one client-visible
  outcome per request, however many backends were tried (the PR-8
  contract, one level up).
- **FleetSupervisor** — the GangSupervisor state machine applied to
  backends: liveness (process poll), ``/readyz`` probes, and heartbeat
  files (liveness.py) detect a dead or wedged backend; it is
  grace-killed and REPLACED under a seeded-backoff restart budget, and
  the replacement warm-starts in seconds off the shared AOT cache
  (pure deserialize, zero new traces — the PR-5/7 contract).
- **FleetAutoscaler** — adds a backend when the smoothed load signal
  breaches the high-water mark for a sustained window, and drains the
  newest backend (drain → settle → kill, nothing lost) at the low-water
  mark, with hysteresis (separate watermarks + cooldown) and min/max
  bounds.

Telemetry: ``fleet_backends{state=}``, ``fleet_route_decisions_total
{backend=}``, ``fleet_backend_restarts_total{backend=}``,
``fleet_scale_events_total{direction=}`` plus ``fleet_route`` /
``backend_death`` / ``backend_replace`` / ``backend_eject`` /
``backend_drain`` / ``fleet_scale`` JSONL events
(docs/OBSERVABILITY.md); ``tools/perf_report.py --telemetry`` renders
the "fleet" section from them.

stdlib + the obs registry only, no jax import in this module: the
front tier supervises the processes that own the devices, so nothing
here may depend on the thing being supervised — the same rationale as
liveness.py.  :class:`FakeBackendServer` is the
structural test/bench harness: a real-HTTP fake backend with serial
capacity, so the 4-backends-beat-1 scaling pin and the kill→replace
drill run at interactive speed (tests/test_fleet.py,
``tools/serve_loadgen.py --fleet-sweep ... --fleet-fake``).
"""

from __future__ import annotations

import http.client
import json
import os
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..analysis.lockwatch import make_lock
from ..obs.export import render_prometheus
from ..liveness import (
    BackoffLadder,
    Heartbeat,
    grace_stop,
    heartbeat_age_s,
    heartbeat_path,
)
from .cache import COALESCED, HIT, FlightTimeout, ResponseCache
from .circuit import (
    CIRCUIT_CLOSED,
    CIRCUIT_HALF_OPEN,
    CIRCUIT_OPEN,
    CircuitBreaker,
)
from .metrics import ServingMetrics
from .wire import WIRE_REQUEST_TYPE

FLEET_POLICIES = ("roundrobin", "least-loaded", "cost")

# Backend lifecycle states (the fleet_backends{state=} gauge keys).
STARTING = "starting"      # spawned, waiting for /readyz
ACTIVE = "active"          # routable
DRAINING = "draining"      # scale-down in progress: no new placements
REPLACING = "replacing"    # dead/hung; killed, awaiting backoff respawn
EJECTED = "ejected"        # restart budget spent; permanently out
RETIRED = "retired"        # drained down cleanly (scale-down complete)
BACKEND_STATES = (STARTING, ACTIVE, DRAINING, REPLACING, EJECTED, RETIRED)

# Env contract between the fleet launcher and its backend processes:
# the serving CLI beats this file from the batcher dispatch loop, so a
# backend that still answers poll() but stopped dispatching is
# detectable by mtime age (liveness.py).
ENV_FLEET_HEARTBEAT_FILE = "SERVE_HEARTBEAT_FILE"

# Front-measured latency EWMA smoothing (serving/router.py's constant).
EWMA_ALPHA = 0.2

_JSON_TYPE = "application/json"


class Backend:
    """One network backend: a name, its URL, an optional owned process,
    and a keep-alive HTTP connection pool with per-attempt timeouts.

    ``proc`` is duck-typed (``poll()``/``send_signal()``/``wait()``):
    a real ``subprocess.Popen`` for the CLI fleet, a
    :class:`FakeBackendServer` handle in tests and the structural bench.
    The object is swapped wholesale on replacement (same name, carried
    breaker), so the router never sees a half-rebuilt backend.
    """

    def __init__(
        self,
        name: str,
        host: str,
        port: int,
        proc=None,
        heartbeat_file: str | None = None,
        pool_size: int = 8,
    ):
        self.name = name
        self.host = host
        self.port = int(port)
        self.proc = proc
        self.heartbeat_file = heartbeat_file
        self.state = STARTING
        self.breaker: CircuitBreaker | None = None
        self.started_at = time.perf_counter()
        # Load signals: polled from the backend's /metrics by the
        # fleet's poller; front_inflight counts this front tier's own
        # in-flight proxied requests (a request can be in a backend's
        # HTTP handler before it shows in that backend's queue gauge).
        self.polled_depth = 0
        self.polled_inflight = 0
        self.polled_latency_ms: float | None = None
        self.polled_compiles: int | None = None
        self.polled_at: float | None = None
        self.front_inflight = 0
        self._inflight_lock = make_lock("fleet.backend.inflight")
        self._ewma_s: float | None = None
        self._pool_size = pool_size
        self._idle: list[http.client.HTTPConnection] = []
        self._conn_lock = make_lock("fleet.backend.conn")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- transport ------------------------------------------------------------

    def _exchange(
        self, conn, method, path, body, timeout_s, headers,
    ) -> tuple[int, bytes, str, bool]:
        """One raw exchange on ``conn``; (status, body, content-type,
        keep-alive?).  ``headers`` override the JSON default wholesale —
        a proxied binary-wire body (serving/wire.py) must reach the
        backend under ITS content type, never re-labeled."""
        conn.timeout = timeout_s
        if conn.sock is not None:
            conn.sock.settimeout(timeout_s)
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        conn.request(method, path, body=body, headers=hdrs)
        resp = conn.getresponse()
        data = resp.read()
        ctype = resp.headers.get("Content-Type") or "application/json"
        return resp.status, data, ctype, not resp.will_close

    def request(
        self,
        method: str,
        path: str,
        body: bytes | None = None,
        timeout_s: float = 5.0,
        headers: dict | None = None,
    ) -> tuple[int, bytes]:
        """:meth:`request_full` without the response content type (the
        probe/metrics callers' surface, unchanged)."""
        status, data, _ctype = self.request_full(
            method, path, body=body, timeout_s=timeout_s, headers=headers
        )
        return status, data

    def request_full(
        self,
        method: str,
        path: str,
        body: bytes | None = None,
        timeout_s: float = 5.0,
        headers: dict | None = None,
    ) -> tuple[int, bytes, str]:
        """One HTTP exchange over a pooled keep-alive connection,
        returning ``(status, body, content_type)`` — the proxy path
        needs the content type to pass a binary response through
        verbatim (docs/SERVING.md wire protocol).

        ``timeout_s`` is the per-attempt socket timeout (applied to this
        attempt's connect and reads) — the fleet tier never blocks
        unboundedly on one backend (the jaxlint JL017 idiom).  Transport
        failures raise (``OSError`` / ``http.client.HTTPException``) and
        close the connection, never returning it to the pool — EXCEPT
        that a failure on a REUSED pooled connection gets one retry on a
        fresh connection first: the backend's own handler idle timeout
        (serving/server.py ``request_timeout_s``) closes keep-alives
        that sat in this pool too long, and treating that routine FIN as
        a backend failure would feed the circuit breaker on every
        sufficiently-spaced request.
        """
        with self._conn_lock:
            conn = self._idle.pop() if self._idle else None
        reused = conn is not None
        if conn is None:
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=timeout_s
            )
        try:
            status, data, ctype, keep = self._exchange(
                conn, method, path, body, timeout_s, headers
            )
        except Exception as e:
            try:
                conn.close()
            except Exception:
                pass
            # Stale keep-alive: one fresh-connection retry — ONLY for
            # the connection-level errors an idle-timed-out keep-alive
            # produces (the peer FIN'd/RST while the socket sat in the
            # pool: broken pipe / reset at send, RemoteDisconnected /
            # empty status line at read).  A read TIMEOUT is explicitly
            # excluded: retrying it would re-send the request to a
            # merely-slow backend and double the attempt's deadline.
            # Re-sending the connection-level cases is safe for the
            # same reason the router's cross-backend transport retry
            # is: /predict is idempotent.
            stale = (
                reused
                and not isinstance(e, TimeoutError)
                and isinstance(e, (
                    ConnectionResetError, BrokenPipeError,
                    ConnectionAbortedError, http.client.BadStatusLine,
                ))
            )
            if not stale:
                raise
            conn = http.client.HTTPConnection(
                self.host, self.port, timeout=timeout_s
            )
            try:
                status, data, ctype, keep = self._exchange(
                    conn, method, path, body, timeout_s, headers
                )
            except Exception:
                try:
                    conn.close()
                except Exception:
                    pass
                raise
        if keep:
            with self._conn_lock:
                if len(self._idle) < self._pool_size:
                    self._idle.append(conn)
                    conn = None
        if conn is not None:
            # Server asked to close, or the pool is full — either way
            # this connection's life ends here, not at GC time (an
            # overflow socket left to the finalizer leaks FDs under
            # sustained over-pool_size concurrency).
            conn.close()
        return status, data, ctype

    def metrics_json(self, timeout_s: float = 0.5) -> dict | None:
        """The backend's /metrics JSON snapshot, or None when it cannot
        be fetched (the caller decides whether that is an incident)."""
        try:
            status, data = self.request("GET", "/metrics", timeout_s=timeout_s)
            if status != 200:
                return None
            return json.loads(data)
        except (OSError, http.client.HTTPException, ValueError):
            return None

    def probe_ready(self, timeout_s: float = 0.5) -> bool:
        """/readyz == 200.  Transport failure and non-200 both read as
        not-ready (the supervisor counts consecutive misses)."""
        try:
            status, _data = self.request("GET", "/readyz", timeout_s=timeout_s)
            return status == 200
        except (OSError, http.client.HTTPException):
            return False

    # -- load / health signals -------------------------------------------------

    def observe_latency(self, latency_s: float) -> None:
        prev = self._ewma_s
        self._ewma_s = (
            latency_s if prev is None
            else EWMA_ALPHA * latency_s + (1.0 - EWMA_ALPHA) * prev
        )

    @property
    def ewma_latency_s(self) -> float | None:
        if self._ewma_s is not None:
            return self._ewma_s
        # Until the front has its own samples, the backend's reported
        # mean (from the polled snapshot) is the prior.
        if self.polled_latency_ms is not None:
            return self.polled_latency_ms / 1e3
        return None

    def load(self) -> int:
        """Polled backlog + this front's own in-flight proxies."""
        with self._inflight_lock:
            front_inflight = self.front_inflight
        return self.polled_depth + self.polled_inflight + front_inflight

    def inflight_enter(self) -> None:
        with self._inflight_lock:
            self.front_inflight += 1

    def inflight_exit(self) -> None:
        with self._inflight_lock:
            self.front_inflight -= 1

    def alive(self) -> bool:
        return self.proc is None or self.proc.poll() is None

    def heartbeat_age(self) -> float | None:
        if not self.heartbeat_file:
            return None
        return heartbeat_age_s(self.heartbeat_file)

    # -- lifecycle -------------------------------------------------------------

    def close_connections(self) -> None:
        with self._conn_lock:
            idle, self._idle = self._idle, []
        for conn in idle:
            try:
                conn.close()
            except Exception:
                pass

    def stop(self, grace_s: float = 5.0) -> None:
        """Grace-kill the owned process: SIGTERM (the serving CLI's
        graceful-drain path), SIGKILL whatever is left after the grace
        window.  External backends (no proc) just lose their pool."""
        self.close_connections()
        p = self.proc
        if p is None or p.poll() is not None:
            return
        if isinstance(p, subprocess.Popen):
            grace_stop([p], grace_s)
            return
        try:
            p.send_signal(signal.SIGTERM)
            deadline = time.monotonic() + grace_s
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(0.01)
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass


class FleetRouter:
    """Place proxied requests over the fleet's active backends.

    The PR-7 policy set over network backends; placement order is
    recomputed per request from the live load signals.  ``submit``
    returns the client-visible ``(status, body)`` — transport failures
    and backend 503s are absorbed by trying the next backend on the
    REMAINING deadline (one attempt per backend), and only when every
    backend refused does the caller see a single 503.
    """

    def __init__(
        self,
        fleet: "Fleet",
        policy: str = "cost",
        default_timeout_s: float = 1.0,
    ):
        if policy not in FLEET_POLICIES:
            raise ValueError(
                f"unknown fleet policy {policy!r}; have {FLEET_POLICIES}"
            )
        self.fleet = fleet
        self.policy = policy
        self.default_timeout_s = float(default_timeout_s)
        self._rr = 0
        self._lock = make_lock("fleet.router")

    # -- ordering (serving/router.py's shapes, backend-flavored) ---------------

    @staticmethod
    def _trials_first(order: list[Backend]) -> list[Backend]:
        trials = [
            b for b in order
            if b.breaker is not None
            and b.breaker.state == CIRCUIT_HALF_OPEN
            and b.breaker.allows()
        ]
        if not trials:
            return order
        return trials + [b for b in order if b not in trials]

    def _order(self, active: list[Backend]) -> list[Backend]:
        with self._lock:
            rotation = self._rr
            self._rr += 1
        k = rotation % len(active)
        rotated = active[k:] + active[:k]
        if self.policy == "roundrobin":
            return self._trials_first(rotated)
        if self.policy == "least-loaded":
            key = lambda b: b.load()  # noqa: E731 - local sort key
        else:
            ewmas = [
                b.ewma_latency_s for b in active
                if b.ewma_latency_s is not None
            ]
            if not ewmas:
                key = lambda b: b.load()  # noqa: E731 - local sort key
            else:
                prior = sum(ewmas) / len(ewmas)

                def key(b: Backend):
                    ewma = b.ewma_latency_s
                    return (b.load() + 1) * (prior if ewma is None else ewma)
        return self._trials_first(sorted(rotated, key=key))

    def _note(self, backend: Backend) -> None:
        registry = self.fleet.metrics.registry
        registry.counter(
            "fleet_route_decisions_total",
            help="front-tier request placements by chosen backend",
            backend=backend.name,
        ).inc()
        if self.fleet.sink:
            self.fleet.sink.emit(
                "fleet_route", policy=self.policy, backend=backend.name,
            )

    # -- the data plane --------------------------------------------------------

    def submit(
        self,
        body: bytes,
        timeout_s: float | None = None,
        headers: dict | None = None,
    ) -> tuple[int, bytes, str]:
        """Proxy one /predict body; returns the client outcome as
        ``(status, body, content_type)``.  The body AND its content
        type pass through verbatim in both directions — the front never
        decodes or re-encodes a payload (the zero-copy proxy contract,
        docs/SERVING.md wire protocol)."""
        metrics = self.fleet.metrics
        metrics.record_admitted()
        t0 = time.perf_counter()
        deadline = t0 + (
            self.default_timeout_s if timeout_s is None else timeout_s
        )
        active = self.fleet.active_backends()
        if not active:
            metrics.record_rejected()
            return 503, b'{"error": "no active backends"}', _JSON_TYPE
        last_503: tuple[bytes, str] | None = None
        transport_errors = 0
        for backend in self._order(active):
            breaker = backend.breaker
            if breaker is not None and not breaker.try_acquire():
                continue
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                if breaker is not None:
                    breaker.release()
                break
            self._note(backend)
            backend.inflight_enter()
            t_attempt = time.perf_counter()
            try:
                status, data, ctype = backend.request_full(
                    "POST", "/predict", body,
                    timeout_s=remaining, headers=headers,
                )
            except (OSError, http.client.HTTPException):
                # Transport: the backend may be dead or mid-replacement.
                # A /predict is idempotent, so the retry on the next
                # backend (remaining budget) cannot duplicate a client-
                # visible outcome — the client holds exactly one socket.
                if breaker is not None:
                    breaker.record_failure()
                transport_errors += 1
                continue
            finally:
                backend.inflight_exit()
            if status == 503:
                # Backpressure, not a failure verdict on the backend:
                # return any trial token and try the next one.  Only a
                # fleet-wide refusal surfaces (exactly one 503).
                if breaker is not None:
                    breaker.release()
                last_503 = (data, ctype)
                continue
            if status == 504:
                # The backend's own deadline verdict — ordered BEFORE
                # the >=500 failure branch: a 504 under a load spike is
                # queueing, not sickness, and counting it as a breaker
                # failure would open a healthy backend's circuit with
                # nothing (the supervisor replaces dead/unready
                # backends, not loaded ones) ever closing it again.
                if breaker is not None:
                    breaker.release()
                metrics.record_timeout()
            elif status >= 500:
                if breaker is not None:
                    breaker.record_failure()
                metrics.record_failed()
            elif status == 200:
                if breaker is not None:
                    breaker.record_success()
                backend.observe_latency(time.perf_counter() - t_attempt)
                metrics.record_completed(time.perf_counter() - t0)
            else:
                # 4xx: a client error is no verdict on the backend.
                if breaker is not None:
                    breaker.release()
            return status, data, ctype
        if time.perf_counter() >= deadline:
            metrics.record_timeout()
            return 504, b'{"error": "fleet deadline expired"}', _JSON_TYPE
        metrics.record_rejected()
        if last_503 is not None:
            return 503, last_503[0], last_503[1]
        return 503, json.dumps({
            "error": "no routable backends "
            f"({transport_errors} unreachable, every circuit open or "
            "backend draining)"
        }).encode(), _JSON_TYPE


class _BackendWatch:
    """Supervisor bookkeeping for one backend's restart ladder."""

    __slots__ = (
        "attempts", "restarts", "next_restart_t", "down_since",
        "probe_misses", "recovery_s", "healthy_since", "replacing",
    )

    def __init__(self):
        self.attempts = 0
        self.restarts = 0
        self.next_restart_t: float | None = None
        self.down_since: float | None = None
        self.probe_misses = 0
        self.recovery_s: list[float] = []
        self.healthy_since: float | None = None
        self.replacing = False


class FleetSupervisor:
    """Replace dead/hung backends under a seeded-backoff restart budget.

    The :class:`~..parallel.elastic.GangSupervisor` state machine
    applied per backend (replace ONE, never restart the world)::

        active ──dead/hung/unready──▶ replacing (grace kill, backoff)
           ▲                              │ attempts > restart_budget
           │ /readyz 200                  ▼
        starting ◀──── respawn        ejected (permanent)

    Health reads per tick: process liveness (``poll()``), heartbeat-file
    age (a backend whose dispatch loops stopped beating is wedged even
    if the process answers), and consecutive failed ``/readyz`` probes
    (transport errors or non-200).  A replacement spawns under the SAME
    name and port, warm-starts off the shared AOT cache, carries the old
    backend's breaker (re-admitted half-open), and counts on
    ``fleet_backend_restarts_total{backend=}`` + a ``backend_replace``
    event whose ``downtime_s`` is incident-to-serving.
    """

    def __init__(
        self,
        fleet: "Fleet",
        interval_s: float = 0.5,
        probe_timeout_s: float = 0.5,
        probe_failures: int = 3,
        heartbeat_timeout_s: float = 0.0,
        grace_s: float = 5.0,
        restart_budget: int = 3,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 10.0,
        backoff_jitter: float = 0.25,
        seed: int = 0,
        ready_timeout_s: float = 120.0,
        healthy_after_s: float = 30.0,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.fleet = fleet
        self.interval_s = interval_s
        self.probe_timeout_s = probe_timeout_s
        self.probe_failures = max(1, probe_failures)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.grace_s = grace_s
        self.restart_budget = max(0, restart_budget)
        self.ready_timeout_s = ready_timeout_s
        self.healthy_after_s = healthy_after_s
        self._ladder = BackoffLadder(
            base_s=backoff_base_s, max_s=backoff_max_s,
            jitter=backoff_jitter, seed=seed,
        )
        self._watch: dict[str, _BackendWatch] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "FleetSupervisor":
        if self._thread is not None:
            raise RuntimeError("fleet supervisor already started")
        self._thread = threading.Thread(
            target=self._run, name="fleet-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _run(self) -> None:
        last_err = 0.0
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:
                # One bad tick (a backend torn down mid-inspection) must
                # not end supervision for the life of the fleet — but a
                # PERSISTENTLY failing tick is a supervisor that has
                # silently become a no-op, so it must be observable
                # (rate-limited: one line per window, not one per tick).
                now = time.monotonic()
                if now - last_err > 5.0:
                    last_err = now
                    print(
                        f"fleet-supervisor: tick failed: "
                        f"{type(e).__name__}: {e}",
                        file=sys.stderr,
                    )
                    if self.fleet.sink:
                        self.fleet.sink.emit(
                            "supervisor_tick_error",
                            error=f"{type(e).__name__}: {e}",
                        )

    # -- the state machine -----------------------------------------------------

    def tick(self, now: float | None = None) -> None:
        """One inspection pass (public so tests step deterministically)."""
        now = now if now is not None else time.perf_counter()
        for backend in self.fleet.backends_snapshot():
            watch = self._watch.setdefault(backend.name, _BackendWatch())
            if backend.state == ACTIVE:
                reason = self._sick_reason(backend, watch)
                if reason is not None:
                    self._incident(backend, watch, reason, now)
                elif (
                    watch.attempts
                    and watch.healthy_since is not None
                    and now - watch.healthy_since > self.healthy_after_s
                ):
                    # Healed spell: the next incident starts a fresh
                    # ladder (the shared supervisor rule).
                    watch.attempts = 0
            elif backend.state == STARTING and watch.replacing:
                if not backend.alive():
                    self._incident(backend, watch, "died_starting", now)
                elif backend.probe_ready(self.probe_timeout_s):
                    self._serving_again(backend, watch, now)
                elif (
                    time.perf_counter() - backend.started_at
                    > self.ready_timeout_s
                ):
                    self._incident(backend, watch, "start_timeout", now)
            elif (
                backend.state == REPLACING
                and watch.next_restart_t is not None
                and now >= watch.next_restart_t
            ):
                self._respawn(backend, watch, now)

    def _sick_reason(self, backend: Backend, watch: _BackendWatch) -> str | None:
        if not backend.alive():
            return "dead"
        if (backend.breaker is not None
                and backend.breaker.state == CIRCUIT_OPEN):
            # The data plane tripped on consecutive request failures —
            # a backend that answers /readyz but poisons /predict.  An
            # open circuit only heals through this supervisor's
            # replacement path (half-open after respawn), so leaving it
            # would strand the backend unroutable forever (the
            # ReplicaSupervisor's circuit_open rule, one level up).
            return "circuit_open"
        if self.heartbeat_timeout_s > 0:
            age = backend.heartbeat_age()
            if age is not None:
                self.fleet.metrics.registry.gauge(
                    "fleet_backend_heartbeat_age_seconds",
                    help="seconds since each backend's last dispatch-loop "
                    "heartbeat (absent backends are still starting up)",
                    backend=backend.name,
                ).set(age)
                if age > self.heartbeat_timeout_s:
                    return "heartbeat"
        if backend.probe_ready(self.probe_timeout_s):
            watch.probe_misses = 0
            if watch.healthy_since is None:
                watch.healthy_since = time.perf_counter()
        else:
            watch.probe_misses += 1
            watch.healthy_since = None
            if watch.probe_misses >= self.probe_failures:
                return "unready"
        return None

    def _incident(self, backend, watch, reason, now) -> None:
        watch.probe_misses = 0
        watch.healthy_since = None
        if watch.down_since is None:
            watch.down_since = now
        if self.fleet.sink:
            self.fleet.sink.emit(
                "backend_death", backend=backend.name, reason=reason,
            )
        if backend.breaker is not None:
            backend.breaker.force_open(reason)
        self.fleet.set_state(backend, REPLACING)
        backend.stop(self.grace_s)
        if watch.attempts >= self.restart_budget:
            self._eject(backend, watch, reason)
            return
        backoff = self._ladder.delay_s(watch.attempts)
        watch.next_restart_t = now + backoff
        if self.fleet.sink:
            self.fleet.sink.emit(
                "backend_replace_scheduled", backend=backend.name,
                reason=reason, attempt=watch.attempts + 1,
                backoff_s=round(backoff, 3),
            )

    def _respawn(self, backend, watch, now) -> None:
        watch.attempts += 1
        watch.next_restart_t = None
        watch.replacing = True
        try:
            replacement = self.fleet.respawn(backend)
        except Exception as e:
            # The spawn itself failed (port race, exec error).  The
            # budget applies here too, or a spawn that always raises
            # would cycle replacing forever.
            if watch.attempts >= self.restart_budget:
                self._eject(backend, watch, f"respawn_failed: {e}")
                return
            backoff = self._ladder.delay_s(watch.attempts)
            watch.next_restart_t = now + backoff
            if self.fleet.sink:
                self.fleet.sink.emit(
                    "backend_replace_scheduled", backend=backend.name,
                    reason="respawn_failed", attempt=watch.attempts + 1,
                    backoff_s=round(backoff, 3),
                    error=f"{type(e).__name__}: {e}",
                )
            return
        self.fleet.set_state(replacement, STARTING)

    def _serving_again(self, backend, watch, now) -> None:
        """The replacement answered /readyz: route to it (half-open
        trials first) and close the incident."""
        watch.replacing = False
        watch.probe_misses = 0
        watch.restarts += 1
        watch.healthy_since = time.perf_counter()
        self.fleet.set_state(backend, ACTIVE)
        if backend.breaker is not None:
            backend.breaker.half_open()
        downtime = (
            now - watch.down_since if watch.down_since is not None else 0.0
        )
        watch.down_since = None
        watch.recovery_s.append(downtime)
        self.fleet.metrics.registry.counter(
            "fleet_backend_restarts_total",
            help="backend processes replaced by the fleet supervisor "
            "(warm start off the shared AOT cache; zero new traces)",
            backend=backend.name,
        ).inc()
        if self.fleet.sink:
            self.fleet.sink.emit(
                "backend_replace", backend=backend.name,
                attempt=watch.attempts, downtime_s=round(downtime, 3),
            )

    def _eject(self, backend, watch, reason) -> None:
        watch.next_restart_t = None
        watch.replacing = False
        self.fleet.set_state(backend, EJECTED)
        if backend.breaker is not None:
            backend.breaker.force_open("ejected")
        backend.stop(self.grace_s)
        if self.fleet.sink:
            self.fleet.sink.emit(
                "backend_eject", backend=backend.name, reason=str(reason),
                attempts=watch.attempts,
            )

    # -- reads -----------------------------------------------------------------

    def stats(self) -> dict:
        per_backend = {
            name: {
                "restarts": w.restarts,
                "attempts_since_healthy": w.attempts,
                "recovery_s": list(w.recovery_s),
            }
            for name, w in self._watch.items()
        }
        recoveries = [s for w in self._watch.values() for s in w.recovery_s]
        return {
            "backends": per_backend,
            "restarts_total": sum(w.restarts for w in self._watch.values()),
            "mean_recovery_s": (
                sum(recoveries) / len(recoveries) if recoveries else None
            ),
        }


class FleetAutoscaler:
    """Add/drain whole backends from the smoothed load signal.

    The signal is the mean per-active-backend backlog (polled queue
    depth + in-flight, the PR-4 gauges) smoothed by an EWMA — or, with
    ``signal="p99"``, the front's recent p99 latency in seconds.  A
    breach must SUSTAIN for ``window_s`` before acting, a scale event
    starts a ``cooldown_s`` during which no further event fires, and
    the two watermarks are separated — three layers of hysteresis, so
    an oscillating signal between the marks never flaps the fleet
    (tests/test_fleet.py pins it).

    Scale-up spawns a NEW backend (fresh name) and waits for /readyz;
    scale-down drains the NEWEST active backend: unroutable first, then
    settle (backend queue + in-flight + this front's own proxies all
    zero), then grace-kill — nothing admitted is lost.
    """

    def __init__(
        self,
        fleet: "Fleet",
        high_water: float = 8.0,
        low_water: float = 1.0,
        signal: str = "depth",
        window_s: float = 2.0,
        cooldown_s: float = 5.0,
        min_backends: int = 1,
        max_backends: int = 4,
        interval_s: float = 0.25,
        alpha: float = 0.3,
    ):
        if signal not in ("depth", "p99"):
            raise ValueError(f"unknown autoscale signal {signal!r}")
        if low_water >= high_water:
            raise ValueError(
                f"low_water {low_water} must be < high_water {high_water} "
                "(the hysteresis band)"
            )
        if min_backends < 1 or max_backends < min_backends:
            raise ValueError(
                f"need 1 <= min_backends <= max_backends, got "
                f"{min_backends}..{max_backends}"
            )
        self.fleet = fleet
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.signal = signal
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.min_backends = int(min_backends)
        self.max_backends = int(max_backends)
        self.interval_s = float(interval_s)
        self.alpha = float(alpha)
        self.smoothed: float | None = None
        self._high_since: float | None = None
        self._low_since: float | None = None
        self._cooldown_until = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "FleetAutoscaler":
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")
        self._thread = threading.Thread(
            target=self._run, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _run(self) -> None:
        last_err = 0.0
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:
                # One bad tick must not end autoscaling, but a silent
                # no-op control loop must not be possible either (the
                # supervisor's rate-limited rule).
                now = time.monotonic()
                if now - last_err > 5.0:
                    last_err = now
                    print(
                        f"fleet-autoscaler: tick failed: "
                        f"{type(e).__name__}: {e}",
                        file=sys.stderr,
                    )
                    if self.fleet.sink:
                        self.fleet.sink.emit(
                            "autoscaler_tick_error",
                            error=f"{type(e).__name__}: {e}",
                        )

    # -- the control loop ------------------------------------------------------

    def _raw_signal(self) -> float | None:
        active = self.fleet.active_backends()
        if not active:
            return None
        if self.signal == "p99":
            lat = sorted(self.fleet.metrics._latency.values())
            if not lat:
                return 0.0
            from ..obs.registry import percentile

            return percentile(lat, 99)
        return sum(b.load() for b in active) / len(active)

    def observe(self, raw: float) -> float:
        """Fold one raw reading into the EWMA (public for tests)."""
        self.smoothed = (
            raw if self.smoothed is None
            else self.alpha * raw + (1.0 - self.alpha) * self.smoothed
        )
        return self.smoothed

    def tick(self, now: float | None = None, raw: float | None = None) -> None:
        """One control decision (public so tests drive a synthetic
        signal deterministically via ``raw`` + ``now``)."""
        now = now if now is not None else time.perf_counter()
        raw = raw if raw is not None else self._raw_signal()
        if raw is None:
            return
        sig = self.observe(raw)
        n = self.fleet.scalable_count()
        if sig > self.high_water:
            self._low_since = None
            if self._high_since is None:
                self._high_since = now
            elif (
                now - self._high_since >= self.window_s
                and now >= self._cooldown_until
                and n < self.max_backends
            ):
                self._scale("up", sig, now)
        elif sig < self.low_water:
            self._high_since = None
            if self._low_since is None:
                self._low_since = now
            elif (
                now - self._low_since >= self.window_s
                and now >= self._cooldown_until
                and n > self.min_backends
            ):
                self._scale("down", sig, now)
        else:
            # Inside the hysteresis band: both breach clocks reset —
            # an oscillation between the marks never accumulates.
            self._high_since = None
            self._low_since = None

    def _scale(self, direction: str, sig: float, now: float) -> None:
        fleet = self.fleet
        before = fleet.scalable_count()
        t_scale = time.perf_counter()
        try:
            if direction == "up":
                fleet.add_backend()
            else:
                fleet.remove_backend()
            # Count/emit only an action that actually took effect — a
            # spawn that missed its ready window or a refused drain must
            # not inflate the scraped tally or the perf_report timeline.
            fleet.metrics.registry.counter(
                "fleet_scale_events_total",
                help="autoscaler actions by direction",
                direction=direction,
            ).inc()
            if fleet.sink:
                fleet.sink.emit(
                    "fleet_scale", direction=direction,
                    backends=before, signal=round(sig, 4),
                    kind=self.signal,
                )
        except Exception:
            if fleet.sink:
                fleet.sink.emit(
                    "fleet_scale_failed", direction=direction,
                    backends=before, signal=round(sig, 4),
                )
            raise
        finally:
            # Cooldown from AFTER the (blocking) bring-up/drain, on the
            # CALLER'S clock (tests tick a synthetic one), and the
            # breach clocks restart: the post-scale world re-proves the
            # breach before the next event.
            self._cooldown_until = (
                now + (time.perf_counter() - t_scale) + self.cooldown_s
            )
            self._high_since = None
            self._low_since = None
            self.smoothed = None  # the signal regime just changed


class Fleet:
    """Backends + router + poller (+ optional supervisor/autoscaler).

    ``spawn(name) -> Backend`` is the backend factory — the CLI fleet's
    spawn launches ``python -m pytorch_mnist_ddp_tpu.serving``
    subprocesses on assigned ports (reusing a name's port on
    replacement); tests and the structural bench spawn
    :class:`FakeBackendServer`\\ s.  All membership changes (add /
    drain / replace / eject) go through this object so the router's
    snapshot is always consistent.
    """

    def __init__(
        self,
        spawn,
        policy: str = "cost",
        metrics: ServingMetrics | None = None,
        sink=None,
        default_timeout_s: float = 1.0,
        poll_s: float = 0.25,
        poll_timeout_s: float = 0.5,
        failure_threshold: int = 3,
        trial_limit: int = 1,
        trial_successes: int = 1,
        settle_timeout_s: float = 30.0,
        grace_s: float = 5.0,
        name_prefix: str = "b",
        response_cache: int | None = None,
    ):
        self.spawn = spawn
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.sink = sink
        # Front-tier host hot path (docs/SERVING.md): wire-format
        # accounting for the proxy, and — with ``response_cache`` — a
        # content-addressed response cache keyed on the RAW proxied
        # body, so a hit answers without touching a backend and
        # concurrent identical bodies coalesce onto one proxied
        # dispatch.  Backends serve one fixed checkpoint per fleet run
        # (replacements re-exec the same argv), so the raw body IS the
        # content address; ``response_cache.invalidate()`` is the
        # operator hook if weights ever swap under a live front.
        self.metrics.ensure_wire()
        self.response_cache = (
            ResponseCache(
                response_cache, metrics=self.metrics, sink=sink,
                scope="front",
            )
            if response_cache else None
        )
        self.poll_s = poll_s
        self.poll_timeout_s = poll_timeout_s
        self.settle_timeout_s = settle_timeout_s
        self.grace_s = grace_s
        self.name_prefix = name_prefix
        self.router = FleetRouter(
            self, policy=policy, default_timeout_s=default_timeout_s
        )
        self._breaker_kwargs = dict(
            failure_threshold=failure_threshold,
            trial_limit=trial_limit,
            trial_successes=trial_successes,
        )
        self.backends: list[Backend] = []
        self.retired: list[Backend] = []
        self._seq = 0
        self._lock = make_lock("fleet.members")
        self.supervisor: FleetSupervisor | None = None
        self.autoscaler: FleetAutoscaler | None = None
        self._poller: threading.Thread | None = None
        self._stop_poll = threading.Event()
        # Scrapeable-before-first-event registration (the CI grep
        # contract): both scale directions and every state gauge exist
        # from the first exposition.
        self.metrics.ensure_fleet()
        self._refresh_state_gauges()

    # -- membership reads ------------------------------------------------------

    def backends_snapshot(self) -> list[Backend]:
        with self._lock:
            return list(self.backends)

    def active_backends(self) -> list[Backend]:
        with self._lock:
            return [b for b in self.backends if b.state == ACTIVE]

    def scalable_count(self) -> int:
        """Backends that count toward the autoscaler's bounds: anything
        not permanently out (a replacing backend is still capacity the
        supervisor is bringing back)."""
        with self._lock:
            return sum(
                1 for b in self.backends if b.state not in (EJECTED,)
            )

    def routable_count(self) -> int:
        with self._lock:
            return sum(
                1 for b in self.backends
                if b.state == ACTIVE
                and (b.breaker is None or b.breaker.allows())
            )

    def backend(self, name: str) -> Backend:
        with self._lock:
            for b in self.backends:
                if b.name == name:
                    return b
        raise KeyError(f"no backend named {name!r}")

    def admin_rollout(
        self, path: str, body: bytes, timeout_s: float = 30.0
    ) -> tuple[int, dict]:
        """Forward one rollout admin verb (``POST /admin/*``,
        serving/server.py) to every ACTIVE backend, SEQUENTIALLY — the
        fleet tier of a zero-downtime swap (docs/SERVING.md swap state
        machine): each backend flips reference-atomically while its
        peers keep serving, so the fleet never drops a request; the
        deterministic canary split needs no coordination at all (every
        backend hashes a payload to the same assignment).

        After a successful mutation the FRONT response cache — keyed on
        raw request bodies, blind to weights — is invalidated; each
        backend already bumped its own cache generation.  A partial
        failure returns 502 with per-backend detail and still
        invalidates (some backends DID move); every verb is idempotent
        at each backend, so the operator re-issues it to converge."""
        results: dict = {}
        ok = True
        mutation = path != "/admin/rollout"
        for b in self.active_backends():
            try:
                status, data, _ctype = b.request_full(
                    "POST", path, body, timeout_s=timeout_s,
                    headers={"Content-Type": "application/json"},
                )
                try:
                    detail = json.loads(data)
                except ValueError:
                    detail = data.decode("utf-8", errors="replace")
                results[b.name] = {"status": status, "body": detail}
                ok = ok and status == 200
            except (OSError, http.client.HTTPException) as e:
                results[b.name] = {"error": f"{type(e).__name__}: {e}"}
                ok = False
        if mutation and self.response_cache is not None:
            self.response_cache.invalidate()
        if self.sink and mutation:
            self.sink.emit(
                "fleet_admin", path=path, ok=ok,
                backends=sorted(results),
            )
        return (200 if ok else 502), {"ok": ok, "backends": results}

    def set_state(self, backend: Backend, state: str) -> None:
        if state not in BACKEND_STATES:
            raise ValueError(f"unknown backend state {state!r}")
        with self._lock:
            backend.state = state
        self._refresh_state_gauges()

    def _refresh_state_gauges(self) -> None:
        with self._lock:
            counts = {state: 0 for state in BACKEND_STATES}
            for b in self.backends:
                counts[b.state] += 1
            counts[RETIRED] += len(self.retired)
        for state, n in counts.items():
            self.metrics.registry.gauge(
                "fleet_backends",
                help="backend processes by lifecycle state",
                state=state,
            ).set(n)

    # -- lifecycle -------------------------------------------------------------

    def start(
        self,
        n: int,
        wait_ready_s: float = 120.0,
        supervise: bool = True,
        supervisor_kwargs: dict | None = None,
        autoscale: bool = False,
        autoscaler_kwargs: dict | None = None,
    ) -> "Fleet":
        """Spawn the initial backends, wait for every /readyz, then
        start the poller (+ supervisor/autoscaler)."""
        if n < 1:
            raise ValueError(f"fleet size must be >= 1, got {n}")
        for _ in range(n):
            self._spawn_next()
        deadline = time.perf_counter() + wait_ready_s
        for b in self.backends_snapshot():
            self._wait_ready(b, deadline)
        self._poller = threading.Thread(
            target=self._poll_loop, name="fleet-poller", daemon=True
        )
        self._poller.start()
        if supervise:
            self.supervisor = FleetSupervisor(
                self, **(supervisor_kwargs or {})
            ).start()
        if autoscale:
            self.autoscaler = FleetAutoscaler(
                self, **(autoscaler_kwargs or {})
            ).start()
        return self

    def stop(self, grace_s: float | None = None) -> None:
        """Autoscaler and supervisor first (a replacement racing the
        teardown would spawn into a dying fleet), then poller, then
        grace-stop every backend — SIGTERM is the serving CLI's
        graceful-drain path, so admitted work finishes."""
        if self.autoscaler is not None:
            self.autoscaler.stop()
            self.autoscaler = None
        if self.supervisor is not None:
            self.supervisor.stop()
            self.supervisor = None
        self._stop_poll.set()
        if self._poller is not None:
            self._poller.join()
            self._poller = None
        for b in self.backends_snapshot() + list(self.retired):
            b.stop(self.grace_s if grace_s is None else grace_s)

    # -- spawning --------------------------------------------------------------

    def _register(self, backend: Backend, breaker: CircuitBreaker | None) -> None:
        backend.breaker = breaker if breaker is not None else CircuitBreaker(
            backend.name, registry=self.metrics.registry, sink=self.sink,
            **self._breaker_kwargs,
        )
        # The restart family must exist per backend from registration
        # (a zero is a statement; an absent family is a flaky grep).
        self.metrics.registry.counter(
            "fleet_backend_restarts_total",
            help="backend processes replaced by the fleet supervisor "
            "(warm start off the shared AOT cache; zero new traces)",
            backend=backend.name,
        )

    def _spawn_next(self) -> Backend:
        with self._lock:
            name = f"{self.name_prefix}{self._seq}"
            self._seq += 1
        backend = self.spawn(name)
        self._register(backend, None)
        with self._lock:
            self.backends.append(backend)
        self._refresh_state_gauges()
        return backend

    def respawn(self, old: Backend) -> Backend:
        """Replacement under the SAME name (the supervisor's mechanics):
        the factory reuses the name's port, the new Backend carries the
        old breaker (still open until the half-open trial passes), and
        the swap is atomic under the membership lock."""
        replacement = self.spawn(old.name)
        self._register(replacement, old.breaker)
        with self._lock:
            idx = self.backends.index(old)
            self.backends[idx] = replacement
        self._refresh_state_gauges()
        return replacement

    def _wait_ready(self, backend: Backend, deadline: float) -> None:
        while time.perf_counter() < deadline:
            if not backend.alive():
                raise RuntimeError(
                    f"backend {backend.name} exited during bring-up "
                    f"(code {backend.proc.poll()})"
                )
            if backend.probe_ready(self.poll_timeout_s):
                self.set_state(backend, ACTIVE)
                return
            time.sleep(0.05)
        raise RuntimeError(
            f"backend {backend.name} not ready within the bring-up window"
        )

    # -- elasticity ------------------------------------------------------------

    def add_backend(self, wait_ready_s: float = 120.0) -> str:
        """Scale-up: spawn a NEW backend (fresh name) and block until it
        serves.  Off the shared AOT cache this is seconds, not a compile
        storm (the warm-start contract).  A backend that dies or misses
        its ready window is torn down and REMOVED before the error
        propagates — a zombie "starting" member would count toward the
        autoscaler's max bound forever while serving nothing."""
        backend = self._spawn_next()
        try:
            self._wait_ready(backend, time.perf_counter() + wait_ready_s)
        except Exception:
            backend.stop(self.grace_s)
            with self._lock:
                if backend in self.backends:
                    self.backends.remove(backend)
            self._refresh_state_gauges()
            raise
        return backend.name

    def remove_backend(self, name: str | None = None) -> str:
        """Scale-down: drain → settle → kill, nothing lost.

        Default target is the NEWEST active backend (last added — the
        autoscaler's LIFO discipline keeps the fleet's stable core
        warm).  Ordering is the correctness: unroutable FIRST (state
        draining), then wait until the backend's own queue + in-flight
        window are empty AND this front has no proxied request still
        open against it, then SIGTERM (the backend's own graceful-drain
        path is the second belt)."""
        with self._lock:
            active = [b for b in self.backends if b.state == ACTIVE]
            if name is not None:
                targets = [b for b in active if b.name == name]
                if not targets:
                    raise RuntimeError(f"no active backend named {name!r}")
                target = targets[0]
            else:
                if not active:
                    raise RuntimeError("no active backend to remove")
                target = active[-1]
            if len(active) == 1:
                raise RuntimeError(
                    f"refusing to drain {target.name!r}: it is the last "
                    "active backend (stop the fleet instead)"
                )
            target.state = DRAINING
        self._refresh_state_gauges()
        t0 = time.perf_counter()
        deadline = t0 + self.settle_timeout_s
        while time.perf_counter() < deadline:
            if target.front_inflight == 0:
                snap = target.metrics_json(self.poll_timeout_s)
                if snap is not None:
                    depth = snap.get("queue_depth", 0) or 0
                    inflight = (snap.get("pipeline") or {}).get("inflight", 0) or 0
                    if depth == 0 and inflight == 0:
                        break
            time.sleep(0.05)
        target.stop(self.grace_s)
        with self._lock:
            self.backends.remove(target)
            target.state = RETIRED
            self.retired.append(target)
        self._refresh_state_gauges()
        if self.sink:
            self.sink.emit(
                "backend_drain", backend=target.name,
                duration_s=round(time.perf_counter() - t0, 3),
            )
        return target.name

    # -- the poller ------------------------------------------------------------

    def _poll_loop(self) -> None:
        while not self._stop_poll.wait(self.poll_s):
            for b in self.backends_snapshot():
                if b.state not in (ACTIVE, DRAINING):
                    continue
                self._poll_one(b)

    def _poll_one(self, b: Backend) -> None:
        snap = b.metrics_json(self.poll_timeout_s)
        if snap is None:
            return
        b.polled_depth = int(snap.get("queue_depth", 0) or 0)
        b.polled_inflight = int(
            (snap.get("pipeline") or {}).get("inflight", 0) or 0
        )
        lat = (snap.get("latency_ms") or {}).get("mean")
        if lat:
            b.polled_latency_ms = float(lat)
        compiles = snap.get("compiles")
        if compiles is not None:
            b.polled_compiles = int(compiles)
        b.polled_at = time.perf_counter()

    # -- the /metrics surface --------------------------------------------------

    def snapshot(self, refresh: bool = True) -> dict:
        """The front's /metrics JSON: the standard ServingMetrics
        snapshot (front-side outcomes + latency) plus the per-backend
        block and the fleet aggregates.  ``refresh`` re-polls each
        live backend so the compile tally is current, not poll_s stale
        (the loadgen's retrace check reads it)."""
        if refresh:
            for b in self.backends_snapshot():
                if b.state in (ACTIVE, DRAINING):
                    self._poll_one(b)
        with self._lock:
            everything = list(self.backends) + list(self.retired)
            per_backend = {
                b.name: {
                    "state": b.state,
                    "url": b.url,
                    "circuit": (
                        b.breaker.state if b.breaker is not None else None
                    ),
                    "queue_depth": b.polled_depth,
                    "inflight": b.polled_inflight,
                    "front_inflight": b.front_inflight,
                    "ewma_latency_ms": (
                        1e3 * b.ewma_latency_s
                        if b.ewma_latency_s is not None else None
                    ),
                    "compiles": b.polled_compiles,
                }
                for b in everything
            }
            depth_total = sum(
                b.polled_depth for b in self.backends if b.state == ACTIVE
            )
            compiles_total = sum(
                b.polled_compiles or 0 for b in everything
            )
        snap = self.metrics.snapshot(
            queue_depth=depth_total, compiles=compiles_total
        )
        snap["backends"] = per_backend
        snap["fleet"] = {
            "policy": self.router.policy,
            "routable": self.routable_count(),
            "supervisor": (
                self.supervisor.stats() if self.supervisor is not None
                else None
            ),
            "autoscaler": (
                {
                    "signal": self.autoscaler.signal,
                    "smoothed": self.autoscaler.smoothed,
                    "high_water": self.autoscaler.high_water,
                    "low_water": self.autoscaler.low_water,
                    "min": self.autoscaler.min_backends,
                    "max": self.autoscaler.max_backends,
                }
                if self.autoscaler is not None else None
            ),
        }
        return snap


# ---------------------------------------------------------------------------
# The front HTTP surface


class FleetHandler(BaseHTTPRequestHandler):
    server_version = "mnist-fleet/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    def setup(self):
        # The PR-12 satellite discipline (serving/server.py): a dead or
        # stalled client must not pin a handler thread forever — and a
        # fleet front multiplies held connections by fan-in.
        self.timeout = getattr(self.server, "request_timeout_s", 30.0)
        super().setup()

    def _send_json(self, status: int, payload: dict) -> None:
        self._send_raw(status, json.dumps(payload).encode())

    def _send_raw(
        self, status: int, body: bytes,
        content_type: str = "application/json",
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib casing
        fleet: Fleet = self.server.fleet  # type: ignore[attr-defined]
        if self.path == "/healthz":
            self._send_json(200, {
                "status": "ok",
                "backends": {
                    b.name: b.state for b in fleet.backends_snapshot()
                },
            })
        elif self.path == "/readyz":
            n = fleet.routable_count()
            self._send_json(200 if n > 0 else 503, {
                "status": "ready" if n > 0 else "unready",
                "routable_backends": n,
                "backends": {
                    b.name: b.state for b in fleet.backends_snapshot()
                },
                "circuits": {
                    b.name: (b.breaker.state if b.breaker else None)
                    for b in fleet.backends_snapshot()
                },
            })
        elif self.path.startswith("/metrics"):
            wants_prom = (
                "format=prom" in self.path
                or "text/plain" in self.headers.get("Accept", "")
            )
            if wants_prom:
                # Mirror the aggregate gauges from the poller's cache
                # (refresh=False): a scrape must not trigger N
                # synchronous backend round trips whose JSON is then
                # discarded — the poller keeps the cache poll_s-fresh.
                fleet.snapshot(refresh=False)
                self._send_raw(
                    200, render_prometheus(fleet.metrics.registry).encode(),
                    content_type="text/plain; version=0.0.4; charset=utf-8",
                )
            else:
                self._send_json(200, fleet.snapshot())
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})

    def do_POST(self):  # noqa: N802 - stdlib casing
        fleet: Fleet = self.server.fleet  # type: ignore[attr-defined]
        admin = self.path.startswith("/admin/")
        if self.path != "/predict" and not admin:
            self._send_json(404, {"error": f"no such path {self.path!r}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            self._send_json(400, {"error": "malformed Content-Length"})
            return
        try:
            body = self.rfile.read(length)
        except (TimeoutError, OSError):
            # Client went silent mid-body: 408 and drop the connection
            # (serving/server.py's idle-client contract).
            try:
                self._send_json(408, {"error": "request body read timed out"})
            except OSError:
                pass
            self.close_connection = True
            return
        if admin:
            # Rolling per-backend forwarding (Fleet.admin_rollout): the
            # fleet tier of swap/canary/rollback.
            status, payload = fleet.admin_rollout(self.path, body)
            self._send_json(status, payload)
            return
        # Pass-through proxy: the request's content type rides to the
        # backend and the backend's rides back — a binary-wire body
        # (serving/wire.py) is never decoded, re-encoded, or re-labeled
        # at this tier (the zero-copy proxy contract, pinned by
        # tests/test_hostpath.py).
        req_ctype = self.headers.get("Content-Type") or "application/json"
        fmt = (
            "binary"
            if req_ctype.split(";")[0].strip().lower() == WIRE_REQUEST_TYPE
            else "json"
        )
        headers = {"Content-Type": req_ctype}
        cache = fleet.response_cache

        def reply(status, data, ctype):
            fleet.metrics.record_wire(
                fmt, bytes_in=len(body), bytes_out=len(data)
            )
            self._send_raw(status, data, content_type=ctype)

        if cache is None:
            status, data, ctype = fleet.router.submit(body, headers=headers)
            reply(status, data, ctype)
            return
        # Front-tier cache + single-flight: the content address is the
        # RAW body under its content type (identical bytes -> identical
        # backend answer, since every backend serves the same weights).
        # Only 200s fill the cache; any other outcome resolves current
        # waiters and is dropped — a refused or failed proxy must never
        # become a stale fill.
        # Multi-part hash: the body is never concatenated or copied on
        # this pass-through tier (the zero-copy proxy discipline).
        key = cache.key(req_ctype.encode(), b"\x00", body)
        outcome, val = cache.claim(key)
        if outcome == HIT:
            reply(*val)
            return
        if outcome == COALESCED:
            try:
                result = val.result(fleet.router.default_timeout_s + 1.0)
            except FlightTimeout:
                # This joiner's own deadline — counted like any other
                # client-visible 504 (the claimant's outcome, whatever
                # it ends up being, is counted by router.submit).
                fleet.metrics.record_timeout()
                reply(
                    504, b'{"error": "fleet deadline expired"}',
                    "application/json",
                )
                return
            except BaseException as e:
                # The claimant's submit raised (cache.fail re-raised it
                # to every joiner — BaseException included, whatever
                # killed that thread): each waiter still gets exactly
                # one HTTP outcome, never a dropped connection.
                reply(
                    500,
                    json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}
                    ).encode(),
                    "application/json",
                )
                return
            reply(*result)
            return
        try:
            status, data, ctype = fleet.router.submit(body, headers=headers)
        except BaseException as e:
            cache.fail(key, val, e)
            raise
        cache.complete(
            key, val, (status, data, ctype), store=status == 200
        )
        reply(status, data, ctype)


class FleetHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the fleet for its handlers."""

    daemon_threads = True

    def __init__(
        self, address: tuple[str, int], fleet: Fleet,
        request_timeout_s: float = 30.0,
    ):
        super().__init__(address, FleetHandler)
        self.fleet = fleet
        self.request_timeout_s = request_timeout_s


def make_fleet_server(
    fleet: Fleet,
    host: str = "127.0.0.1",
    port: int = 0,
    request_timeout_s: float = 30.0,
) -> FleetHTTPServer:
    """Wire a (started) fleet into a front HTTP server (port 0 =
    OS-assigned; the bound port is ``server.server_address[1]``)."""
    return FleetHTTPServer((host, port), fleet, request_timeout_s)


# ---------------------------------------------------------------------------
# The structural fake backend (tests + the host-bound bench caveat)


class _FakeProc:
    """Process-handle duck type for an in-process fake backend."""

    def __init__(self, server: "FakeBackendServer"):
        self._server = server

    def poll(self):
        return None if self._server.running else 0

    def send_signal(self, signum) -> None:
        if signum == signal.SIGKILL:
            self._server.kill()
        else:
            self._server.shutdown()

    def terminate(self) -> None:
        self._server.shutdown()

    def kill(self) -> None:
        self._server.kill()

    def wait(self, timeout=None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._server.running:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired("fake-backend", timeout)
            time.sleep(0.005)
        return 0


class _FakeBackendHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    def _send(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib casing
        fake: FakeBackendServer = self.server.fake  # type: ignore[attr-defined]
        if self.path == "/readyz":
            ready = fake.ready and fake.running
            self._send(200 if ready else 503,
                       {"status": "ready" if ready else "unready"})
        elif self.path == "/healthz":
            self._send(200, {"status": "ok"})
        elif self.path.startswith("/metrics"):
            self._send(200, fake.metrics_snapshot())
        else:
            self._send(404, {"error": self.path})

    def do_POST(self):  # noqa: N802 - stdlib casing
        fake: FakeBackendServer = self.server.fake  # type: ignore[attr-defined]
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length)
        if fake.fail_predict:
            fake.failed += 1
            self._send(500, {"error": "injected backend failure"})
            return
        try:
            n = len(json.loads(raw or b"{}").get("instances") or [None])
        except ValueError:
            n = 1
        with fake.depth_lock:
            fake.waiting += 1
        # Serial "device": one request at a time per backend — the
        # structural reason N backends beat 1 (the scaling pin).
        with fake.slot:
            with fake.depth_lock:
                fake.waiting -= 1
                fake.inflight += 1
            time.sleep(fake.service_s)
            with fake.depth_lock:
                fake.inflight -= 1
        if fake.killed:
            # An abrupt kill mid-service: the response is never written
            # (the client sees a transport error, like a real SIGKILL).
            self.close_connection = True
            return
        fake.completed += 1
        self._send(200, {"predictions": [0] * n})


class FakeBackendServer:
    """A real-HTTP fake serving backend with SERIAL capacity.

    The structural half of the fleet story on a host-bound CI box
    (docs/SERVING.md): each fake serves one request at a time, taking
    ``service_s`` — so wall time over a fixed workload scales with the
    backend count, and the fleet's routing/replacement/scaling
    machinery is exercised over genuine sockets without N jax processes
    fighting two cores.  ``warm_store`` plays the shared AOT cache: a
    name already in the store "warm-starts" reporting zero compiles —
    exactly the replacement pin the real fleet gets from
    ``ExecutableStore``.
    """

    def __init__(
        self,
        name: str = "fake",
        service_s: float = 0.02,
        buckets: tuple[int, ...] = (4, 8),
        warm_store: set | None = None,
        heartbeat_file: str | None = None,
        heartbeat_interval_s: float = 0.05,
        port: int = 0,
    ):
        self.name = name
        self.service_s = float(service_s)
        self.ready = True
        self.fail_predict = False
        self.killed = False
        self.waiting = 0
        self.inflight = 0
        self.completed = 0
        self.failed = 0
        self.depth_lock = threading.Lock()
        self.slot = threading.Lock()
        if warm_store is not None and name in warm_store:
            self.compiles = 0
        else:
            self.compiles = len(buckets)
            if warm_store is not None:
                warm_store.add(name)
        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", port), _FakeBackendHandler
        )
        self._httpd.daemon_threads = True
        self._httpd.fake = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self.running = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"fake-backend-{name}",
        )
        self._thread.start()
        self._hb_stop = threading.Event()
        self._hb_thread: threading.Thread | None = None
        if heartbeat_file:
            hb = Heartbeat(heartbeat_file, interval_s=heartbeat_interval_s)

            def _beat() -> None:
                while not self._hb_stop.wait(heartbeat_interval_s):
                    hb.beat(force=True)

            self._hb_thread = threading.Thread(target=_beat, daemon=True)
            self._hb_thread.start()

    @property
    def proc(self) -> _FakeProc:
        return _FakeProc(self)

    def metrics_snapshot(self) -> dict:
        with self.depth_lock:
            waiting, inflight = self.waiting, self.inflight
        return {
            "queue_depth": waiting,
            "pipeline": {"inflight": inflight},
            "compiles": self.compiles,
            "requests": {"completed": self.completed, "failed": self.failed},
            "latency_ms": {"mean": 1e3 * self.service_s},
        }

    def stop_heartbeat(self) -> None:
        """Simulate a wedged dispatch loop: alive, answering HTTP, but
        no longer beating (the supervisor's mtime-age signal)."""
        self._hb_stop.set()

    def shutdown(self) -> None:
        """Graceful stop (the SIGTERM analogue): in-flight requests
        finish, then the server goes away."""
        if not self.running:
            return
        self.running = False
        self._hb_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()

    def kill(self) -> None:
        """Abrupt stop (the SIGKILL analogue): in-flight requests get
        their connections dropped without a response."""
        if not self.running:
            return
        self.killed = True
        self.running = False
        self._hb_stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()


def fake_backend_spawner(
    service_s: float = 0.02,
    buckets: tuple[int, ...] = (4, 8),
    warm_store: set | None = None,
    heartbeat_dir: str | None = None,
    registry: dict | None = None,
):
    """A ``spawn(name) -> Backend`` factory over fake backends.

    ``warm_store`` (a plain set, shared across spawns) makes every
    REPLACEMENT warm-start with zero compiles; ``registry`` (a dict, if
    given) maps name -> live FakeBackendServer so tests and the bench
    kill-round can reach the fake to kill/hang it.
    """
    store = warm_store if warm_store is not None else set()

    def spawn(name: str) -> Backend:
        hb = (
            heartbeat_path(heartbeat_dir, name) if heartbeat_dir else None
        )
        fake = FakeBackendServer(
            name=name, service_s=service_s, buckets=buckets,
            warm_store=store, heartbeat_file=hb,
        )
        if registry is not None:
            registry[name] = fake
        return Backend(
            name, "127.0.0.1", fake.port, proc=fake.proc,
            heartbeat_file=hb,
        )

    return spawn


def subprocess_backend_spawner(
    backend_args: list[str],
    host: str = "127.0.0.1",
    base_port: int = 8101,
    heartbeat_dir: str | None = None,
    log_dir: str | None = None,
):
    """A ``spawn(name) -> Backend`` factory over REAL serving processes:
    ``python -m pytorch_mnist_ddp_tpu.serving <backend_args> --host H
    --port P``.  Port assignment is by name, so a REPLACEMENT reuses its
    predecessor's port (``HTTPServer.allow_reuse_address`` makes the
    rebind race-free); ``backend_args`` should carry a shared
    ``--aot-cache`` so replacements warm-start.  ``spawn.handles`` maps
    backend name -> its open log file (one per name, reused across
    respawns; the owner closes them at fleet exit)."""
    ports: dict[str, int] = {}
    handles: dict[str, object] = {}

    def spawn(name: str) -> Backend:
        port = ports.setdefault(name, base_port + len(ports))
        hb = heartbeat_path(heartbeat_dir, name) if heartbeat_dir else None
        cmd = [
            sys.executable, "-m", "pytorch_mnist_ddp_tpu.serving",
            *backend_args, "--host", host, "--port", str(port),
        ]
        if log_dir:
            # Per-backend telemetry subdirectory: the front strips the
            # operator's --telemetry-dir from backend argv (two rank-0
            # backends sharing one dir would collide on the JSONL
            # filename), so re-add it scoped by name — backend events
            # (serving_request, model_swap, rollback, ...) land beside
            # the front's events-fleet.jsonl instead of vanishing.  A
            # replacement reuses its predecessor's subdir; the sink is
            # append-mode, so the event trail survives respawns.
            cmd += ["--telemetry-dir", os.path.join(log_dir, name)]
        env = dict(os.environ)
        if hb:
            env[ENV_FLEET_HEARTBEAT_FILE] = hb
        # The backend must import this package regardless of the
        # operator's CWD (the front may have been launched via an
        # installed console path or a repo checkout).
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else pkg_root
        )
        stdout = stderr = None
        if log_dir:
            # ONE append-mode handle per name, reused across respawns —
            # a replacement inherits its predecessor's log file, and a
            # periodically flapping backend cannot leak an FD per
            # incident over the fleet's lifetime.
            stdout = handles.get(name)
            if stdout is None:
                stdout = handles[name] = open(
                    os.path.join(log_dir, f"backend-{name}.log"), "ab"
                )
            stderr = subprocess.STDOUT
        proc = subprocess.Popen(
            cmd, env=env, start_new_session=True,
            stdout=stdout, stderr=stderr,
        )
        return Backend(name, host, port, proc=proc, heartbeat_file=hb)

    spawn.ports = ports
    spawn.handles = handles  # the owner closes these at fleet exit
    return spawn


# ---------------------------------------------------------------------------
# The CLI fleet (python -m pytorch_mnist_ddp_tpu.serving --fleet N)

# Front-tier-only flags that must NOT reach a backend's command line
# (the backend is this same CLI, fleet-less, on its own port).
_FLEET_VALUE_FLAGS = {
    "--fleet", "--fleet-base-port", "--fleet-restart-budget",
    "--fleet-heartbeat-timeout-s", "--fleet-ready-timeout-s",
    "--scale-high", "--scale-low", "--scale-min", "--scale-max",
    "--scale-window-s", "--scale-cooldown-s",
    "--port", "--host", "--telemetry-dir", "--aot-cache",
}
_FLEET_BOOL_FLAGS = {"--autoscale"}


def backend_argv(argv: list[str]) -> list[str]:
    """Strip fleet-front flags (and per-backend-overridden ones: port,
    host, telemetry dir, AOT cache) from the CLI argv, so a backend
    re-executes the ORIGINAL serving configuration — the same
    zero-knowledge re-exec contract as the elastic launcher's
    ``strip_chaos_args``."""
    out: list[str] = []
    skip = False
    for arg in argv:
        if skip:
            skip = False
            continue
        if arg in _FLEET_VALUE_FLAGS:
            skip = True
            continue
        if arg in _FLEET_BOOL_FLAGS:
            continue
        if any(arg.startswith(flag + "=")
               for flag in _FLEET_VALUE_FLAGS | _FLEET_BOOL_FLAGS):
            continue
        out.append(arg)
    return out


def run_fleet(args, argv: list[str]) -> int:
    """The ``--fleet N`` entry point (serving/__main__.py delegates
    here BEFORE any jax import): spawn N backend serving processes,
    front them with the router + supervisor (+ autoscaler), serve."""
    import shutil
    import tempfile

    from ..obs.events import EventSink, NullSink

    sink = (
        EventSink(args.telemetry_dir, filename="events-fleet.jsonl")
        if args.telemetry_dir else NullSink()
    )
    if sink:
        print(f"fleet telemetry: {sink.path}")
    metrics = ServingMetrics()
    scratch: list[str] = []
    aot_cache = args.aot_cache
    if aot_cache is None:
        # The warm-replacement contract needs ONE store all backends
        # (and every replacement) share — without an operator-named dir,
        # a per-run scratch store still makes replacements pure
        # deserialize; only cross-RUN warmth needs --aot-cache.
        aot_cache = tempfile.mkdtemp(prefix="fleet-aot-")
        scratch.append(aot_cache)
    hb_dir = tempfile.mkdtemp(prefix="fleet-hb-")
    scratch.append(hb_dir)
    base_port = (
        args.fleet_base_port if args.fleet_base_port is not None
        else args.port + 1
    )
    spawn = subprocess_backend_spawner(
        backend_argv(argv) + ["--aot-cache", aot_cache],
        host=args.host, base_port=base_port, heartbeat_dir=hb_dir,
        log_dir=args.telemetry_dir,
    )
    logs = spawn.handles.values()
    fleet = Fleet(
        spawn, policy=args.router_policy, metrics=metrics, sink=sink,
        # The front's routing deadline: the backend's own --timeout-ms
        # budget plus slack, so a loaded backend answers its OWN 504
        # (the informative one) and the front's synthetic 504 is only
        # the backstop for a hung transport.
        default_timeout_s=args.timeout_ms / 1e3 + 2.0,
        # Two-tier caching: the flag also rides backend_argv (it is not
        # a front-only flag), so backends cache at their own admission
        # points while the front absorbs exact-repeat bodies here.
        response_cache=args.response_cache,
    )
    print(
        f"fleet: spawning {args.fleet} backend(s) on ports "
        f"{base_port}..{base_port + args.fleet - 1} "
        f"(shared AOT cache {aot_cache})"
    )
    try:
        fleet.start(
            args.fleet,
            wait_ready_s=args.fleet_ready_timeout_s,
            supervise=True,
            supervisor_kwargs=dict(
                restart_budget=args.fleet_restart_budget,
                heartbeat_timeout_s=args.fleet_heartbeat_timeout_s,
                ready_timeout_s=args.fleet_ready_timeout_s,
                seed=args.seed,
            ),
            autoscale=args.autoscale,
            autoscaler_kwargs=dict(
                high_water=args.scale_high,
                low_water=args.scale_low,
                min_backends=args.scale_min,
                max_backends=args.scale_max,
                window_s=args.scale_window_s,
                cooldown_s=args.scale_cooldown_s,
            ) if args.autoscale else None,
        )
    except Exception as e:
        print(f"fleet: bring-up failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        fleet.stop()
        sink.close()
        for f in logs:
            f.close()
        return 1
    server = make_fleet_server(
        fleet, host=args.host, port=args.port,
        request_timeout_s=args.request_timeout_s,
    )
    host, port = server.server_address[:2]
    print(
        f"fleet front on http://{host}:{port} (POST /predict, GET /metrics, "
        f"/healthz, /readyz; {args.fleet} backends, policy "
        f"{args.router_policy}, autoscale "
        + (f"on [{args.scale_low:g}..{args.scale_high:g} depth, "
           f"{args.scale_min}..{args.scale_max} backends]"
           if args.autoscale else "off")
        + ")"
    )

    def _shutdown(signum, frame):
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)
    try:
        server.serve_forever()
    finally:
        print("fleet: draining backends...")
        fleet.stop()
        server.server_close()
        print(metrics.report_lines())
        sink.close()
        for f in logs:
            f.close()
        for path in scratch:
            shutil.rmtree(path, ignore_errors=True)
    return 0
