"""Binary wire protocol: the host hot path's zero-copy request format.

The JSON ``/predict`` surface pays a host tax per request that has
nothing to do with the model: the client renders every pixel as decimal
text, the server re-parses ~784·n Python numbers back into floats, and
the response walks the same road in reverse.  At the rates the fleet
sweeps reach on small hosts, that encode/decode IS the bottleneck (the
PR-7/12 "host-bound" caveat).  This module is the flat alternative —
``Content-Type: application/x-mnist-f32`` — designed so the server's
entire parse is ONE ``np.frombuffer`` view (zero copy; the only copy a
binary request ever pays is the batcher's staging memcpy, which the
JSON path pays too), and the response is the raw float32 logits bytes.

Request layout (all integers little-endian)::

    offset  size  field        meaning
    0       4     magic        b"MNW1" (format + version in one tag)
    4       2     header_size  bytes before the payload (>= 24; a newer
                               writer may append fields — readers skip)
    6       2     flags        bit 0: rows are pre-normalized floats
                               (the JSON "normalized" field); other
                               bits reserved, must be zero
    8       4     count        number of rows (>= 1)
    12      4     row_elems    floats per row; must equal 784 (28x28)
    16      1     dtype        served variant: 0=f32, 1=bf16, 2=int8
                               (payload floats are ALWAYS f32; the code
                               picks the engine variant, like the JSON
                               "dtype" field)
    17      1     qos          0=server default, 1=interactive, 2=batch
    18      2     reserved     must be zero
    20      4     deadline_ms  per-request deadline override; 0 = the
                               server's --timeout-ms default
    24      ...   payload      count x row_elems float32, row-major

Registry extension (ISSUE 17): a request naming a registry ``model`` /
``version`` (the JSON body fields of the same names) appends, AFTER
offset 24 and BEFORE the payload::

    24      2     model_len    UTF-8 bytes of the model name (0 = unset)
    26      2     version_len  UTF-8 bytes of the version (0 = unset)
    28      ...   model name bytes, then version bytes

and sets ``header_size = 28 + model_len + version_len``.  Presence is
keyed on ``header_size > 24`` — NOT on a flag bit, because this decoder
(correctly) rejects unknown flag bits, while the versioning rule below
makes longer headers skippable: a pre-registry reader serves such a
request through its default route, exactly what absent fields mean.  A
request with neither field keeps ``header_size = 24`` — byte-identical
to the pre-registry wire.

Response layout (``application/x-mnist-logits-f32``)::

    offset  size  field        meaning
    0       4     magic        b"MNL1"
    4       2     header_size  >= 16
    6       2     flags        reserved, zero
    8       4     count        rows (== the request's count)
    12      4     classes      logits per row (10)
    16      ...   payload      count x classes float32 log-probs

Versioning/fallback rules (docs/SERVING.md): an unknown magic or a
header shorter than the fixed part is a malformed request (HTTP 400,
never a hang); a LONGER header from a future writer is read by
``header_size`` and the extra bytes are skipped; any ``/predict`` body
whose Content-Type is not this format parses as JSON — the default
protocol stays byte-identical, so old clients never notice this module
exists.

Pure stdlib + numpy, no jax import: the fleet front (serving/fleet.py)
must be able to speak the format without owning a device, and the
loadgen encodes requests client-side.
"""

from __future__ import annotations

import struct

import numpy as np

# The /predict content types (the header values on the wire).
WIRE_REQUEST_TYPE = "application/x-mnist-f32"
WIRE_RESPONSE_TYPE = "application/x-mnist-logits-f32"

REQUEST_MAGIC = b"MNW1"
RESPONSE_MAGIC = b"MNL1"

# magic, header_size, flags, count, row_elems, dtype, qos, reserved,
# deadline_ms — 24 bytes (see the module docstring's layout table).
_REQ_HEADER = struct.Struct("<4sHHIIBBHI")
# magic, header_size, flags, count, classes — 16 bytes.
_RESP_HEADER = struct.Struct("<4sHHII")
# model_len, version_len — the registry extension's length prefix at
# offset 24 (present iff header_size > 24; see the layout table).
_REQ_EXT = struct.Struct("<HH")

REQUEST_HEADER_SIZE = _REQ_HEADER.size
RESPONSE_HEADER_SIZE = _RESP_HEADER.size

FLAG_NORMALIZED = 0x1

ROW_ELEMS = 28 * 28

# Wire code <-> name tables.  Codes are append-only: reusing a retired
# code would silently re-route old clients' requests to a different
# variant/class.
DTYPE_CODES = {"f32": 0, "bf16": 1, "int8": 2}
DTYPE_NAMES = {code: name for name, code in DTYPE_CODES.items()}
QOS_CODES = {None: 0, "interactive": 1, "batch": 2}
QOS_NAMES = {code: name for name, code in QOS_CODES.items()}

# Row-count sanity bound: a header claiming 2**31 rows must fail on the
# header check, not on a gigabyte allocation attempt.  Generous vs any
# real bucket ladder (top default 128).
MAX_ROWS = 1 << 20


class WireError(ValueError):
    """Malformed binary request/response — HTTP 400 at the server, a
    client bug at the loadgen.  Subclasses ValueError so the server's
    existing 400 mapping handles it unchanged."""


class WireRequest:
    """One decoded binary request: a zero-copy float32 row view plus the
    sideband fields the JSON surface carries as body keys."""

    __slots__ = ("rows", "normalized", "dtype", "qos", "deadline_ms",
                 "model", "version")

    def __init__(self, rows, normalized, dtype, qos, deadline_ms,
                 model=None, version=None):
        self.rows = rows              # [n, 784] float32 view into the body
        self.normalized = normalized  # bool: skip the serving normalize
        self.dtype = dtype            # served variant name ("f32", ...)
        self.qos = qos                # scheduling class name or None
        self.deadline_ms = deadline_ms  # per-request override or None
        self.model = model            # registry model name or None
        self.version = version        # registry version or None

    @property
    def n(self) -> int:
        return len(self.rows)


def _rows_f32(x, elems: int, what: str) -> np.ndarray:
    """``x`` as a contiguous little-endian ``[n, elems]`` float32 block.

    Accepts the shapes the JSON surface accepts (flat rows, 28x28,
    28x28x1) so callers encode whatever they already hold; the copy
    (if any) happens HERE, once, at encode time — never per send."""
    x = np.asarray(x)
    if x.ndim >= 2 and int(np.prod(x.shape[1:])) == elems:
        x = x.reshape(len(x), elems)
    else:
        raise WireError(
            f"{what} must be [n, {elems}]-shaped rows (flat, 28x28, or "
            f"28x28x1); got array shape {x.shape}"
        )
    return np.ascontiguousarray(x, dtype="<f4")


def encode_request(
    rows,
    dtype: str = "f32",
    qos: str | None = None,
    normalized: bool = False,
    deadline_ms: float | None = None,
    model: str | None = None,
    version: str | None = None,
) -> bytes:
    """Rows + sideband fields -> one wire message (header ++ payload).
    ``model``/``version`` (registry routing, both optional) ride in the
    header extension; omitting both emits the pre-registry 24-byte
    header, bit for bit."""
    x = _rows_f32(rows, ROW_ELEMS, "request rows")
    if len(x) < 1:
        raise WireError("request must carry at least one row")
    if dtype not in DTYPE_CODES:
        raise WireError(
            f"unknown dtype {dtype!r}; wire codes exist for "
            f"{list(DTYPE_CODES)}"
        )
    if qos not in QOS_CODES:
        raise WireError(
            f"unknown qos {qos!r}; wire codes exist for "
            f"{[q for q in QOS_CODES if q is not None]}"
        )
    if deadline_ms is not None:
        # 0 on the wire means "no override" — a requested deadline must
        # never silently become one (sub-ms rounds UP to 1), and a
        # value past the u32 field is the caller's bug named here, not
        # a struct.error escaping the WireError contract.
        if not 0 < deadline_ms < 1 << 32:
            raise WireError(
                f"deadline_ms {deadline_ms!r} outside (0, 2**32) "
                "(omit it for the server default)"
            )
        deadline_field = max(1, int(deadline_ms))
    else:
        deadline_field = 0
    ext = b""
    if model is not None or version is not None:
        model_b = (model or "").encode("utf-8")
        version_b = (version or "").encode("utf-8")
        if max(len(model_b), len(version_b)) >= 1 << 16:
            raise WireError("model/version names exceed the u16 length field")
        ext = _REQ_EXT.pack(len(model_b), len(version_b)) + model_b + version_b
    header = _REQ_HEADER.pack(
        REQUEST_MAGIC,
        REQUEST_HEADER_SIZE + len(ext),
        FLAG_NORMALIZED if normalized else 0,
        len(x),
        ROW_ELEMS,
        DTYPE_CODES[dtype],
        QOS_CODES[qos],
        0,
        deadline_field,
    )
    return header + ext + x.tobytes()


def decode_request(body: bytes) -> WireRequest:
    """One wire message -> :class:`WireRequest`; the returned ``rows``
    are a read-only ``np.frombuffer`` VIEW into ``body`` — no float
    parsing, no copy (the staging memcpy downstream is the first and
    only one).  Raises :class:`WireError` on anything malformed or
    truncated; the message names the defect for the 400 body."""
    if len(body) < REQUEST_HEADER_SIZE:
        raise WireError(
            f"binary request of {len(body)} bytes is shorter than the "
            f"{REQUEST_HEADER_SIZE}-byte header"
        )
    (magic, header_size, flags, count, row_elems, dtype_code, qos_code,
     reserved, deadline_ms) = _REQ_HEADER.unpack_from(body)
    if magic != REQUEST_MAGIC:
        raise WireError(
            f"bad magic {magic!r}; expected {REQUEST_MAGIC!r} "
            "(wrong format or an incompatible future version)"
        )
    if header_size < REQUEST_HEADER_SIZE:
        raise WireError(
            f"header_size {header_size} is shorter than the fixed "
            f"{REQUEST_HEADER_SIZE}-byte layout"
        )
    if flags & ~FLAG_NORMALIZED:
        raise WireError(f"reserved flag bits set: 0x{flags:x}")
    if reserved:
        raise WireError(f"reserved header field set: 0x{reserved:x}")
    if row_elems != ROW_ELEMS:
        raise WireError(
            f"row_elems {row_elems} != {ROW_ELEMS} (28x28 pixels per row)"
        )
    if not 1 <= count <= MAX_ROWS:
        raise WireError(f"row count {count} outside [1, {MAX_ROWS}]")
    expected = header_size + 4 * count * row_elems
    if len(body) != expected:
        raise WireError(
            f"body is {len(body)} bytes; header promises {expected} "
            f"({count} rows x {row_elems} floats after a "
            f"{header_size}-byte header)"
        )
    dtype = DTYPE_NAMES.get(dtype_code)
    if dtype is None:
        raise WireError(
            f"unknown dtype code {dtype_code}; have {DTYPE_NAMES}"
        )
    if qos_code not in QOS_NAMES:
        raise WireError(f"unknown qos code {qos_code}; have {QOS_NAMES}")
    model = version = None
    if header_size > REQUEST_HEADER_SIZE:
        # Registry extension (or a future writer's longer header — the
        # lengths still lead, extra tail bytes are skipped).
        ext_end = REQUEST_HEADER_SIZE + _REQ_EXT.size
        if header_size < ext_end:
            raise WireError(
                f"extended header_size {header_size} is shorter than the "
                f"{ext_end}-byte model/version extension"
            )
        model_len, version_len = _REQ_EXT.unpack_from(
            body, REQUEST_HEADER_SIZE
        )
        if ext_end + model_len + version_len > header_size:
            raise WireError(
                f"model/version lengths ({model_len}, {version_len}) "
                f"overrun the {header_size}-byte header"
            )
        try:
            names = body[ext_end:ext_end + model_len + version_len]
            model = names[:model_len].decode("utf-8") or None
            version = names[model_len:].decode("utf-8") or None
        except UnicodeDecodeError as e:
            raise WireError(f"model/version names are not UTF-8: {e}")
    rows = np.frombuffer(
        body, dtype="<f4", count=count * row_elems, offset=header_size
    ).reshape(count, row_elems)
    return WireRequest(
        rows=rows,
        normalized=bool(flags & FLAG_NORMALIZED),
        dtype=dtype,
        qos=QOS_NAMES[qos_code],
        deadline_ms=float(deadline_ms) if deadline_ms else None,
        model=model,
        version=version,
    )


def to_model_input(req: WireRequest) -> np.ndarray:
    """Decoded rows -> model-ready ``[n, 28, 28, 1]`` float32 — the
    binary twin of :func:`~.server.decode_instances`, sharing its
    normalize so identical pixel values produce BIT-identical model
    inputs (and therefore identical cache keys) on either wire."""
    x = req.rows.reshape(req.n, 28, 28)
    if req.normalized:
        return x[..., None]
    from ..data.transforms import normalize

    return normalize(x)


def encode_response(logits) -> bytes:
    """``[n, classes]`` float32 log-probs -> raw response bytes."""
    x = np.ascontiguousarray(np.asarray(logits), dtype="<f4")
    if x.ndim != 2:
        raise WireError(f"logits must be [n, classes], got shape {x.shape}")
    header = _RESP_HEADER.pack(
        RESPONSE_MAGIC, RESPONSE_HEADER_SIZE, 0, x.shape[0], x.shape[1]
    )
    return header + x.tobytes()


def decode_response(body: bytes) -> np.ndarray:
    """Raw response bytes -> ``[n, classes]`` float32 logits view."""
    if len(body) < RESPONSE_HEADER_SIZE:
        raise WireError(
            f"binary response of {len(body)} bytes is shorter than the "
            f"{RESPONSE_HEADER_SIZE}-byte header"
        )
    magic, header_size, flags, count, classes = _RESP_HEADER.unpack_from(body)
    if magic != RESPONSE_MAGIC:
        raise WireError(f"bad response magic {magic!r}")
    if header_size < RESPONSE_HEADER_SIZE:
        raise WireError(f"response header_size {header_size} too short")
    if flags:
        raise WireError(f"reserved response flags set: 0x{flags:x}")
    expected = header_size + 4 * count * classes
    if len(body) != expected:
        raise WireError(
            f"response is {len(body)} bytes; header promises {expected}"
        )
    return np.frombuffer(
        body, dtype="<f4", count=count * classes, offset=header_size
    ).reshape(count, classes)
