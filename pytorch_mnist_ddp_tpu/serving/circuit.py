"""Per-target circuit breaker: closed → open → half-open → closed.

Factored out of serving/router.py (PR 8) so the serving FLEET's front
tier (serving/fleet.py) can wrap one around each network backend without
importing the in-process replica stack — the breaker itself is pure
host-side state (stdlib + the obs registry), and the fleet front must
stay importable without jax (it supervises the processes that own the
devices; it must keep working when they are the broken part).

The data-plane half of fault tolerance (the control-plane half is the
supervisor — serving/pool.py for replicas, serving/fleet.py for
backends): a target whose requests FAIL — launch errors, completion-read
errors, transport errors — must fall out of placement within a handful
of attempts, long before any polling supervisor notices, or every routed
request until then is a poisoned 500.

- **closed** — normal placement.  ``failure_threshold`` consecutive
  failures trip it open (any success resets the streak).
- **open** — the router never places here.  Only an explicit
  :meth:`half_open` (the supervisor, after a restart) re-admits.
- **half-open** — at most ``trial_limit`` concurrently outstanding
  *trial* requests are placed; ``trial_successes`` successes close
  the circuit, any failure re-opens it.

Transitions land on the ``serving_circuit_state{replica=}`` gauge and as
``circuit_transition`` events, so a breaker flapping is observable, not
folkloric.  Thread-safe: outcome feeders (completion workers, the fleet
proxy threads) record while placement threads check.
"""

from __future__ import annotations

from ..analysis.lockwatch import make_lock

# Circuit states, and the numeric encoding the serving_circuit_state
# gauge exports (docs/OBSERVABILITY.md): 0 = closed (healthy), 1 =
# half-open (trial traffic only), 2 = open (no placement).
CIRCUIT_CLOSED = "closed"
CIRCUIT_HALF_OPEN = "half-open"
CIRCUIT_OPEN = "open"
_CIRCUIT_GAUGE = {CIRCUIT_CLOSED: 0.0, CIRCUIT_HALF_OPEN: 1.0, CIRCUIT_OPEN: 2.0}


class CircuitBreaker:
    """One target's breaker; see the module docstring for the states.

    ``replica`` is the label on the gauge/event surfaces — historical
    name (PR 8 predates the fleet), and kept because the exported
    ``serving_circuit_state{replica=}`` family is a scraped contract.
    """

    def __init__(
        self,
        replica: str,
        failure_threshold: int = 3,
        trial_limit: int = 1,
        trial_successes: int = 1,
        registry=None,
        sink=None,
    ):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.replica = replica
        self.failure_threshold = failure_threshold
        self.trial_limit = max(1, trial_limit)
        self.trial_successes = max(1, trial_successes)
        self.state = CIRCUIT_CLOSED
        self.last_reason: str | None = None
        self._consecutive_failures = 0
        self._trial_inflight = 0
        self._trial_passed = 0
        self._lock = make_lock("circuit.breaker")
        self._sink = sink
        self._gauge = (
            registry.gauge(
                "serving_circuit_state",
                help="per-replica circuit breaker: 0 closed, 1 half-open "
                "(trial traffic only), 2 open (no placement)",
                replica=replica,
            )
            if registry is not None
            else None
        )
        if self._gauge is not None:
            self._gauge.set(0.0)

    def _transition(self, to: str, reason: str | None) -> None:
        """State change + gauge + event, under the lock."""
        src = self.state
        if src == to:
            return
        self.state = to
        self.last_reason = reason
        self._trial_inflight = 0
        self._trial_passed = 0
        if to == CIRCUIT_CLOSED:
            self._consecutive_failures = 0
        if self._gauge is not None:
            self._gauge.set(_CIRCUIT_GAUGE[to])
        if self._sink:
            self._sink.emit(
                "circuit_transition", replica=self.replica,
                src=src, dst=to, **({"reason": reason} if reason else {}),
            )

    # -- placement side -------------------------------------------------------

    def allows(self) -> bool:
        """Pure check (no token consumed): could this target be placed
        on right now?"""
        with self._lock:
            return self.state == CIRCUIT_CLOSED or (
                self.state == CIRCUIT_HALF_OPEN
                and self._trial_inflight < self.trial_limit
            )

    def try_acquire(self) -> bool:
        """Claim the right to place one request.  Free when closed;
        consumes a trial token when half-open; refused when open."""
        with self._lock:
            if self.state == CIRCUIT_CLOSED:
                return True
            if (self.state == CIRCUIT_HALF_OPEN
                    and self._trial_inflight < self.trial_limit):
                self._trial_inflight += 1
                return True
            return False

    def release(self) -> None:
        """Return an unused trial token (the submit itself was rejected
        before any work dispatched — not an outcome either way)."""
        with self._lock:
            if self._trial_inflight > 0:
                self._trial_inflight -= 1

    # -- outcome side ---------------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self.state == CIRCUIT_HALF_OPEN:
                if self._trial_inflight > 0:
                    self._trial_inflight -= 1
                self._trial_passed += 1
                if self._trial_passed >= self.trial_successes:
                    self._transition(CIRCUIT_CLOSED, "trial_passed")

    def record_failure(self) -> None:
        with self._lock:
            if self.state == CIRCUIT_HALF_OPEN:
                self._transition(CIRCUIT_OPEN, "trial_failed")
                return
            self._consecutive_failures += 1
            if (self.state == CIRCUIT_CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._transition(CIRCUIT_OPEN, "failure_threshold")

    # -- supervisor side ------------------------------------------------------

    def force_open(self, reason: str = "quarantined") -> None:
        with self._lock:
            self._transition(CIRCUIT_OPEN, reason)

    def half_open(self) -> None:
        """Admit trial traffic after a restart (supervisor only — an
        open circuit never self-heals by clock, because the thing that
        tripped it has not been fixed by time passing)."""
        with self._lock:
            self._transition(CIRCUIT_HALF_OPEN, "restart_trial")
