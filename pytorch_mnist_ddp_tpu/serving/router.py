"""Queue-aware admission router over a pool of serving replicas.

The single-engine stack tops out at one device: one dispatch worker, one
in-flight window, one queue (serving/batcher.py).  Scale-out runs one
full PR-4 pipeline — engine + micro-batcher — per device and puts this
router in front as the shared admission surface: HTTP handlers (or any
caller) ``submit()`` here, and the router places each request onto a
replica whose batcher then coalesces it with same-replica neighbors.

Placement policies (the ``--router-policy`` A/B switch):

- **roundrobin** — rotate over active replicas; the baseline that
  ignores load entirely.
- **least-loaded** — smallest ``queue depth + in-flight batches``; the
  live PR-3/4 gauges are exactly the load signal.
- **cost** (default) — expected time-to-answer: ``(load + 1) x EWMA
  request latency`` per replica, where the EWMA is fed by each
  batcher's completion worker (``on_complete`` hook).  A replica that
  has gone slow (thermals, a noisy neighbor, a bigger device queue than
  the gauges show) decays out of rotation even at equal queue depths.
  Until a replica has a latency sample the score falls back to
  least-loaded — the fallback the policy name promises.

Every decision lands on ``serving_router_decisions_total{policy=,
replica=}`` and (with a sink) as ``router_decision`` events, so the A/B
is observable per placement, not just in aggregate.

**Sharded dispatch.**  A request bigger than one replica's maximal
batch — which a lone MicroBatcher rejects outright — is split into
top-bucket-sized chunks placed independently (data-parallel over the
pool, the multi-replica analogue of ``ddp.make_predict_step``'s
data-axis sharding), and the returned :class:`ShardedRequest`
reassembles chunk results in arrival order.  The cap becomes
``active replicas x max_batch``.

**Elasticity.**  :meth:`drain` removes a replica under live traffic:
mark it unroutable FIRST, then run its batcher's PR-4 ``stop(drain=
True)`` — everything already admitted or launched completes, nothing is
dropped, torn, or duplicated, and the only externally visible change is
capacity.  A submit that raced onto the draining replica either drains
with it or is flushed with ``RejectedError`` at ``result()`` time — the
HTTP handler resubmits such a never-executed request once, so the retry
lands on a surviving replica (serving/server.py).
:meth:`attach` re-adds a replica (a fresh batcher around a still-warm
engine — the pool's ``add``).  Drain wall time is the
``serving_replica_drain_seconds`` histogram + ``replica_drain`` events.

Pure host-side stdlib + numpy (no jax import): policies, sharding, and
drain ordering are all testable against fake engines at interactive
speed (tests/test_scaleout.py), exactly like the batcher.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from .batcher import MicroBatcher, PendingRequest, RejectedError

POLICIES = ("roundrobin", "least-loaded", "cost")

# EWMA smoothing for per-replica request latency: ~5 requests of memory,
# fast enough to notice a replica going slow, smooth enough not to
# thrash on one outlier.
EWMA_ALPHA = 0.2


class Replica:
    """One routable replica: a name, its (started) batcher, optionally
    the engine behind it, and the router-side load state.

    The object is persistent across drain/re-add cycles — the router
    holds it forever and :meth:`reactivate` swaps in a fresh batcher —
    so membership changes never race list mutation in the hot path.
    """

    def __init__(self, name: str, batcher: MicroBatcher, engine=None):
        self.name = name
        self.batcher = batcher
        self.engine = engine
        self.state = "active"  # active | draining | drained
        self._ewma_s: float | None = None

    # -- load signals --------------------------------------------------------

    def observe_latency(self, latency_s: float) -> None:
        """Completion-worker hook (MicroBatcher ``on_complete``): feed
        the per-replica EWMA the cost policy scores with."""
        prev = self._ewma_s
        self._ewma_s = (
            latency_s if prev is None
            else EWMA_ALPHA * latency_s + (1.0 - EWMA_ALPHA) * prev
        )

    @property
    def ewma_latency_s(self) -> float | None:
        return self._ewma_s

    def load(self) -> int:
        """Queue depth + in-flight batches — the live backlog."""
        return self.batcher.depth() + self.batcher.inflight()

    # -- membership ----------------------------------------------------------

    @property
    def active(self) -> bool:
        return self.state == "active"

    def reactivate(self, batcher: MicroBatcher) -> None:
        if self.state != "drained":
            raise RuntimeError(
                f"replica {self.name!r} is {self.state}, not drained; "
                "drain it before attaching a new batcher"
            )
        self.batcher = batcher
        self._ewma_s = None  # stale latency must not bias placement
        self.state = "active"


class ShardedRequest:
    """N chunk requests posing as one: data-parallel sharded dispatch.

    ``result()`` concatenates chunk results in submit (= arrival) order,
    so the caller sees exactly the rows it sent, reassembled.  Any chunk
    error propagates as the request's error (remaining chunks still
    complete on their replicas; device work is never torn mid-batch).
    """

    def __init__(self, parts: list[PendingRequest]):
        self._parts = parts
        self._value: np.ndarray | None = None

    @property
    def n(self) -> int:
        return sum(p.n for p in self._parts)

    def result(self, grace_s: float = 1.0) -> np.ndarray:
        if self._value is None:
            self._value = np.concatenate(
                [p.result(grace_s) for p in self._parts]
            )
        return self._value


class Router:
    """Shared admission front: place requests over replica batchers.

    ``submit()`` mirrors the MicroBatcher surface (the HTTP handlers and
    the loadgen cannot tell a router from a batcher), plus the
    aggregate ``depth``/``inflight`` reads the server's ``/metrics``
    snapshot uses.  Thread-safe: any number of handler threads submit
    concurrently; membership changes (:meth:`drain`/:meth:`attach`)
    take the same lock as placement ordering.
    """

    def __init__(
        self,
        replicas: list[Replica],
        policy: str = "cost",
        registry=None,
        sink=None,
        metrics=None,
    ):
        if policy not in POLICIES:
            raise ValueError(f"unknown router policy {policy!r}; have {POLICIES}")
        if not replicas:
            raise ValueError("router needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.policy = policy
        self.replicas = list(replicas)
        self.metrics = metrics
        self._registry = registry
        self._sink = sink
        self._lock = threading.Lock()
        self._rr = 0
        self._drain_hist = (
            registry.histogram(
                "serving_replica_drain_seconds",
                help="wall time of a graceful replica drain (queue + "
                "in-flight window finished, nothing dropped)",
            )
            if registry is not None
            else None
        )

    # -- membership / aggregate reads ----------------------------------------

    def active(self) -> list[Replica]:
        with self._lock:
            return [r for r in self.replicas if r.active]

    def replica(self, name: str) -> Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"no replica named {name!r}")

    def depth(self) -> int:
        """Summed admission-queue depth over ALL replicas — a draining
        replica's queued work still occupies its device and must not
        read as load that vanished (drained batchers report 0)."""
        return sum(r.batcher.depth() for r in self.replicas)

    def inflight(self) -> int:
        """Summed launched-not-yet-read batches over ALL replicas (see
        :meth:`depth` — draining work is still live work)."""
        return sum(r.batcher.inflight() for r in self.replicas)

    @property
    def max_inflight(self) -> int:
        return sum(r.batcher.max_inflight for r in self.active())

    @property
    def timeout_s(self) -> float:
        """The pool's default per-request deadline (min over replicas)
        — lets the handler's drain-race retry pass the REMAINING budget
        instead of granting the resubmission a fresh full deadline."""
        return min(r.batcher.timeout_s for r in self.replicas)

    @property
    def current_linger_ms(self) -> float:
        lingers = [r.batcher.current_linger_ms for r in self.active()]
        return sum(lingers) / len(lingers) if lingers else 0.0

    def replica_stats(self) -> dict[str, dict]:
        """Per-replica live state: the ``/metrics`` ``replicas`` block."""
        return {
            r.name: {
                "state": r.state,
                "queue_depth": r.batcher.depth(),
                "inflight": r.batcher.inflight(),
                "ewma_latency_ms": (
                    1e3 * r.ewma_latency_s
                    if r.ewma_latency_s is not None else None
                ),
            }
            for r in self.replicas
        }

    # -- placement ------------------------------------------------------------

    def _order(self, active: list[Replica]) -> list[Replica]:
        """Active replicas, best placement first, under the lock."""
        with self._lock:
            rotation = self._rr
            self._rr += 1
        if self.policy == "roundrobin":
            k = rotation % len(active)
            return active[k:] + active[:k]
        if self.policy == "least-loaded":
            key = lambda r: r.load()  # noqa: E731 - local sort key
        else:
            # cost: expected time-to-answer = (backlog + this request) x
            # EWMA latency.  A replica without samples yet (fresh, or
            # just re-added) scores with the pool-mean EWMA as its prior
            # — NOT last place, which would starve it of the very
            # traffic that builds its estimate; with no samples anywhere
            # the policy degrades to least-loaded (the documented
            # fallback).
            ewmas = [
                r.ewma_latency_s for r in active
                if r.ewma_latency_s is not None
            ]
            if not ewmas:
                key = lambda r: r.load()  # noqa: E731 - local sort key
            else:
                prior = sum(ewmas) / len(ewmas)

                def key(r: Replica):
                    ewma = r.ewma_latency_s
                    return (r.load() + 1) * (prior if ewma is None else ewma)
        # Rotate before the stable sort so exact ties spread over
        # replicas instead of always landing on the first name.
        k = rotation % len(active)
        return sorted(active[k:] + active[:k], key=key)

    def _note(self, replica: Replica, rows: int) -> None:
        if self._registry is not None:
            self._registry.counter(
                "serving_router_decisions_total",
                help="request placements by policy and chosen replica",
                policy=self.policy,
                replica=replica.name,
            ).inc()
        if self._sink:
            self._sink.emit(
                "router_decision", policy=self.policy,
                replica=replica.name, rows=rows,
            )

    def submit(
        self,
        x: np.ndarray,
        timeout_ms: float | None = None,
        dtype: str | None = None,
    ) -> PendingRequest | ShardedRequest:
        """Place one request (or its shards) onto the pool.

        Tries replicas in policy order: a replica that rejects (queue
        full, or a drain racing this submit) is transparently skipped —
        only when EVERY active replica refuses does the caller see the
        503.  Per-attempt rejections are not double-counted on the
        metrics surface (only the final, client-visible one is).
        """
        active = self.active()
        if not active:
            if self.metrics is not None:
                self.metrics.record_rejected()
            raise RejectedError("no active replicas")
        x = np.asarray(x, np.float32)
        cap = min(r.batcher.max_batch for r in active)
        if len(x) > cap:
            return self._submit_sharded(x, active, cap, timeout_ms, dtype)
        return self._place(x, active, timeout_ms, dtype)

    def _place(self, x, active, timeout_ms, dtype) -> PendingRequest:
        # ``active`` is the submit-time snapshot (one lock round-trip
        # per request, shared across a sharded request's chunks).  A
        # replica drained after the snapshot rejects at its batcher and
        # is skipped like any other refusal.
        order = self._order(active)
        last = order[-1]
        for r in order:
            try:
                req = r.batcher.submit(
                    x, timeout_ms=timeout_ms, dtype=dtype,
                    count_reject=r is last,
                )
            except RejectedError:
                if r is last:
                    raise
                continue
            self._note(r, len(x))
            return req
        raise RejectedError("no active replicas")  # unreachable: order != []

    def _submit_sharded(self, x, active, cap, timeout_ms, dtype) -> ShardedRequest:
        """Chunks are placed sequentially; a rejection mid-placement
        (every replica full) propagates to the client as one 503, while
        chunks already admitted drain normally on their replicas — their
        finished device work is discarded, exactly as for a client that
        disconnects mid-request.  The client-visible contract stays
        atomic: one request, one answer or one error, never a partial
        result."""
        if len(x) > cap * len(active):
            if self.metrics is not None:
                self.metrics.record_rejected()
            raise RejectedError(
                f"request of {len(x)} samples exceeds pool capacity "
                f"({len(active)} replicas x {cap} max batch)"
            )
        # Near-equal chunks preserve arrival order (chunk i = rows
        # [offsets[i], offsets[i+1])) and spread the work instead of
        # filling replica 1 and sending replica 2 the remainder.
        n_chunks = -(-len(x) // cap)
        bounds = np.linspace(0, len(x), n_chunks + 1).astype(int)
        parts = [
            self._place(x[lo:hi], active, timeout_ms, dtype)
            for lo, hi in zip(bounds[:-1], bounds[1:])
        ]
        return ShardedRequest(parts)

    # -- elasticity ------------------------------------------------------------

    def drain(self, name: str) -> float:
        """Gracefully remove one replica under live traffic.

        Ordering is the correctness: the replica is marked unroutable
        BEFORE its batcher drains, so no new placement can land on it
        mid-drain; ``stop(drain=True)`` then finishes its queue and
        in-flight window (the PR-4 guarantee — nothing lost, nothing
        duplicated).  Returns (and records) the drain wall seconds.
        """
        replica = self.replica(name)
        with self._lock:
            if not replica.active:
                raise RuntimeError(
                    f"replica {name!r} is {replica.state}, not active"
                )
            if sum(1 for r in self.replicas if r.active) == 1:
                raise RuntimeError(
                    f"refusing to drain {name!r}: it is the last active "
                    "replica (stop the server instead)"
                )
            replica.state = "draining"
        t0 = time.perf_counter()
        replica.batcher.stop(drain=True)
        duration = time.perf_counter() - t0
        replica.state = "drained"
        if self._drain_hist is not None:
            self._drain_hist.observe(duration)
        if self._sink:
            self._sink.emit(
                "replica_drain", replica=name, duration_s=duration
            )
        return duration

    def attach(self, name: str, batcher: MicroBatcher) -> Replica:
        """Re-add a drained replica with a fresh (started) batcher, or
        register a brand-new one.  Routable as soon as this returns."""
        with self._lock:
            for r in self.replicas:
                if r.name == name:
                    r.reactivate(batcher)
                    return r
            replica = Replica(name, batcher)
            self.replicas.append(replica)
            return replica

    # -- lifecycle -------------------------------------------------------------

    def stop(self, drain: bool = True) -> None:
        """Stop every active replica's batcher (draining by default).
        Replicas already drained are left alone.  Drains run
        concurrently — each replica's queue/window finishes on its own
        device, so shutdown wall time is the slowest drain, not the
        sum of all of them."""
        stopping = [r for r in self.replicas if r.state != "drained"]
        for r in stopping:
            r.state = "draining"
        if not stopping:
            return

        def _stop(r: Replica) -> None:
            r.batcher.stop(drain=drain)
            r.state = "drained"

        with ThreadPoolExecutor(max_workers=len(stopping)) as pool:
            list(pool.map(_stop, stopping))
