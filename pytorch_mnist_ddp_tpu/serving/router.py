"""Queue-aware admission router over a pool of serving replicas.

The single-engine stack tops out at one device: one dispatch worker, one
in-flight window, one queue (serving/batcher.py).  Scale-out runs one
full PR-4 pipeline — engine + micro-batcher — per device and puts this
router in front as the shared admission surface: HTTP handlers (or any
caller) ``submit()`` here, and the router places each request onto a
replica whose batcher then coalesces it with same-replica neighbors.

Placement policies (the ``--router-policy`` A/B switch):

- **roundrobin** — rotate over active replicas; the baseline that
  ignores load entirely.
- **least-loaded** — smallest ``queue depth + in-flight batches``; the
  live PR-3/4 gauges are exactly the load signal.
- **cost** (default) — expected time-to-answer: ``(load + 1) x EWMA
  request latency`` per replica, where the EWMA is fed by each
  batcher's completion worker (``on_complete`` hook).  A replica that
  has gone slow (thermals, a noisy neighbor, a bigger device queue than
  the gauges show) decays out of rotation even at equal queue depths.
  Until a replica has a latency sample the score falls back to
  least-loaded — the fallback the policy name promises.

Every decision lands on ``serving_router_decisions_total{policy=,
replica=}`` and (with a sink) as ``router_decision`` events, so the A/B
is observable per placement, not just in aggregate.

**Sharded dispatch.**  A request bigger than one replica's maximal
batch — which a lone MicroBatcher rejects outright — is split into
top-bucket-sized chunks placed independently (data-parallel over the
pool, the multi-replica analogue of ``ddp.make_predict_step``'s
data-axis sharding), and the returned :class:`ShardedRequest`
reassembles chunk results in arrival order.  The cap becomes
``active replicas x max_batch``.

**Elasticity.**  :meth:`drain` removes a replica under live traffic:
mark it unroutable FIRST, then run its batcher's PR-4 ``stop(drain=
True)`` — everything already admitted or launched completes, nothing is
dropped, torn, or duplicated, and the only externally visible change is
capacity.  A submit that raced onto the draining replica either drains
with it or is flushed with ``RejectedError`` at ``result()`` time — the
HTTP handler resubmits such a never-executed request once, so the retry
lands on a surviving replica (serving/server.py).
:meth:`attach` re-adds a replica (a fresh batcher around a still-warm
engine — the pool's ``add``).  Drain wall time is the
``serving_replica_drain_seconds`` histogram + ``replica_drain`` events.

**Fault tolerance** (docs/ROBUSTNESS.md).  Every replica carries a
:class:`CircuitBreaker`: consecutive batch failures trip it open and
placement stops selecting the replica within a handful of requests —
no polling latency in the data plane.  :meth:`quarantine` is the
supervisor's hard removal (abort, not drain: a dead replica cannot
finish its window), and after a restart the breaker goes **half-open**,
admitting trial requests until one closes it.  Requests flushed off a
dead replica surface as ``ReplicaDeadError`` (a ``RejectedError``), so
the HTTP handler's existing drain-race retry resubmits them on
survivors with the REMAINING deadline budget — exactly one
client-visible outcome per request, counted once
(``serving_request_retries_total`` tallies the transparent retries).

Pure host-side stdlib + numpy (no jax import): policies, sharding, and
drain ordering are all testable against fake engines at interactive
speed (tests/test_scaleout.py), exactly like the batcher.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..analysis.lockwatch import make_lock
from .batcher import MicroBatcher, PendingRequest, RejectedError
from .circuit import (  # noqa: F401 - canonical home since the fleet tier; re-exported
    CIRCUIT_CLOSED,
    CIRCUIT_HALF_OPEN,
    CIRCUIT_OPEN,
    CircuitBreaker,
)
from .qos import DEFAULT_QOS

POLICIES = ("roundrobin", "least-loaded", "cost")

# EWMA smoothing for per-replica request latency: ~5 requests of memory,
# fast enough to notice a replica going slow, smooth enough not to
# thrash on one outlier.
EWMA_ALPHA = 0.2


def shape_class(rows: int | None) -> str:
    """Pow2-ceiling shape-class label (``"b8"`` holds 5..8 rows).

    The cost policy's latency samples are bucketed the way the engine
    pads, so one class aggregates requests that cost the SAME device
    work — in a heterogeneous pool (a 4-device TP replica beside
    1-device DP replicas) a replica's big-batch speedup must not be
    credited to its small-batch requests, or vice versa."""
    if not rows or rows < 1:
        return "b1"
    b = 1
    while b < rows:
        b *= 2
    return f"b{b}"


class Replica:
    """One routable replica: a name, its (started) batcher, optionally
    the engine behind it, and the router-side load state.

    The object is persistent across drain/re-add cycles — the router
    holds it forever and :meth:`reactivate` swaps in a fresh batcher —
    so membership changes never race list mutation in the hot path.
    """

    def __init__(self, name: str, batcher: MicroBatcher, engine=None):
        self.name = name
        self.batcher = batcher
        self.engine = engine
        # active | draining | drained | quarantined | restarting | ejected
        # (the last three are supervisor-owned, serving/pool.py).
        self.state = "active"
        # Assigned by the Router (it owns registry + sink); standalone
        # Replica objects in tests stay breaker-less and unrestricted.
        self.breaker: CircuitBreaker | None = None
        self._ewma_s: float | None = None
        # Per-shape-class EWMAs (cost policy): {"b8": seconds, ...}.
        self._class_ewma_s: dict[str, float] = {}

    # -- load signals --------------------------------------------------------

    def observe_latency(self, latency_s: float, rows: int | None = None) -> None:
        """Completion-worker hook (MicroBatcher ``on_complete``): feed
        the per-replica EWMAs the cost policy scores with, and count the
        success toward the circuit breaker.  ``rows`` (the completed
        request's row count) additionally lands the sample on its
        shape class, so a heterogeneous replica's per-shape profile —
        a TP replica that is fast at b64 but ordinary at b1 — is scored
        per class, not smeared into one number.  ``rows=None`` (legacy
        callers) keeps only the global EWMA."""
        prev = self._ewma_s
        self._ewma_s = (
            latency_s if prev is None
            else EWMA_ALPHA * latency_s + (1.0 - EWMA_ALPHA) * prev
        )
        if rows is not None:
            cls = shape_class(rows)
            prev_c = self._class_ewma_s.get(cls)
            self._class_ewma_s[cls] = (
                latency_s if prev_c is None
                else EWMA_ALPHA * latency_s + (1.0 - EWMA_ALPHA) * prev_c
            )
        if self.breaker is not None:
            self.breaker.record_success()

    def class_latency_s(self, cls: str) -> float | None:
        """This replica's EWMA latency for one shape class (None until
        a request of that class completes here)."""
        return self._class_ewma_s.get(cls)

    def observe_failure(self, count: int = 1) -> None:
        """Worker failure hook (MicroBatcher ``on_failure``): one failed
        BATCH is one breaker strike regardless of how many requests rode
        it — the breaker measures replica health, not blast radius."""
        if self.breaker is not None:
            self.breaker.record_failure()

    def observe_expiry(self, count: int = 1) -> None:
        """Queue-expiry hook (MicroBatcher ``on_expire``): a request
        that timed out before dispatch is no verdict on the replica, but
        any half-open trial token it held must come back — otherwise the
        breaker stays half-open forever with its whole trial quota
        leaked to requests that never ran."""
        if self.breaker is not None:
            for _ in range(count):
                self.breaker.release()

    @property
    def ewma_latency_s(self) -> float | None:
        return self._ewma_s

    def load(self) -> int:
        """Queue depth + in-flight batches — the live backlog."""
        return self.batcher.depth() + self.batcher.inflight()

    # -- membership ----------------------------------------------------------

    @property
    def active(self) -> bool:
        return self.state == "active"

    def reactivate(self, batcher: MicroBatcher) -> None:
        # "restarting" is the supervisor's restart path (serving/pool.py)
        # — same fresh-batcher-around-a-warm-engine move as a re-add.
        if self.state not in ("drained", "restarting"):
            raise RuntimeError(
                f"replica {self.name!r} is {self.state}, not drained; "
                "drain it before attaching a new batcher"
            )
        self.batcher = batcher
        self._ewma_s = None  # stale latency must not bias placement
        self._class_ewma_s = {}
        self.state = "active"


class ShardedRequest:
    """N chunk requests posing as one: data-parallel sharded dispatch.

    ``result()`` concatenates chunk results in submit (= arrival) order,
    so the caller sees exactly the rows it sent, reassembled.  Any chunk
    error propagates as the request's error (remaining chunks still
    complete on their replicas; device work is never torn mid-batch).
    """

    def __init__(self, parts: list[PendingRequest]):
        self._parts = parts
        self._value: np.ndarray | None = None

    @property
    def n(self) -> int:
        return sum(p.n for p in self._parts)

    def result(self, grace_s: float = 1.0) -> np.ndarray:
        if self._value is None:
            self._value = np.concatenate(
                [p.result(grace_s) for p in self._parts]
            )
        return self._value


class _HedgeEntry:
    """One tracked request awaiting its hedge decision."""

    __slots__ = ("req", "origin", "due_t", "placed", "attempted")

    def __init__(self, req: PendingRequest, origin: str, due_t: float):
        self.req = req
        self.origin = origin
        self.due_t = due_t
        self.placed: str | None = None   # hedge replica once dispatched
        self.attempted = False           # a due hedge tried to place


class HedgeManager:
    """Hedged dispatch: re-submit the straggler request to a SECOND
    replica after a tail-derived delay; first completion wins.

    The tail-latency move (docs/SERVING.md): once a request has waited
    past its class's p99, the most likely explanation is that its
    replica is having a bad time (deep batch, slow device, noisy
    neighbor) — a copy on a healthy replica usually answers first, at
    the cost of ~1% duplicated work.  Safety comes from primitives that
    already exist: the hedge enqueues the SAME :class:`~.batcher
    .PendingRequest` (``MicroBatcher.submit_hedge``), so the PR-8
    first-wins lock guarantees exactly one client-visible outcome, and
    the batcher's win-gated accounting keeps the loser's completion off
    the metrics and breaker surfaces — a hedge can never double-count
    (tests/test_tail.py pins it).

    Delay: ``delay_ms`` fixed, or — when None — the request class's
    ONLINE p99 from the per-QoS latency digest
    (``ServingMetrics.qos_p99_s``); no hedging until the digest has
    ``min_samples`` observations, so a cold start never hedges on noise.

    Placement: least-loaded active replica other than the origin, with
    a CLOSED breaker only — half-open circuits carry supervised trial
    traffic, and a hedge must neither consume a trial token it cannot
    return nor evict real work (``submit_hedge`` never sheds).

    Outcomes land on ``serving_hedges_total{outcome=}`` +
    ``hedge_dispatch``/``hedge_outcome`` events: **won** (the hedge's
    completion was the client-visible one), **lost** (the primary
    answered first; the duplicate was discarded by first-wins),
    **cancelled** (a due hedge was abandoned — target queues full, no
    eligible replica, or the request settled/expired before a decisive
    dispatch).  Requests that complete before their delay elapses are
    simply untracked: they were never hedges.
    """

    def __init__(
        self,
        router: "Router",
        delay_ms: float | None = None,
        poll_s: float = 0.005,
        min_samples: int = 20,
        digest_refresh_s: float = 0.25,
    ):
        self.router = router
        self.delay_ms = delay_ms
        self.poll_s = poll_s
        self.min_samples = min_samples
        self.digest_refresh_s = digest_refresh_s
        self._entries: list[_HedgeEntry] = []
        self._lock = make_lock("router.hedge")
        self._p99: dict[str, tuple[float, float | None]] = {}  # qos -> (t, p99)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if router.metrics is not None:
            # The outcome family must be scrapeable before the first
            # hedge fires (CI greps a short smoke's exposition).
            router.metrics.ensure_hedges()

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "HedgeManager":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="serve-hedger", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    # -- tracking (router submit path) ----------------------------------------

    def _delay_s(self, qos: str, now: float) -> float | None:
        if self.delay_ms is not None:
            return self.delay_ms / 1e3
        cached = self._p99.get(qos)
        if cached is None or now - cached[0] > self.digest_refresh_s:
            metrics = self.router.metrics
            p99 = (
                metrics.qos_p99_s(qos, min_samples=self.min_samples)
                if metrics is not None else None
            )
            self._p99[qos] = cached = (now, p99)
        return cached[1]

    def track(self, req: PendingRequest, origin: str) -> None:
        now = time.perf_counter()
        delay = self._delay_s(getattr(req, "qos", DEFAULT_QOS), now)
        if delay is None:
            return  # digest still cold: no hedging on noise
        due = now + delay
        if due >= req.deadline:
            return  # the hedge could never answer inside the deadline
        with self._lock:
            self._entries.append(_HedgeEntry(req, origin, due))

    # -- the decision loop ----------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.tick()
            except Exception:
                # One bad tick (a replica torn down mid-inspection) must
                # not end hedging for the life of the process.
                pass
        # Shutdown: resolve what we can so counters don't dangle —
        # resolve ONLY: dispatching a new hedge here would land it on a
        # batcher about to drain (the reason Router.stop stops the
        # hedger first) with nobody left to resolve its outcome.
        try:
            self.tick(dispatch=False)
        except Exception:
            pass  # a replica torn down concurrently with shutdown

    def tick(self, now: float | None = None, dispatch: bool = True) -> None:
        """One inspection pass (public so tests step deterministically).
        ``dispatch=False`` resolves settled entries without placing new
        hedges (the shutdown pass)."""
        now = now if now is not None else time.perf_counter()
        with self._lock:
            entries = list(self._entries)
        done: set[_HedgeEntry] = set()
        for entry in entries:
            if entry.req.done():
                if entry.placed is not None:
                    # completed_by is set only by a WINNING completion
                    # worker: the hedge replica -> won; another replica
                    # (the primary) -> lost; None -> the outcome was an
                    # error with no replica behind it (expiry, flush) —
                    # nobody's dispatch was decisive, so it counts as
                    # cancelled, not as a primary win ("lost" would
                    # deflate the reported win rate with every 504).
                    by = entry.req.completed_by
                    self._resolve(
                        entry,
                        "won" if by == entry.placed
                        else ("lost" if by is not None else "cancelled"),
                    )
                elif entry.attempted:
                    self._resolve(entry, "cancelled")
                done.add(entry)
            elif entry.req.expired(now):
                if entry.placed is not None or entry.attempted:
                    self._resolve(entry, "cancelled")
                done.add(entry)
            elif (dispatch and entry.placed is None
                    and now >= entry.due_t):
                entry.attempted = True
                self._dispatch_hedge(entry)
        if done:
            with self._lock:
                self._entries = [
                    e for e in self._entries if e not in done
                ]

    def _dispatch_hedge(self, entry: _HedgeEntry) -> None:
        req = entry.req
        candidates = [
            r for r in self.router.active()
            if r.name != entry.origin
            and (r.breaker is None or r.breaker.state == CIRCUIT_CLOSED)
        ]
        candidates.sort(key=lambda r: r.load())
        for r in candidates:
            if not hasattr(r.batcher, "submit_hedge"):
                continue  # a fake/legacy batcher without the surface
            try:
                r.batcher.submit_hedge(req)
            except RejectedError:
                # Full queue / draining: try the next candidate this
                # tick, the rest next tick.  (Deliberately NOT catching
                # AttributeError here — a bug inside submit_hedge must
                # stay loud, not read as "replica declined".)
                continue
            entry.placed = r.name
            if self.router._registry is not None:
                self.router._registry.counter(
                    "serving_hedge_dispatches_total",
                    help="hedge re-dispatches placed, by target replica",
                    replica=r.name,
                ).inc()
            if self.router._sink:
                self.router._sink.emit(
                    "hedge_dispatch", origin=entry.origin, replica=r.name,
                    qos=getattr(req, "qos", DEFAULT_QOS),
                    waited_ms=1e3 * (time.perf_counter() - req.t_submit),
                )
            return

    def _resolve(self, entry: _HedgeEntry, outcome: str) -> None:
        if self.router.metrics is not None:
            self.router.metrics.record_hedge(outcome)
        if self.router._sink:
            self.router._sink.emit(
                "hedge_outcome", outcome=outcome, origin=entry.origin,
                **({"replica": entry.placed} if entry.placed else {}),
                qos=getattr(entry.req, "qos", DEFAULT_QOS),
            )

    def pending(self) -> int:
        with self._lock:
            return len(self._entries)


class Router:
    """Shared admission front: place requests over replica batchers.

    ``submit()`` mirrors the MicroBatcher surface (the HTTP handlers and
    the loadgen cannot tell a router from a batcher), plus the
    aggregate ``depth``/``inflight`` reads the server's ``/metrics``
    snapshot uses.  Thread-safe: any number of handler threads submit
    concurrently; membership changes (:meth:`drain`/:meth:`attach`)
    take the same lock as placement ordering.
    """

    def __init__(
        self,
        replicas: list[Replica],
        policy: str = "cost",
        registry=None,
        sink=None,
        metrics=None,
        failure_threshold: int = 3,
        trial_limit: int = 1,
        trial_successes: int = 1,
        hedge: bool = False,
        hedge_delay_ms: float | None = None,
        hedge_poll_s: float = 0.005,
    ):
        if policy not in POLICIES:
            raise ValueError(f"unknown router policy {policy!r}; have {POLICIES}")
        if not replicas:
            raise ValueError("router needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.policy = policy
        self.replicas = list(replicas)
        self.metrics = metrics
        self._registry = registry
        self._sink = sink
        self._lock = make_lock("router.replicas")
        self._rr = 0
        self._breaker_kwargs = dict(
            failure_threshold=failure_threshold,
            trial_limit=trial_limit,
            trial_successes=trial_successes,
        )
        for r in self.replicas:
            r.breaker = CircuitBreaker(
                r.name, registry=registry, sink=sink, **self._breaker_kwargs
            )
        self._drain_hist = (
            registry.histogram(
                "serving_replica_drain_seconds",
                help="wall time of a graceful replica drain (queue + "
                "in-flight window finished, nothing dropped)",
            )
            if registry is not None
            else None
        )
        # Hedged dispatch (docs/SERVING.md tail latency): off by
        # default; ``hedge_delay_ms=None`` derives the delay from each
        # class's online p99 digest.  One replica cannot hedge.
        self.hedger: HedgeManager | None = None
        if hedge and len(self.replicas) > 1:
            self.hedger = HedgeManager(
                self, delay_ms=hedge_delay_ms, poll_s=hedge_poll_s
            ).start()

    # -- membership / aggregate reads ----------------------------------------

    def active(self) -> list[Replica]:
        with self._lock:
            return [r for r in self.replicas if r.active]

    def routable_count(self) -> int:
        """Active replicas whose circuit currently admits placement —
        the readiness signal (``/readyz``, docs/ROBUSTNESS.md): zero
        means every replica is draining, quarantined, ejected, or
        circuit-blocked, and new requests can only 503."""
        with self._lock:
            return sum(
                1 for r in self.replicas
                if r.active and (r.breaker is None or r.breaker.allows())
            )

    def replica(self, name: str) -> Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"no replica named {name!r}")

    def depth(self) -> int:
        """Summed admission-queue depth over ALL replicas — a draining
        replica's queued work still occupies its device and must not
        read as load that vanished (drained batchers report 0)."""
        return sum(r.batcher.depth() for r in self.replicas)

    def inflight(self) -> int:
        """Summed launched-not-yet-read batches over ALL replicas (see
        :meth:`depth` — draining work is still live work)."""
        return sum(r.batcher.inflight() for r in self.replicas)

    @property
    def max_inflight(self) -> int:
        return sum(r.batcher.max_inflight for r in self.active())

    @property
    def qos_classes(self) -> tuple[str, ...]:
        """The pool's QoS classes (batchers are built identically, so
        replica 0 speaks for all) — the server's 400-validation list."""
        batcher = self.replicas[0].batcher
        return getattr(batcher, "qos_classes", ())

    @property
    def timeout_s(self) -> float:
        """The pool's default per-request deadline (min over replicas)
        — lets the handler's drain-race retry pass the REMAINING budget
        instead of granting the resubmission a fresh full deadline."""
        return min(r.batcher.timeout_s for r in self.replicas)

    @property
    def current_linger_ms(self) -> float:
        lingers = [r.batcher.current_linger_ms for r in self.active()]
        return sum(lingers) / len(lingers) if lingers else 0.0

    def replica_stats(self) -> dict[str, dict]:
        """Per-replica live state: the ``/metrics`` ``replicas`` block."""
        return {
            r.name: {
                "state": r.state,
                "circuit": r.breaker.state if r.breaker is not None else None,
                "queue_depth": r.batcher.depth(),
                "qos_depth": (
                    r.batcher.qos_depths()
                    if hasattr(r.batcher, "qos_depths") else None
                ),
                "inflight": r.batcher.inflight(),
                "ewma_latency_ms": (
                    1e3 * r.ewma_latency_s
                    if r.ewma_latency_s is not None else None
                ),
                "class_ewma_ms": {
                    cls: 1e3 * s
                    for cls, s in sorted(r._class_ewma_s.items())
                },
            }
            for r in self.replicas
        }

    # -- placement ------------------------------------------------------------

    @staticmethod
    def _trials_first(order: list[Replica]) -> list[Replica]:
        """Stable-partition half-open replicas with free trial tokens to
        the front.  A half-open circuit can only close by carrying trial
        traffic, and policy order alone may never offer it any: the cost
        policy ranks a restarted replica by its persisted EWMA, so a
        slow-but-recovered replica sorts last and a light request stream
        (or the post-chaos recovery probe) lands every request on its
        healthier peers — leaving it half-open forever.  Preferring it
        is safe because ``try_acquire`` bounds exposure to
        ``trial_limit`` concurrent trials; everything past the quota
        falls through to normal policy order on the same pass."""
        trials = [
            r for r in order
            if r.breaker is not None
            and r.breaker.state == CIRCUIT_HALF_OPEN
            and r.breaker.allows()
        ]
        if not trials:
            return order
        return trials + [r for r in order if r not in trials]

    def _order(
        self, active: list[Replica], rows: int | None = None
    ) -> list[Replica]:
        """Active replicas, best placement first, under the lock."""
        with self._lock:
            rotation = self._rr
            self._rr += 1
        if self.policy == "roundrobin":
            k = rotation % len(active)
            return self._trials_first(active[k:] + active[:k])
        if self.policy == "least-loaded":
            key = lambda r: r.load()  # noqa: E731 - local sort key
        else:
            # cost: expected time-to-answer = (backlog + this request) x
            # EWMA latency for THIS request's shape class.  Per-class
            # scoring is what makes heterogeneous pools routable: a
            # 4-device TP replica is several times faster at the top
            # bucket but ordinary at b1, and one smeared EWMA would
            # either hide the big-batch win or falsely promote it for
            # small requests.  A replica without samples in the class
            # scores with the CLASS's pool-mean as its prior — not the
            # replica's other-shape samples (a fresh TP replica's b1
            # latency says nothing about its b64), and not last place,
            # which would starve it of the very traffic that builds its
            # estimate.  No samples in the class anywhere -> the legacy
            # global-EWMA score; no samples at all -> least-loaded (the
            # documented fallback).
            cls = shape_class(rows) if rows is not None else None
            class_ewmas = (
                [
                    e for e in
                    (r.class_latency_s(cls) for r in active)
                    if e is not None
                ]
                if cls is not None else []
            )
            if class_ewmas:
                prior = sum(class_ewmas) / len(class_ewmas)

                def key(r: Replica):
                    ewma = r.class_latency_s(cls)
                    return (r.load() + 1) * (prior if ewma is None else ewma)
            else:
                ewmas = [
                    r.ewma_latency_s for r in active
                    if r.ewma_latency_s is not None
                ]
                if not ewmas:
                    key = lambda r: r.load()  # noqa: E731 - local sort key
                else:
                    prior = sum(ewmas) / len(ewmas)

                    def key(r: Replica):
                        ewma = r.ewma_latency_s
                        return (r.load() + 1) * (
                            prior if ewma is None else ewma
                        )
        # Rotate before the stable sort so exact ties spread over
        # replicas instead of always landing on the first name.
        k = rotation % len(active)
        return self._trials_first(sorted(active[k:] + active[:k], key=key))

    def _note(self, replica: Replica, rows: int) -> None:
        cls = shape_class(rows)
        if self._registry is not None:
            self._registry.counter(
                "serving_router_decisions_total",
                help="request placements by policy and chosen replica",
                policy=self.policy,
                replica=replica.name,
            ).inc()
            # A separate family, NOT an extra label on the one above:
            # the per-replica family's label schema is pinned by CI
            # greps and dashboards, and the shape tally answers a
            # different question (which classes the cost model routed,
            # perf_report's sharded-serving section).
            self._registry.counter(
                "serving_router_shape_decisions_total",
                help="request placements by policy and request shape "
                "class (pow2-ceiling rows bucket)",
                policy=self.policy,
                shape_class=cls,
            ).inc()
        if self._sink:
            self._sink.emit(
                "router_decision", policy=self.policy,
                replica=replica.name, rows=rows, shape_class=cls,
            )

    def submit(
        self,
        x: np.ndarray,
        timeout_ms: float | None = None,
        dtype: str | None = None,
        qos: str | None = None,
    ) -> PendingRequest | ShardedRequest:
        """Place one request (or its shards) onto the pool.

        Tries replicas in policy order: a replica that rejects (queue
        full, or a drain racing this submit) is transparently skipped —
        only when EVERY active replica refuses does the caller see the
        503.  Per-attempt rejections are not double-counted on the
        metrics surface (only the final, client-visible one is).
        ``qos`` rides through to each batcher's weighted admission
        queue (serving/qos.py); placed requests are registered with the
        hedger when hedging is on (sharded chunks are not hedged — a
        chunk's twin would race its own reassembly).
        """
        active = self.active()
        if not active:
            if self.metrics is not None:
                self.metrics.record_rejected()
            raise RejectedError("no active replicas")
        x = np.asarray(x, np.float32)
        cap = min(r.batcher.max_batch for r in active)
        if len(x) > cap:
            return self._submit_sharded(x, active, cap, timeout_ms, dtype, qos)
        req, placed = self._place(x, active, timeout_ms, dtype, qos)
        if self.hedger is not None and (
            placed.breaker is None
            or placed.breaker.state == CIRCUIT_CLOSED
        ):
            # Never hedge a request placed on a non-closed origin: a
            # half-open placement holds one of the breaker's trial
            # tokens, and the token only returns through THAT replica's
            # own outcome paths (record_success / record_failure /
            # on_expire).  A hedge twin winning elsewhere would leave
            # the origin's copy to be silently discarded — token leaked,
            # breaker pinned half-open forever.  Semantically the trial
            # must run on the origin anyway: hedging around the probe
            # defeats it.
            self.hedger.track(req, placed.name)
        return req

    def _place(self, x, active, timeout_ms, dtype, qos=None):
        # ``active`` is the submit-time snapshot (one lock round-trip
        # per request, shared across a sharded request's chunks).  A
        # replica drained after the snapshot rejects at its batcher and
        # is skipped like any other refusal.  An OPEN circuit blocks
        # placement outright (docs/ROBUSTNESS.md); a half-open one
        # admits at most its trial quota, so a freshly restarted replica
        # proves itself on a trickle, not the full stream.
        order = self._order(active, len(x))
        saw_error: RejectedError | None = None
        for r in order:
            if r.breaker is not None and not r.breaker.try_acquire():
                continue
            try:
                req = r.batcher.submit(
                    x, timeout_ms=timeout_ms, dtype=dtype, qos=qos,
                    count_reject=False,
                )
            except RejectedError as e:
                # Admission refused before any work dispatched — return
                # the trial token; this is backpressure, not a failure.
                if r.breaker is not None:
                    r.breaker.release()
                saw_error = e
                continue
            self._note(r, len(x))
            return req, r
        # Exactly one client-visible 503 however many replicas were
        # tried (the per-attempt skips are not client outcomes).
        if self.metrics is not None:
            self.metrics.record_rejected()
        raise saw_error if saw_error is not None else RejectedError(
            "no routable replicas (every circuit open or replica draining)"
        )

    def _submit_sharded(
        self, x, active, cap, timeout_ms, dtype, qos=None
    ) -> ShardedRequest:
        """Chunks are placed sequentially; a rejection mid-placement
        (every replica full) propagates to the client as one 503, while
        chunks already admitted drain normally on their replicas — their
        finished device work is discarded, exactly as for a client that
        disconnects mid-request.  The client-visible contract stays
        atomic: one request, one answer or one error, never a partial
        result."""
        if len(x) > cap * len(active):
            if self.metrics is not None:
                self.metrics.record_rejected()
            raise RejectedError(
                f"request of {len(x)} samples exceeds pool capacity "
                f"({len(active)} replicas x {cap} max batch)"
            )
        # Near-equal chunks preserve arrival order (chunk i = rows
        # [offsets[i], offsets[i+1])) and spread the work instead of
        # filling replica 1 and sending replica 2 the remainder.
        n_chunks = -(-len(x) // cap)
        bounds = np.linspace(0, len(x), n_chunks + 1).astype(int)
        parts = [
            self._place(x[lo:hi], active, timeout_ms, dtype, qos)[0]
            for lo, hi in zip(bounds[:-1], bounds[1:])
        ]
        return ShardedRequest(parts)

    # -- elasticity ------------------------------------------------------------

    def drain(self, name: str) -> float:
        """Gracefully remove one replica under live traffic.

        Ordering is the correctness: the replica is marked unroutable
        BEFORE its batcher drains, so no new placement can land on it
        mid-drain; ``stop(drain=True)`` then finishes its queue and
        in-flight window (the PR-4 guarantee — nothing lost, nothing
        duplicated).  Returns (and records) the drain wall seconds.
        """
        replica = self.replica(name)
        with self._lock:
            if not replica.active:
                raise RuntimeError(
                    f"replica {name!r} is {replica.state}, not active"
                )
            if sum(1 for r in self.replicas if r.active) == 1:
                raise RuntimeError(
                    f"refusing to drain {name!r}: it is the last active "
                    "replica (stop the server instead)"
                )
            replica.state = "draining"
        t0 = time.perf_counter()
        replica.batcher.stop(drain=True)
        duration = time.perf_counter() - t0
        replica.state = "drained"
        if self._drain_hist is not None:
            self._drain_hist.observe(duration)
        if self._sink:
            self._sink.emit(
                "replica_drain", replica=name, duration_s=duration
            )
        return duration

    def attach(self, name: str, batcher: MicroBatcher) -> Replica:
        """Re-add a drained (or supervisor-restarting) replica with a
        fresh (started) batcher, or register a brand-new one.  Routable
        as soon as this returns — subject to the replica's circuit
        (a restart leaves it half-open until a trial passes)."""
        with self._lock:
            for r in self.replicas:
                if r.name == name:
                    r.reactivate(batcher)
                    return r
            replica = Replica(name, batcher)
            replica.breaker = CircuitBreaker(
                name, registry=self._registry, sink=self._sink,
                **self._breaker_kwargs,
            )
            self.replicas.append(replica)
            return replica

    # -- fault tolerance (the supervisor's surface, serving/pool.py) ---------

    def quarantine(self, name: str, reason: str = "sick") -> int:
        """Forcibly remove a SICK replica from rotation: trip its
        circuit open, mark it quarantined, and abort its batcher —
        queued and in-flight requests complete with
        :class:`~.batcher.ReplicaDeadError` so their handlers retry on
        survivors.  Unlike :meth:`drain`, this never waits on the
        replica (a dead one would park the drain forever) and it IS
        allowed to take the last active replica down — a sick lone
        replica serving poison is worse than an honest 503.  Returns the
        flushed-request count."""
        replica = self.replica(name)
        with self._lock:
            if replica.state != "active":
                raise RuntimeError(
                    f"replica {name!r} is {replica.state}, not active"
                )
            replica.state = "quarantined"
        if replica.breaker is not None:
            replica.breaker.force_open(reason)
        flushed = replica.batcher.abort()
        if self._sink:
            self._sink.emit(
                "replica_quarantine", replica=name, reason=reason,
                flushed=flushed,
            )
        return flushed

    def record_retry(self) -> None:
        """One handler-side resubmission of a never-executed request
        (drain race or replica death) — the failure-aware retry tally
        (``serving_request_retries_total``)."""
        if self.metrics is not None:
            self.metrics.record_retry()
        if self._sink:
            self._sink.emit("request_retry")

    # -- lifecycle -------------------------------------------------------------

    def stop(self, drain: bool = True) -> None:
        """Stop every active replica's batcher (draining by default).
        Replicas already drained are left alone; quarantined/ejected
        ones were aborted by the supervisor, and their ``stop`` is a
        no-op (the aborted completion worker may be unjoinable).  Drains
        run concurrently — each replica's queue/window finishes on its
        own device, so shutdown wall time is the slowest drain, not the
        sum of all of them."""
        if self.hedger is not None:
            # Hedger first: a hedge placed onto a draining batcher would
            # either race its flush or delay the drain for nothing.
            self.hedger.stop()
        stopping = [
            r for r in self.replicas if r.state not in ("drained", "ejected")
        ]
        for r in stopping:
            if r.state != "quarantined":
                r.state = "draining"
        if not stopping:
            return

        def _stop(r: Replica) -> None:
            r.batcher.stop(drain=drain)
            if r.state != "quarantined":
                r.state = "drained"

        with ThreadPoolExecutor(max_workers=len(stopping)) as pool:
            list(pool.map(_stop, stopping))
