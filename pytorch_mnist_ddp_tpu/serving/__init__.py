"""Inference serving: the checkpoint -> answering-requests path.

Everything else in this repo is training-side; this package grows the
eval seed (``parallel/ddp.make_eval_step``; PAPER.md's survey calls it
the inference entry point) into a request-serving engine shaped for the
ROADMAP's "heavy traffic" north star:

- :mod:`.buckets` — the power-of-two shape-bucket policy.  Serving
  traffic arrives at arbitrary batch sizes; jit retraces on every new
  shape, so the engine only ever dispatches a fixed set of bucket
  shapes, padding up and slicing back down.  This is the serving twin of
  the training loader's pad-the-final-partial-batch rule
  (data/loader.py), enforced at runtime by a RecompileSentinel.
- :mod:`.engine` — :class:`InferenceEngine`: loads a checkpoint
  (either surface: ``--save-model`` or ``--save-state``), warms every
  bucket exactly once, and runs the forward on the data-parallel mesh.
- :mod:`.batcher` — :class:`MicroBatcher`: coalesces queued requests up
  to a max batch or a linger deadline, with a bounded admission queue,
  per-request deadlines, reject-don't-queue backpressure, and graceful
  drain.  Pipelined (PR 4): a dispatch worker pads into preallocated
  staging buffers and launches async; a completion worker does the
  blocking D2H read — a bounded in-flight window (``max_inflight``)
  overlaps batch N+1's host work with batch N's device compute, and an
  :class:`AdaptiveLinger` controller shrinks the linger toward 0 when
  the queue is deep.
- :mod:`.metrics` — queue depth, batch occupancy, padding waste,
  latency percentiles, throughput (string-returning report helpers,
  utils/logging.py convention), rebuilt on the shared telemetry
  registry (obs/registry.py) so the same numbers back the JSON
  snapshot AND the Prometheus exposition.
- :mod:`.server` — stdlib-only ``http.server`` JSON endpoint
  (``/metrics`` also serves Prometheus text with ``Accept: text/plain``
  or ``?format=prom``); run it with
  ``python -m pytorch_mnist_ddp_tpu.serving``.
- :mod:`.pool` / :mod:`.router` — scale-out (PR 7): one engine+batcher
  replica per device (:class:`EnginePool`, shared weights + shared AOT
  store, explicit device pinning) behind a queue-aware admission
  :class:`Router` (``--replicas`` / ``--router-policy {roundrobin,
  least-loaded,cost}``), with sharded dispatch for oversized batches
  and graceful replica drain/re-add under live traffic.
- :mod:`.faults` — fault tolerance (PR 8, docs/ROBUSTNESS.md): a
  deterministic, seedable fault-injection surface (dormant fault points
  in dispatch/completion/warmup/AOT-load), driven by the
  :class:`~.pool.ReplicaSupervisor` (quarantine → backoff restart →
  ejection) and per-replica :class:`~.router.CircuitBreaker`\\ s
  (closed/open/half-open) so a replica that throws, hangs, or dies is
  detected, ejected from placement, and healed under live load — and
  the loadgen's ``--chaos`` mode proves it.
- :mod:`.qos` — tail-latency engineering (PR 11, docs/SERVING.md):
  per-request QoS classes (``interactive``/``batch``) on a weighted
  admission queue that sheds the lowest class first under pressure,
  deadline-aware batch close (the linger is clamped by the oldest
  member's remaining budget), and hedged dispatch
  (:class:`~.router.HedgeManager`: stragglers re-dispatch to a second
  replica after a p99-derived delay, first-wins completion, no
  double-counted outcomes).

- :mod:`.fleet` — the multi-host tier (PR 12, docs/SERVING.md fleet
  section): a jax-free front (:class:`~.fleet.Fleet` +
  :class:`~.fleet.FleetRouter`) that speaks HTTP to N backend serving
  PROCESSES over keep-alive pools with per-attempt timeouts, places by
  the PR-7 policies fed from polled ``/metrics`` snapshots, wraps each
  backend in a :class:`~.circuit.CircuitBreaker`, REPLACES dead/wedged
  backends (:class:`~.fleet.FleetSupervisor`: liveness + ``/readyz``
  probes + heartbeat files, seeded-backoff budget, warm-start off the
  shared AOT cache — zero new traces), and autoscales
  (:class:`~.fleet.FleetAutoscaler`: watermark + sustain-window +
  cooldown hysteresis; drain → settle → kill loses nothing).  Run it
  with ``python -m pytorch_mnist_ddp_tpu.serving --fleet N
  [--autoscale]``.

- :mod:`.wire` / :mod:`.cache` — the host hot path (PR 14,
  docs/SERVING.md): a binary wire protocol for ``/predict``
  (``Content-Type: application/x-mnist-f32`` — fixed little-endian
  header + raw float32 rows, parsed with ONE zero-copy
  ``np.frombuffer``; responses are raw logits bytes; JSON stays the
  byte-identical default) that the fleet front proxies verbatim, and a
  content-addressed response cache with single-flight dedup
  (``--response-cache N``: deterministic inference keyed on
  (weights digest, dtype, payload hash); concurrent identical requests
  coalesce onto one dispatch; a failed dispatch fails every coalesced
  waiter and never leaves a stale fill; off by default).

Load-test with ``tools/serve_loadgen.py``; see docs/SERVING.md.
"""

# Lazy exports (PEP 562).  The fleet front tier (`--fleet`,
# serving/fleet.py) is a jax-free control plane that must come up in
# milliseconds and keep working when jax — the thing its backends own —
# is the broken part; an eager `from .engine import ...` here would pay
# the full jax import on EVERY `import pytorch_mnist_ddp_tpu.serving`,
# including the front's.  Attribute access resolves the submodule on
# first touch, so `from pytorch_mnist_ddp_tpu.serving import Fleet`
# stays light while `... import EnginePool` still works (and pays jax
# only then).
_EXPORTS = {
    "batcher": (
        "AdaptiveLinger", "MicroBatcher", "RejectedError",
        "ReplicaDeadError", "RequestTimeout",
    ),
    "buckets": (
        "StagingPool", "bucket_for", "pad_to_bucket", "pow2_buckets",
        "validate_buckets",
    ),
    "cache": ("ResponseCache",),
    "circuit": ("CircuitBreaker",),
    "engine": ("InferenceEngine",),
    "faults": ("FaultError", "FaultInjector"),
    "fleet": (
        "Backend", "FakeBackendServer", "Fleet", "FleetAutoscaler",
        "FleetRouter", "FleetSupervisor", "fake_backend_spawner",
        "make_fleet_server",
    ),
    "metrics": ("ServingMetrics",),
    "pool": ("EnginePool", "ReplicaSupervisor"),
    "qos": ("DEFAULT_QOS", "QOS_CLASSES", "QoSQueue"),
    "router": ("HedgeManager", "Replica", "Router", "ShardedRequest"),
    "wire": ("WireError", "WireRequest"),
}
_EXPORT_TO_MODULE = {
    name: module for module, names in _EXPORTS.items() for name in names
}


def __getattr__(name: str):
    module = _EXPORT_TO_MODULE.get(name)
    if module is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    import importlib

    value = getattr(importlib.import_module(f".{module}", __name__), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORT_TO_MODULE))

__all__ = [
    "AdaptiveLinger",
    "Backend",
    "CircuitBreaker",
    "DEFAULT_QOS",
    "EnginePool",
    "FakeBackendServer",
    "FaultError",
    "FaultInjector",
    "Fleet",
    "FleetAutoscaler",
    "FleetRouter",
    "FleetSupervisor",
    "HedgeManager",
    "InferenceEngine",
    "MicroBatcher",
    "QOS_CLASSES",
    "QoSQueue",
    "RejectedError",
    "ResponseCache",
    "Replica",
    "ReplicaDeadError",
    "ReplicaSupervisor",
    "RequestTimeout",
    "Router",
    "ServingMetrics",
    "ShardedRequest",
    "StagingPool",
    "WireError",
    "WireRequest",
    "bucket_for",
    "fake_backend_spawner",
    "make_fleet_server",
    "pad_to_bucket",
    "pow2_buckets",
    "validate_buckets",
]
