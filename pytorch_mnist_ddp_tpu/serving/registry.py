"""Multi-tenant model registry: named (model, version) serving entries.

The reference trainer writes ONE checkpoint and the serving stack (until
this module) hard-coded exactly one of them per process — so shipping a
model meant restarting the fleet, which a fleet serving live traffic can
never do (ROADMAP open item 1).  The registry is the control-plane
answer: a directory holding checkpoints plus ONE durable manifest
(``registry.json``, written atomically — utils/checkpoint.py
``save_registry_manifest``) that names every ``(model, version)`` entry:

- the checkpoint path (registry-relative when inside the directory, so
  the whole directory relocates — rsync to a new host, mount elsewhere);
- the **weights digest** (serving/engine.py ``weights_digest``) recorded
  at publish time and re-verified at load time, so a checkpoint file
  swapped or corrupted behind the manifest's back is REFUSED, never
  silently served;
- the **model family** (``net`` today; recorded so a future multi-family
  engine can refuse a family it cannot serve instead of crashing);
- the **parity record** — the version's reduced-precision gate verdicts,
  carried from wherever the version was validated.

Routing state lives in the same manifest: ``default_model`` plus each
model's ``default_version`` are the aliases a ``/predict`` with absent
``model``/``version`` fields resolves through — which is how the
pre-registry behavior stays byte-identical: no registry, or a request
with no fields, serves exactly what it served yesterday.

The taught access idiom (jaxlint JL022, docs/ANALYSIS.md): serving code
reaches checkpoints ONLY through :meth:`ModelRegistry.resolve` /
:meth:`ModelRegistry.load`, and publishes new versions ONLY through
:meth:`ModelRegistry.publish` — direct checkpoint-path construction or
engine weight mutation outside this surface is a lint error, because a
path or a weight swap the manifest does not know about is invisible to
the rollout controller, the response cache's invalidation, and every
per-version metric.

The data-plane half — request routing, canary percentages, swap
execution, auto-rollback — is :class:`~.rollout.RolloutController`
(serving/rollout.py).  stdlib + numpy here; jax is imported lazily only
when weights are actually loaded or prewarmed.
"""

from __future__ import annotations

import os
from typing import Any, Sequence

from ..analysis.lockwatch import make_lock
from ..utils.checkpoint import (
    load_registry_manifest,
    registry_manifest_path,
    save_registry_manifest,
)

# The family every checkpoint this repo trains today belongs to
# (models/net.py).  Recorded per entry for forward-compatibility; the
# engine refuses families it cannot serve at load time.
DEFAULT_FAMILY = "net"


class RegistryError(ValueError):
    """A registry operation that cannot proceed — unknown model/version,
    digest mismatch, malformed manifest.  Subclasses ValueError so the
    server's 400 mapping handles unknown-name resolution unchanged."""


class ModelVersion:
    """One immutable (model, version) manifest entry."""

    __slots__ = ("model", "version", "checkpoint", "digest", "family",
                 "parity")

    def __init__(self, model, version, checkpoint, digest, family, parity):
        self.model = model
        self.version = version
        self.checkpoint = checkpoint  # registry-relative or absolute
        self.digest = digest          # weights_digest at publish time
        self.family = family
        self.parity = parity          # per-dtype gate record or None

    def path(self, directory: str) -> str:
        return (
            self.checkpoint
            if os.path.isabs(self.checkpoint)
            else os.path.join(directory, self.checkpoint)
        )

    def describe(self) -> dict:
        return {
            "model": self.model,
            "version": self.version,
            "checkpoint": self.checkpoint,
            "digest": self.digest,
            "family": self.family,
            "parity": self.parity,
        }


class ModelRegistry:
    """The durable (model, version) -> checkpoint catalog over one
    directory.

    Construction loads the manifest when one exists; a directory without
    one is a valid EMPTY registry (the first :meth:`publish` creates
    it).  All mutation goes through publish/set_default, each of which
    rewrites the whole manifest atomically — a reader (another backend
    mid-rolling-swap, an operator's inspection) only ever sees a
    complete manifest.
    """

    def __init__(self, directory: str, sink=None):
        self.directory = os.path.abspath(directory)
        self._sink = sink
        self._lock = make_lock("registry.manifest")
        self._default_model: str | None = None
        self._models: dict[str, dict] = {}
        if os.path.exists(registry_manifest_path(self.directory)):
            self._read_manifest()

    # -- manifest I/O ---------------------------------------------------------

    def _read_manifest(self) -> None:
        manifest = load_registry_manifest(self.directory)
        models: dict[str, dict] = {}
        for model, spec in (manifest.get("models") or {}).items():
            versions = {}
            for version, entry in (spec.get("versions") or {}).items():
                versions[version] = ModelVersion(
                    model=model,
                    version=version,
                    checkpoint=entry["checkpoint"],
                    digest=entry.get("digest", ""),
                    family=entry.get("family", DEFAULT_FAMILY),
                    parity=entry.get("parity"),
                )
            models[model] = {
                "default_version": spec.get("default_version"),
                "versions": versions,
            }
        self._models = models
        self._default_model = manifest.get("default_model")

    def _manifest_dict(self) -> dict:
        return {
            "default_model": self._default_model,
            "models": {
                model: {
                    "default_version": spec["default_version"],
                    "versions": {
                        v: {
                            "checkpoint": e.checkpoint,
                            "digest": e.digest,
                            "family": e.family,
                            "parity": e.parity,
                        }
                        for v, e in spec["versions"].items()
                    },
                }
                for model, spec in self._models.items()
            },
        }

    def _write_manifest(self) -> None:
        save_registry_manifest(self._manifest_dict(), self.directory)

    # -- reads ----------------------------------------------------------------

    def models(self) -> list[str]:
        with self._lock:
            return sorted(self._models)

    def versions(self, model: str) -> list[str]:
        with self._lock:
            spec = self._models.get(model)
            if spec is None:
                raise RegistryError(
                    f"unknown model {model!r}; registered: "
                    f"{sorted(self._models)}"
                )
            return sorted(spec["versions"])

    def resolve(
        self, model: str | None = None, version: str | None = None
    ) -> ModelVersion:
        """THE routing lookup (and the JL022 taught idiom): absent
        ``model`` resolves to the default model, absent ``version`` to
        that model's default version — so a request carrying neither
        field serves exactly the pre-registry checkpoint.  Unknown
        names raise :class:`RegistryError` (-> HTTP 400)."""
        with self._lock:
            name = model if model is not None else self._default_model
            if name is None or name not in self._models:
                raise RegistryError(
                    f"unknown model {name!r}; registered: "
                    f"{sorted(self._models)}"
                )
            spec = self._models[name]
            v = version if version is not None else spec["default_version"]
            if v is None or v not in spec["versions"]:
                raise RegistryError(
                    f"unknown version {v!r} of model {name!r}; registered: "
                    f"{sorted(spec['versions'])}"
                )
            return spec["versions"][v]

    def describe(self) -> dict:
        """The admin/status surface: default aliases + every entry."""
        with self._lock:
            return {
                "directory": self.directory,
                "default_model": self._default_model,
                "models": {
                    model: {
                        "default_version": spec["default_version"],
                        "versions": {
                            v: e.describe()
                            for v, e in spec["versions"].items()
                        },
                    }
                    for model, spec in self._models.items()
                },
            }

    # -- weights --------------------------------------------------------------

    def load(self, entry: ModelVersion) -> dict[str, Any]:
        """Entry -> eval-ready Flax variables, digest-verified.

        The digest recorded at publish time must match what the file
        hashes to NOW; a mismatch means the checkpoint changed behind
        the manifest's back (partial copy, overwrite, corruption) and
        serving it would put weights on the wire that no manifest,
        metric, or cache key describes — refused here."""
        from ..utils.checkpoint import load_inference_variables
        from .engine import weights_digest

        path = entry.path(self.directory)
        variables = load_inference_variables(path)
        if entry.digest:
            served = (
                variables
                if "batch_stats" in variables
                else variables["params"]
            )
            actual = weights_digest(served)
            if actual != entry.digest:
                raise RegistryError(
                    f"checkpoint {path!r} hashes to {actual} but the "
                    f"manifest records {entry.digest} for "
                    f"{entry.model}@{entry.version}; the file changed "
                    "behind the manifest — re-publish the version"
                )
        return variables

    # -- mutation -------------------------------------------------------------

    def publish(
        self,
        model: str,
        version: str,
        checkpoint: str,
        *,
        family: str = DEFAULT_FAMILY,
        parity: dict | None = None,
        make_default: bool = False,
    ) -> ModelVersion:
        """Register (or re-register) a version and atomically publish
        the manifest — the ONLY write path for serving checkpoints
        (jaxlint JL022).

        ``checkpoint`` may live anywhere; a path inside the registry
        directory is recorded relative so the directory relocates as a
        unit.  The weights digest is computed HERE, from the actual
        file, so the manifest can never claim a digest the bytes don't
        back.  ``make_default`` (or being the first model/version)
        updates the routing aliases in the same atomic write."""
        from ..utils.checkpoint import load_inference_variables
        from .engine import weights_digest

        if not model or not version:
            raise RegistryError("model and version must be non-empty")
        # "@" is the engine's dtype<->version variant-key separator
        # (engine.VERSION_SEP); a version containing it would mint
        # ambiguous canary keys.
        if "@" in version:
            raise RegistryError(
                f"version {version!r} must not contain '@'"
            )
        path = os.path.abspath(checkpoint)
        if not os.path.exists(path):
            raise RegistryError(f"checkpoint {path!r} does not exist")
        variables = load_inference_variables(path)
        served = (
            variables if "batch_stats" in variables else variables["params"]
        )
        digest = weights_digest(served)
        rel = os.path.relpath(path, self.directory)
        stored = path if rel.startswith("..") else rel
        entry = ModelVersion(
            model=model, version=version, checkpoint=stored,
            digest=digest, family=family, parity=parity,
        )
        with self._lock:
            spec = self._models.setdefault(
                model, {"default_version": None, "versions": {}}
            )
            spec["versions"][version] = entry
            if make_default or spec["default_version"] is None:
                spec["default_version"] = version
            if make_default or self._default_model is None:
                self._default_model = model
            self._write_manifest()
        if self._sink:
            self._sink.emit(
                "model_publish", model=model, version=version,
                digest=digest, default=bool(
                    make_default or spec["default_version"] == version
                ),
            )
        return entry

    def set_default(self, model: str, version: str) -> ModelVersion:
        """Point the routing aliases at (model, version) — the durable
        half of a swap promotion, in one atomic manifest write."""
        with self._lock:
            spec = self._models.get(model)
            if spec is None or version not in spec["versions"]:
                raise RegistryError(
                    f"cannot default to unregistered {model}@{version}"
                )
            spec["default_version"] = version
            self._default_model = model
            self._write_manifest()
            return spec["versions"][version]

    # -- per-version Program grids --------------------------------------------

    def prewarm(
        self,
        entry: ModelVersion,
        mesh,
        buckets: Sequence[int],
        store,
        *,
        use_bn: bool = False,
        conv_impl: str = "conv",
        device_stage: bool | None = None,
    ) -> list:
        """Build (or deserialize) VERSION's per-bucket Program grid into
        the shared ExecutableStore, keyed under its version — the
        warm-swap prerequisite: because versions join the canonical
        :func:`~..compile.predict_config` digest, two versions' grids
        COEXIST in one store, and a fleet backend restarted onto the
        new default warm-starts with zero traces (the SLO gate's swap
        round pins this, tools/slo_gate.py)."""
        from ..compile import build_programs, serving_predict_programs

        variables = self.load(entry)
        served = (
            variables if "batch_stats" in variables else variables["params"]
        )
        programs = serving_predict_programs(
            mesh, served, buckets, store=store, use_bn=use_bn,
            conv_impl=conv_impl, device_stage=device_stage,
            version=entry.version,
        )
        build_programs(programs)
        return programs
