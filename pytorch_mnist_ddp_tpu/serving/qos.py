"""QoS classes and the weighted per-class admission queue.

The tail-latency story (docs/SERVING.md QoS section): at load, queueing —
not compute — owns p99, and a single FIFO admission queue makes every
latency-sensitive request wait behind whatever bulk traffic arrived
first.  This module gives the batcher the two scheduler primitives that
fix it:

- **QoS classes.**  Every request carries a class name
  (``interactive`` / ``batch`` by default; the list is extensible).
  Classes are ordered by priority: earlier in the tuple = more
  latency-sensitive.  The class travels ``/predict`` → router →
  ``MicroBatcher.submit(qos=)`` and lands on the per-class metric
  families (``serving_qos_requests_total{qos=}``,
  ``serving_qos_latency_seconds{qos=}`` — docs/OBSERVABILITY.md).

- :class:`QoSQueue` — the bounded admission queue, rebuilt per class.
  Dequeue order is **weighted round-robin** over non-empty classes
  (default 4:1 interactive:batch): bulk traffic keeps flowing, but a
  queued interactive request overtakes an arbitrarily deep batch
  backlog within one service cycle instead of draining behind it.
  Under pressure the queue **sheds lowest class first**: when full, an
  arriving request may evict the most-recently-admitted request of a
  strictly lower class (least sunk queue time), so interactive goodput
  holds while batch absorbs the 503s.  It also supports **eager expiry**
  (:meth:`QoSQueue.sweep_expired`): a request whose deadline passed
  while queued is removed the moment any worker looks, not when batch
  formation happens to reach it — freeing its queue slot and (through
  the batcher's ``on_expire`` hook) any half-open circuit trial token
  it holds.

The queue intentionally speaks the ``queue.Queue`` subset the batcher
always used (``put_nowait``/``get``/``get_nowait``/``qsize``/
``maxsize`` raising ``queue.Full``/``queue.Empty``), so every existing
drain/flush path works unchanged.  Pure stdlib, no jax import — tested
at interactive speed (tests/test_tail.py).
"""

from __future__ import annotations

import queue
import time
from collections import deque

from ..analysis.lockwatch import make_lock

# Priority order, most latency-sensitive first.  The names are the label
# values on every per-class metric family, so keep them short and stable.
QOS_CLASSES: tuple[str, ...] = ("interactive", "batch")

# Requests that name no class get the most latency-sensitive one: a
# pre-QoS client keeps exactly its old behavior (every request in one
# class = plain FIFO), and bulk jobs OPT IN to being shed first.
DEFAULT_QOS = "interactive"

# Weighted-round-robin service shares when several classes have queued
# work: of every 5 dequeues under contention, 4 are interactive.  Batch
# is never starved outright — weight 0 would be starvation, not QoS.
DEFAULT_WEIGHTS: dict[str, int] = {"interactive": 4, "batch": 1}


class QoSQueue:
    """Bounded per-class admission queue with weighted dequeue and
    lowest-class-first shedding.

    ``maxsize`` bounds the TOTAL queued count across classes (the same
    backpressure bound the old single queue enforced).  Thread-safe; one
    condition covers every mutation, and blocking :meth:`get` honors a
    timeout exactly like ``queue.Queue``.
    """

    def __init__(
        self,
        maxsize: int,
        classes: tuple[str, ...] = QOS_CLASSES,
        weights: dict[str, int] | None = None,
    ):
        if maxsize < 1:
            raise ValueError(f"maxsize must be >= 1, got {maxsize}")
        if not classes:
            raise ValueError("need at least one QoS class")
        weights = dict(DEFAULT_WEIGHTS if weights is None else weights)
        for name in classes:
            if weights.get(name, 0) < 1:
                # Weight 0 would starve the class forever — shedding is
                # the sanctioned way to sacrifice it under pressure.
                weights[name] = 1
        self.maxsize = maxsize
        self.classes = tuple(classes)
        self.weights = {name: int(weights[name]) for name in self.classes}
        self._priority = {name: i for i, name in enumerate(self.classes)}
        self._queues: dict[str, deque] = {name: deque() for name in self.classes}
        # Weighted-round-robin state: how many of the current class's
        # service share have been used this cycle.
        self._wrr_class = 0
        self._wrr_served = 0
        self._cond = make_lock("qos.queue", kind="condition")

    # -- sizes -----------------------------------------------------------------

    def qsize(self) -> int:
        with self._cond:
            return sum(len(q) for q in self._queues.values())

    def sizes(self) -> dict[str, int]:
        """Per-class queued counts (the /metrics qos block)."""
        with self._cond:
            return {name: len(q) for name, q in self._queues.items()}

    # -- admission -------------------------------------------------------------

    def put_nowait(self, req) -> None:
        """Admit ``req`` (which must carry ``.qos``) or raise
        ``queue.Full``.  Never sheds — eviction is an explicit policy
        decision the batcher makes (:meth:`shed_for`)."""
        qos = getattr(req, "qos", None) or self.classes[0]
        if qos not in self._priority:
            raise ValueError(
                f"unknown QoS class {qos!r}; have {list(self.classes)}"
            )
        with self._cond:
            if sum(len(q) for q in self._queues.values()) >= self.maxsize:
                raise queue.Full
            self._queues[qos].append(req)
            self._cond.notify()

    def shed_for(self, qos: str):
        """Evict (and return) one queued request of a class strictly
        lower-priority than ``qos``, or None when nothing is sheddable.

        Policy: lowest class first; within the class, the NEWEST request
        (least sunk queue time — the oldest is closest to dispatching,
        so evicting it wastes the most already-paid waiting).  The
        caller completes the victim with the 503 and counts the shed
        (``serving_shed_total{qos=}``).
        """
        incoming = self._priority.get(qos, 0)
        with self._cond:
            for name in reversed(self.classes):
                if self._priority[name] <= incoming:
                    return None
                q = self._queues[name]
                if q:
                    return q.pop()
        return None

    # -- dequeue (dispatch worker) ---------------------------------------------

    def _pick_locked(self):
        """Weighted round-robin choice over non-empty classes, under the
        condition lock.  Returns a request or None when empty."""
        n = len(self.classes)
        if all(not q for q in self._queues.values()):
            return None
        for _ in range(2 * n):  # at most one full cycle + wrap
            name = self.classes[self._wrr_class]
            q = self._queues[name]
            if q and self._wrr_served < self.weights[name]:
                self._wrr_served += 1
                return q.popleft()
            # Class empty or share spent: move on, reset its tally.
            self._wrr_class = (self._wrr_class + 1) % n
            self._wrr_served = 0
        return None  # unreachable while any queue is non-empty

    def get(self, timeout: float | None = None):
        with self._cond:
            req = self._pick_locked()
            if req is not None:
                return req
            deadline = (
                None if timeout is None else time.monotonic() + timeout
            )
            while True:
                if deadline is None:
                    self._cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(remaining):
                        # Timed out (or woke at the boundary): one last
                        # look before giving up, matching queue.Queue.
                        req = self._pick_locked()
                        if req is None:
                            raise queue.Empty
                        return req
                req = self._pick_locked()
                if req is not None:
                    return req

    def get_nowait(self):
        with self._cond:
            req = self._pick_locked()
            if req is None:
                raise queue.Empty
            return req

    # -- eager expiry ----------------------------------------------------------

    def sweep_expired(self, now: float | None = None) -> list:
        """Remove and return every queued request whose deadline has
        passed; silently drop requests already completed elsewhere (a
        hedge whose twin already answered — nothing to expire, the slot
        is simply freed).  The caller expires the returned requests
        through the ``on_expire`` path so queue slot AND any held
        circuit trial token free immediately (docs/ROBUSTNESS.md)."""
        now = now if now is not None else time.perf_counter()
        expired: list = []
        with self._cond:
            for name, q in self._queues.items():
                keep: deque = deque()
                for req in q:
                    done = getattr(req, "done", None)
                    if done is not None and done():
                        continue  # satisfied elsewhere; free the slot
                    if req.expired(now):
                        expired.append(req)
                    else:
                        keep.append(req)
                self._queues[name] = keep
        return expired
