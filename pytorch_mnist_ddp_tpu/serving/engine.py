"""The inference engine: checkpoint -> warmed bucket executables -> logits.

Lifecycle: construct (variables placed replicated on the data-parallel
mesh), :meth:`warmup` (compile every bucket exactly once, then verify a
second pass is pure cache hits), then :meth:`predict_logits` from the
dispatch thread.  The jitted forward is wrapped in a RecompileSentinel
budgeted at exactly ``len(buckets)`` traces, so ANY post-warmup shape
leak — the silent per-request compile stall this subsystem exists to
prevent — raises ``RecompileError`` with a pointed message instead of
serving at 1000x latency.

Threading contract: jax dispatch is not guarded here; exactly one thread
(the micro-batcher dispatch worker, or the caller in direct use) may
call ``launch``/``predict_logits``.  Reading a previously launched
batch's result (``np.asarray`` on the returned device array) is safe
from a second thread — that is the batcher's completion worker, which
overlaps D2H + unsplitting with the next batch's pad + dispatch.  The
HTTP handler threads never touch the engine — they talk to the
batcher's queue.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np

from ..analysis.sentinel import RecompileError, RecompileSentinel
from ..models.net import INPUT_SHAPE, NUM_CLASSES, init_params, init_variables
from ..parallel.ddp import make_predict_step, replicate_params
from ..parallel.mesh import DATA_AXIS, make_mesh
from .buckets import StagingPool, pow2_buckets, validate_buckets
from .metrics import ServingMetrics


class InferenceEngine:
    """Bucket-warmed forward over a data-parallel mesh.

    Parameters
    ----------
    variables:
        Flax variable dict — ``{"params": ...}`` plus ``{"batch_stats":
        ...}`` for BN-bearing checkpoints (``use_bn`` is inferred from
        the tree, never guessed from flags, so a ``--syncbn`` checkpoint
        serves correctly without the operator knowing it was one).
    mesh:
        The data-parallel mesh to dispatch on; defaults to every visible
        device on the ``data`` axis (parallel/mesh.make_mesh).
    buckets:
        Batch-size ladder to warm; defaults to the power-of-two ladder
        from the data-axis size up to ``max_bucket``.  Validated against
        the mesh (every bucket must shard evenly).
    metrics:
        Optional :class:`ServingMetrics`; per-dispatch occupancy is
        recorded when present.
    """

    def __init__(
        self,
        variables: dict[str, Any],
        mesh=None,
        buckets: Sequence[int] | None = None,
        max_bucket: int | None = None,
        compute_dtype=None,
        conv_impl: str = "conv",
        metrics: ServingMetrics | None = None,
    ):
        self.mesh = mesh if mesh is not None else make_mesh()
        n_shards = self.mesh.shape[DATA_AXIS]
        if buckets is None:
            from .buckets import DEFAULT_MAX_BUCKET

            buckets = pow2_buckets(n_shards, max_bucket or DEFAULT_MAX_BUCKET)
        elif max_bucket is not None:
            raise ValueError("pass buckets or max_bucket, not both")
        self.buckets = validate_buckets(buckets, n_shards)
        self.use_bn = "bn1" in variables.get("params", {})
        if self.use_bn and "batch_stats" not in variables:
            # A BN model without running averages would eval-normalize by
            # garbage; init defaults (mean 0 / var 1) are torch's
            # never-trained behavior and at least well-defined.
            variables = dict(variables)
            variables["batch_stats"] = init_variables(
                jax.random.PRNGKey(0), use_bn=True
            )["batch_stats"]
        served = (
            {"params": variables["params"],
             "batch_stats": variables["batch_stats"]}
            if self.use_bn
            else variables["params"]
        )
        self._variables = replicate_params(served, self.mesh)
        fn = make_predict_step(
            self.mesh,
            compute_dtype=compute_dtype or jax.numpy.float32,
            use_bn=self.use_bn,
            conv_impl=conv_impl,
        )
        # One trace per bucket, ever.  A post-warmup retrace means a
        # request shape escaped the bucket policy.  Compile events land
        # on the shared registry (jax_compiles_total{fn="predict_step"})
        # so /metrics exposes the count Prometheus-side too.
        self._predict = RecompileSentinel(
            fn,
            max_traces=len(self.buckets),
            name="predict_step",
            registry=metrics.registry if metrics is not None else None,
        )
        self.metrics = metrics
        self.warmed = False
        # Direct-call staging: one preallocated pad target per bucket, so
        # the serial predict_logits path allocates nothing per dispatch
        # (one slot suffices — the result is read back before the next
        # chunk stages, so the buffer is always free again by then).
        self._staging = StagingPool(self.buckets, INPUT_SHAPE, slots=1)

    # -- construction helpers ------------------------------------------------

    @classmethod
    def from_checkpoint(cls, path: str, **kwargs) -> "InferenceEngine":
        """Load either checkpoint surface (``--save-model`` torch/npz file
        or a ``--save-state`` archive) and build the engine around it."""
        from ..utils.checkpoint import load_inference_variables

        return cls(load_inference_variables(path), **kwargs)

    @classmethod
    def from_seed(cls, seed: int = 1, **kwargs) -> "InferenceEngine":
        """Fresh reference-init params (utils/rng stream layout) — the
        no-checkpoint path used by ``--warmup-only`` smoke runs and load
        tests, where serving mechanics matter and weights don't."""
        from ..utils.rng import root_key, split_streams

        key = split_streams(root_key(seed))["init"]
        return cls({"params": init_params(key)}, **kwargs)

    # -- lifecycle ------------------------------------------------------------

    def compile_count(self) -> int:
        """Distinct traces of the forward so far (== warmed buckets once
        warmup has run; the /metrics ``compiles`` field)."""
        return self._predict.trace_count()

    def warmup(
        self,
        on_bucket=None,
        parallel: bool = True,
        max_workers: int | None = None,
        sink=None,
    ) -> list[tuple[int, int]]:
        """Compile every bucket exactly once; verify the second pass hits.

        ``parallel=True`` (the default) fans the ladder out over a
        :class:`~..compile.CompileService` thread pool: XLA compilation
        releases the GIL and jit's caches are thread-safe, so N buckets
        compile in the wall time of the slowest one instead of the sum —
        the startup win the fake-compiler structural test pins
        (tests/test_compile.py).  The RecompileSentinel budget is
        untouched: concurrent or not, warmup produces exactly
        ``len(buckets)`` traces, and the serial verification sweep below
        proves every rung is a cache hit afterwards.

        Returns ``[(bucket, cumulative_trace_count), ...]`` in ladder
        order.  Serially the counts step up one per rung; under parallel
        warmup each entry records the trace count observed when THAT
        bucket finished (concurrent completions may see later counts) —
        monotonicity per rung is no longer meaningful, the invariant is
        the final count.  ``on_bucket(bucket, traces)`` fires as each
        bucket finishes compiling — from worker threads in parallel mode
        — so callers can report progress DURING the slow phase (a TPU
        ladder is tens of seconds per rung; silence until the end reads
        as a hang).  A second sweep over the ladder must add zero
        traces; the sentinel raises otherwise, and a final count check
        catches the inverse failure (two buckets aliasing to one
        executable would silently under-warm).

        ``sink`` (obs event sink) receives the per-bucket ``compile``
        spans from the service, so JSONL telemetry shows which rung took
        how long (`tools/perf_report.py --telemetry` "startup compiles").
        """
        registry = self.metrics.registry if self.metrics is not None else None
        done: dict[int, int] = {}

        def warm_one(b: int) -> None:
            self._predict(self._variables, np.zeros((b, *INPUT_SHAPE), np.float32))
            traces = self._predict.trace_count()
            done[b] = traces
            if on_bucket is not None:
                on_bucket(b, traces)

        if parallel and len(self.buckets) > 1:
            from ..compile import CompileService

            with CompileService(
                max_workers=min(len(self.buckets), max_workers or 8),
                registry=registry,
                sink=sink,
            ) as svc:
                for b in self.buckets:
                    svc.submit(f"predict_step[{b}]", warm_one, b)
                svc.wait_all()
        else:
            # The opt-in serial fallback (parallel=False): deterministic
            # rung-by-rung compile order for debugging ladder issues.
            for b in self.buckets:
                warm_one(b)
        report = [(b, done[b]) for b in self.buckets]
        for b in self.buckets:
            self._predict(self._variables, np.zeros((b, *INPUT_SHAPE), np.float32))  # jaxlint: disable=JL010 -- verification sweep, not warmup: every call here MUST be a cache hit (the sentinel raises otherwise), so there is nothing to parallelize
        if self._predict.trace_count() != len(self.buckets):
            raise RecompileError(
                f"warmup traced {self._predict.trace_count()} executables "
                f"for {len(self.buckets)} buckets {self.buckets}; the "
                "bucket ladder does not map 1:1 onto compiled programs"
            )
        self.warmed = True
        return report

    # -- serving --------------------------------------------------------------

    def launch(self, staged: np.ndarray, n: int):
        """Dispatch one already-bucket-shaped batch WITHOUT reading back.

        ``staged`` must be exactly a warmed bucket shape (the batcher and
        :meth:`predict_logits` stage through a :class:`StagingPool`, so
        jit only ever sees bucket shapes) and carry ``n`` live rows at
        the front.  Returns the on-device ``[bucket, 10]`` log-probs —
        jax's async dispatch means this does NOT wait for the compute, so
        the caller can overlap host work (padding the next batch) with
        device execution and read the result later with ``np.asarray``.
        """
        bucket = len(staged)
        if bucket not in self.buckets:
            raise ValueError(
                f"staged batch of {bucket} rows is not a warmed bucket "
                f"{self.buckets}; stage through StagingPool/bucket_for"
            )
        if not 1 <= n <= bucket:
            raise ValueError(f"live rows {n} outside [1, {bucket}]")
        logits = self._predict(self._variables, staged)
        if self.metrics is not None:
            self.metrics.record_batch(n, bucket)
        return logits

    def predict_logits(self, x: np.ndarray) -> np.ndarray:
        """``[n, 28, 28, 1]`` normalized float32 -> ``[n, 10]`` log-probs.

        Pads into the engine's preallocated staging buffers (zero-alloc
        steady state), dispatches, slices padding back off.  ``n`` above
        the top bucket is chunked (direct callers only — the batcher
        never coalesces past the top bucket).  Serial by design: each
        chunk's result is read before the next stages; the overlapped
        path is the pipelined batcher (serving/batcher.py).
        """
        x = np.asarray(x, np.float32)
        if x.ndim != 1 + len(INPUT_SHAPE) or x.shape[1:] != INPUT_SHAPE:
            raise ValueError(
                f"expected [n, {', '.join(map(str, INPUT_SHAPE))}] input, "
                f"got shape {x.shape}"
            )
        n = len(x)
        if n == 0:
            raise ValueError("empty batch")
        top = self.buckets[-1]
        outs = []
        for start in range(0, n, top):
            chunk = x[start : start + top]
            staged, bucket = self._staging.stage([chunk])
            try:
                logits = self.launch(staged, len(chunk))
                outs.append(np.asarray(logits)[: len(chunk)])  # jaxlint: disable=JL009 -- serial direct-call path: each chunk is read inline by contract; the overlapped read lives in the batcher's completion worker
            finally:
                self._staging.release(staged, bucket)
        out = outs[0] if len(outs) == 1 else np.concatenate(outs)
        assert out.shape == (n, NUM_CLASSES)
        return out
