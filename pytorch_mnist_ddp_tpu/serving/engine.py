"""The inference engine: checkpoint -> warmed bucket executables -> logits.

Lifecycle: construct (variables placed replicated on the data-parallel
mesh), :meth:`warmup` (compile every bucket of every dtype variant
exactly once, then verify a second pass is pure cache hits),
:meth:`verify_parity` (gate reduced-precision variants against f32),
then :meth:`launch`/:meth:`predict_logits` from the dispatch thread.
Each variant's jitted forward is wrapped in a RecompileSentinel budgeted
at exactly ``len(buckets)`` traces, so ANY post-warmup shape leak — the
silent per-request compile stall this subsystem exists to prevent —
raises ``RecompileError`` with a pointed message instead of serving at
1000x latency.

Reduced-precision variants (docs/SERVING.md): ``dtypes=("bf16",)`` /
``("int8",)`` add serving paths beside the default f32 forward — bf16
casts activations/matmuls to the MXU's native width (params stay f32,
models/net.py), int8 serves per-channel-quantized weights with int8
GEMMs (models/quant.py).  A variant is REFUSED until its parity gate
passes: logit tolerance + argmax-identical vs f32 on a fixed eval
slice, mirroring the ``--bf16`` trainer discipline.  Per-dtype
executables can persist through the PR-5 :class:`~..compile.aot.
ExecutableStore` (``aot_cache``): dtype and bucket join the config
digest, so variants get distinct entries that hit on warm start.

Device staging (``device_stage``, on by default on single-process
meshes): padded batches are committed to the mesh's data-axis sharding
with an async ``jax.device_put`` before the forward launches, so the
H2D transfer rides under the dispatch thread's next host work instead
of stalling inside the jit call — the steady-state overlap discipline
of data/prefetch.py applied to serving.  Staging is consistent across
warmup, parity, and dispatch (committed vs uncommitted inputs key
different jit cache entries; mixing them would blow the sentinel
budget).

Threading contract: jax dispatch is not guarded here; exactly one thread
(the micro-batcher dispatch worker, or the caller in direct use) may
call ``launch``/``predict_logits``.  Reading a previously launched
batch's result (``np.asarray`` on the returned device array) is safe
from a second thread — that is the batcher's completion worker, which
overlaps D2H + unsplitting with the next batch's pad + dispatch.  The
HTTP handler threads never touch the engine — they talk to the
batcher's queue.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np

from ..analysis.sentinel import RecompileError, RecompileSentinel
from ..models.net import INPUT_SHAPE, NUM_CLASSES, init_params, init_variables
from ..parallel.ddp import (
    make_int8_predict_step,
    make_packed_int8_predict_step,
    make_packed_predict_step,
    make_predict_step,
    replicate_params,
)
from ..parallel.mesh import DATA_AXIS, SHARD_KINDS, make_mesh
from .buckets import (
    StagingPool,
    packed_capacities,
    pow2_buckets,
    validate_buckets,
)
from .metrics import ServingMetrics

# The default (reference-precision) variant every engine serves.
DEFAULT_DTYPE = "f32"

# Separator between a dtype and a pinned model version in a variant key
# ("f32@v2"): the registry/rollout tier (serving/registry.py) installs a
# canary version's weights as parallel variants under these keys, so the
# batcher coalesces canary traffic separately and a batch is NEVER mixed
# across versions.  Client-facing "dtype" fields must not contain it
# (the server rejects them); only the rollout controller mints keys.
VERSION_SEP = "@"

# Reduced-precision variants an engine can additionally serve; each must
# pass its parity gate before a single request is dispatched to it.
VARIANT_DTYPES = ("bf16", "int8")

# Parity-gate logit tolerances (max |variant - f32| over the eval slice,
# log-prob units).  Measured headroom on this repo's CNN: bf16 lands
# ~1.5e-3, int8 (per-channel weights + per-row activations) ~4e-3 — the
# gates are 50-100x above the expected error, but far below the ~1.0
# log-prob scale where a wrong model would hide.  argmax-identity is the
# sharp edge either way.
PARITY_TOL = {"bf16": 0.25, "int8": 1.0}

# Rows in the fixed parity slice (padded up/down to a warmed bucket at
# gate time).  Deterministic seed: the gate must be reproducible — a
# variant that passes once passes every restart of the same weights.
PARITY_ROWS = 64
PARITY_SEED = 20260803


def weights_digest(variables) -> str:
    """Deterministic content hash of a variable tree (host pass, done
    once at engine construction).  Leaf order is jax's tree-flatten
    order (sorted dict keys — stable across processes), and each leaf
    contributes its shape/dtype tag plus raw bytes, so two trees hash
    equal iff they would serve identical logits."""
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for leaf in jax.tree_util.tree_leaves(variables):
        arr = np.asarray(leaf)
        h.update(f"{arr.shape}{arr.dtype}".encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


class UnverifiedVariantError(RuntimeError):
    """A reduced-precision variant was asked to serve before (or after
    failing) its parity gate — the refusal contract, docs/SERVING.md."""


class ParityError(AssertionError):
    """verify_parity(raise_on_failure=True) found a failing variant."""


class _Variant:
    """One served dtype: its jitted forward (sentinel-wrapped), its
    variable tree, its gate state, and its per-bucket
    :class:`~..compile.Program` grid (the unified compile/AOT/dispatch
    artifact — one Program per rung, sharing this variant's jit fn and
    sentinel budget)."""

    __slots__ = ("name", "jit_fn", "predict", "variables", "verified",
                 "parity", "programs", "aot")

    def __init__(self, name, jit_fn, predict, variables, verified=False):
        self.name = name
        self.jit_fn = jit_fn
        self.predict = predict
        self.variables = variables
        self.verified = verified
        self.parity: dict | None = None
        self.programs: dict[int, Any] = {}
        self.aot = False


class InferenceEngine:
    """Bucket-warmed forward over a data-parallel mesh.

    Parameters
    ----------
    variables:
        Flax variable dict — ``{"params": ...}`` plus ``{"batch_stats":
        ...}`` for BN-bearing checkpoints (``use_bn`` is inferred from
        the tree, never guessed from flags, so a ``--syncbn`` checkpoint
        serves correctly without the operator knowing it was one).
    mesh:
        The data-parallel mesh to dispatch on; defaults to every visible
        device on the ``data`` axis (parallel/mesh.make_mesh).
    buckets:
        Batch-size ladder to warm; defaults to the power-of-two ladder
        from the data-axis size up to ``max_bucket``.  Validated against
        the mesh (every bucket must shard evenly).
    dtypes:
        Extra reduced-precision variants to serve beside the f32
        default (subset of :data:`VARIANT_DTYPES`); each warms its own
        ladder under its own sentinel and is gated by
        :meth:`verify_parity` before it may serve.
    aot_cache:
        Directory for serialized per-(dtype, bucket) executables
        (compile/aot.ExecutableStore), or an already-constructed
        ``ExecutableStore`` to share (the replica pool passes one store
        to every engine); a warm start deserializes every rung instead
        of tracing.  Omitted = plain jit + sentinel.
    device_stage:
        Commit inputs to the data-axis sharding with an async
        ``device_put`` before dispatch.  Default (None) = auto: on when
        every mesh device is process-local, off otherwise.
    metrics:
        Optional :class:`ServingMetrics`; per-dispatch occupancy is
        recorded when present.
    packed:
        Packed ragged batching (docs/SERVING.md): collapse the pow2
        ladder to the rows-capacity ladder (serving/buckets.py
        ``packed_capacities``) and serve the segment-aware forward —
        requests concatenate into one dense rows buffer plus a
        segment-id vector instead of padding each batch to its own
        rung.  ``self.buckets`` then IS the capacity ladder, so
        staging, sentinel budgets, AOT store sizing, and metrics all
        see the collapsed grid through the existing surface.
    int8_impl:
        Dense-head implementation for the int8 variant: ``"dot"``
        (reference) or ``"pallas"`` (ops/pallas_infer.py fused kernel).
        Pallas on a backend without a real lowering falls back to
        ``"dot"`` with a warning BEFORE any AOT key is composed, so
        the persisted config always names the impl that ran.
    shard_kind:
        Replica shard topology (parallel/mesh.SHARD_KINDS).  The default
        ``"dp"`` is the classic 1-device-per-replica data-parallel
        engine, byte-for-byte unchanged.  ``"tp"``/``"vtp"``/``"ep"``/
        ``"pp"`` make THIS engine one logical replica spanning a
        k-device mesh (serving/sharded.py): tensor-parallel CNN,
        tensor-parallel ViT, expert-parallel MoE, 2-stage pipeline.
        Sharded kinds require an explicit ``mesh`` (replica_mesh),
        serve f32 only (``dtypes`` must be empty — the parity anchor is
        the SINGLE-DEVICE f32 forward), refuse BN trees and non-default
        conv impls, and start UNVERIFIED: :meth:`verify_sharded_parity`
        must pass before :meth:`launch` will serve a request.
    vit_cfg:
        Model config for the ``vtp``/``ep`` families (defaults per
        serving/sharded.py — note EP's serving default holds
        capacity-factor headroom so routing never drops tokens).
    pp_microbatches:
        Pipeline microbatch count (``pp`` only); every bucket must
        divide by it.
    """

    def __init__(
        self,
        variables: dict[str, Any],
        mesh=None,
        buckets: Sequence[int] | None = None,
        max_bucket: int | None = None,
        compute_dtype=None,
        conv_impl: str = "conv",
        metrics: ServingMetrics | None = None,
        dtypes: Sequence[str] | None = None,
        aot_cache: str | None = None,
        device_stage: bool | None = None,
        version: str = "",
        packed: bool = False,
        int8_impl: str = "dot",
        shard_kind: str = "dp",
        vit_cfg=None,
        pp_microbatches: int = 2,
    ):
        # The model-registry version identity of the served weights
        # ("" = the unversioned single-checkpoint path, which keeps the
        # canonical predict_config digest — and therefore cross-surface
        # AOT reuse with the trainer handoff — exactly as before).
        self.version = str(version)
        self.shard_kind = str(shard_kind)
        if self.shard_kind not in SHARD_KINDS:
            raise ValueError(
                f"unknown shard kind {self.shard_kind!r}; have {SHARD_KINDS}"
            )
        is_sharded = self.shard_kind != "dp"
        if is_sharded and mesh is None:
            raise ValueError(
                f"shard kind {self.shard_kind!r} needs an explicit replica "
                "mesh (parallel.mesh.replica_mesh); defaulting to the "
                "every-device DP mesh would silently serve the wrong "
                "topology"
            )
        self.mesh = mesh if mesh is not None else make_mesh()
        n_shards = self.mesh.shape[DATA_AXIS]
        if buckets is None:
            from .buckets import DEFAULT_MAX_BUCKET

            buckets = pow2_buckets(n_shards, max_bucket or DEFAULT_MAX_BUCKET)
        elif max_bucket is not None:
            raise ValueError("pass buckets or max_bucket, not both")
        self.buckets = validate_buckets(buckets, n_shards)
        self.packed = bool(packed)
        if self.packed:
            # The packed grid: one (or two) rows-capacities instead of a
            # rung per pow2.  Idempotent, so the pool can pre-resolve
            # capacities for store sizing and pass them back in here.
            self.buckets = packed_capacities(self.buckets[-1], n_shards)
        self.pp_microbatches = int(pp_microbatches)
        if self.shard_kind == "pp":
            if self.pp_microbatches < 1:
                raise ValueError(
                    f"pp_microbatches must be >= 1, got {self.pp_microbatches}"
                )
            bad = [b for b in self.buckets if b % self.pp_microbatches]
            if bad:
                raise ValueError(
                    f"buckets {bad} do not divide by {self.pp_microbatches} "
                    "pipeline microbatches; every warmed rung must split "
                    "evenly into the microbatch schedule"
                )
        if int8_impl not in ("dot", "pallas"):
            raise ValueError(
                f"unknown int8 impl {int8_impl!r} (want dot|pallas)"
            )
        if int8_impl == "pallas":
            from ..ops.pallas_infer import pallas_infer_active

            if not pallas_infer_active(True):
                import warnings

                warnings.warn(
                    "--int8-impl pallas requested on backend "
                    f"{jax.default_backend()!r}, which has no real Pallas "
                    "lowering; serving the reference dot-general head "
                    "instead (set TPU_MNIST_PALLAS_INTERPRET=1 to force "
                    "interpret mode for testing)",
                    stacklevel=2,
                )
                int8_impl = "dot"
        self.int8_impl = int8_impl
        self.use_bn = "bn1" in variables.get("params", {})
        self._vit_cfg = None
        if is_sharded:
            from . import sharded as shardlib

            if dtypes:
                raise ValueError(
                    f"sharded replicas serve f32 only; dtypes="
                    f"{tuple(dtypes)} cannot ride shard kind "
                    f"{self.shard_kind!r} (the parity anchor is the "
                    "single-device f32 forward; mix precisions at the "
                    "POOL level with heterogeneous replicas instead)"
                )
            if self.use_bn:
                raise ValueError(
                    f"shard kind {self.shard_kind!r} has no BN-aware "
                    "sharded forward; serve BN checkpoints on DP replicas"
                )
            if conv_impl != "conv":
                raise ValueError(
                    f"shard kind {self.shard_kind!r} serves the reference "
                    f"conv impl only; got conv_impl={conv_impl!r}"
                )
            if compute_dtype is not None and (
                jax.numpy.dtype(compute_dtype)
                != jax.numpy.dtype(jax.numpy.float32)
            ):
                raise ValueError(
                    "sharded replicas serve f32 only; drop compute_dtype"
                )
            shardlib.validate_family(self.shard_kind, variables["params"])
            if self.shard_kind in ("vtp", "ep"):
                self._vit_cfg = (
                    vit_cfg if vit_cfg is not None
                    else shardlib.default_vit_cfg(self.shard_kind)
                )
        if self.use_bn and "batch_stats" not in variables:
            # A BN model without running averages would eval-normalize by
            # garbage; init defaults (mean 0 / var 1) are torch's
            # never-trained behavior and at least well-defined.
            variables = dict(variables)
            variables["batch_stats"] = init_variables(
                jax.random.PRNGKey(0), use_bn=True
            )["batch_stats"]
        served = (
            {"params": variables["params"],
             "batch_stats": variables["batch_stats"]}
            if self.use_bn
            else variables["params"]
        )
        if dtypes and compute_dtype is not None and (
            jax.numpy.dtype(compute_dtype) != jax.numpy.dtype(jax.numpy.float32)
        ):
            # The parity gates compare variants against THE DEFAULT
            # variant as their f32 reference; a reduced-precision default
            # (legacy --bf16) would silently gate bf16 against itself
            # and int8 against a bf16-skewed anchor while still claiming
            # "parity vs f32".
            raise ValueError(
                "a non-f32 default compute_dtype cannot anchor the "
                "variants' parity gates; drop the legacy --bf16 flag and "
                "request the reduced-precision path via dtypes=('bf16',) "
                "instead"
            )
        self._conv_impl = conv_impl
        # Content address of the served weights (the response cache's
        # model-digest key component, serving/cache.py): hashed from the
        # HOST-side tree before placement, so it costs one pass at
        # construction and a swapped engine — new checkpoint, new seed,
        # retrained weights — necessarily changes it, making every old
        # cache entry unreachable without an explicit invalidation hook.
        self.weights_digest = weights_digest(served)
        # Host-side copy of the served tree: the sharded parity gate's
        # single-device reference forward reads it (the placed tree's
        # leaves live sharded across the replica mesh).
        self._host_served = served
        if is_sharded:
            self._variables = shardlib.place_params(
                self.shard_kind, served, self.mesh, self._vit_cfg
            )
        else:
            self._variables = replicate_params(served, self.mesh)
        self.metrics = metrics
        registry = metrics.registry if metrics is not None else None
        if device_stage is None:
            # Auto: committed placement needs every device addressable
            # from this process (same gate as ddp.replicate_params).
            device_stage = all(
                d.process_index == jax.process_index()
                for d in self.mesh.devices.flat
            )
        self.device_stage = bool(device_stage)
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._input_sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        if is_sharded:
            # The kind's shard_map forward (serving/sharded.py): inputs
            # ride the data axis (size 1 on tp/vtp/pp replicas, k on
            # ep), params are placed per the kind's specs above.
            fn = shardlib.build_predict_fn(
                self.shard_kind,
                self.mesh,
                vit_cfg=self._vit_cfg,
                pp_microbatches=self.pp_microbatches,
                packed=self.packed,
            )
        else:
            make_default = (
                make_packed_predict_step if self.packed else make_predict_step
            )
            fn = make_default(
                self.mesh,
                compute_dtype=compute_dtype or jax.numpy.float32,
                use_bn=self.use_bn,
                conv_impl=conv_impl,
            )
        # One trace per bucket per variant, ever.  A post-warmup retrace
        # means a request shape escaped the bucket policy.  Compile
        # events land on the shared registry (jax_compiles_total{fn=
        # "predict_step"} / {fn="predict_step_bf16"} ...) so /metrics
        # exposes the counts Prometheus-side too.
        self._predict = RecompileSentinel(
            fn,
            max_traces=len(self.buckets),
            name="predict_step",
            registry=registry,
        )
        # The DP default (reference-precision) variant serves unverified
        # by definition: it IS the parity reference.  A SHARDED default
        # is the opposite — it starts refused, and only
        # verify_sharded_parity (vs the single-device forward) may flip
        # it servable: the same gate discipline the dtype variants get,
        # applied to the shard topology.
        self._variants: dict[str, _Variant] = {
            DEFAULT_DTYPE: _Variant(
                DEFAULT_DTYPE, fn, self._predict, self._variables,
                verified=not is_sharded,
            )
        }
        # EP expert-load plumbing: each dispatch returns (logp, load);
        # the load is stashed and the PREVIOUS one is read back on the
        # next dispatch (one-batch lag — an immediate np.asarray would
        # sync the dispatch thread against its own batch).
        self._pending_expert_load = None
        self._reference_fn = None
        for name in dtypes or ():
            if name == DEFAULT_DTYPE or name in self._variants:
                continue
            self._variants[name] = self._build_variant(
                name, variables, registry
            )
        self._aot_store = None
        if aot_cache:
            from ..compile import ExecutableStore, predict_store_size

            if isinstance(aot_cache, ExecutableStore):
                # Pool mode (serving/pool.py): N replicas share ONE
                # store object over one directory, sized by the pool for
                # the full replicas x dtypes x buckets grid.  The store
                # is concurrent-writer safe (compile/aot.py), so the
                # replicas' warmups may populate it in parallel.
                self._aot_store = aot_cache
            else:
                self._aot_store = ExecutableStore(
                    aot_cache,
                    registry=registry,
                    # Hold the whole dtype x bucket grid plus headroom for
                    # one config change; the default bound would prune
                    # mid-grid.
                    max_entries=predict_store_size(
                        1, len(self._variants), len(self.buckets)
                    ),
                )
            for v in self._variants.values():
                v.aot = True
        self.warmed = False
        # Direct-call staging: one preallocated pad target per bucket, so
        # the serial predict_logits path allocates nothing per dispatch
        # (one slot suffices — the result is read back before the next
        # chunk stages, so the buffer is always free again by then).
        self._staging = StagingPool(self.buckets, INPUT_SHAPE, slots=1)

    def _build_variant(self, name: str, variables, registry) -> _Variant:
        if name == "bf16":
            make_fn = (
                make_packed_predict_step if self.packed else make_predict_step
            )
            fn = make_fn(
                self.mesh,
                compute_dtype=jax.numpy.bfloat16,
                use_bn=self.use_bn,
                conv_impl=self._conv_impl,
            )
            placed = self._variables
        elif name == "int8":
            from ..models.quant import quantize_params

            if self.use_bn:
                raise ValueError(
                    "int8 variant does not support BatchNorm checkpoints; "
                    "serve BN checkpoints at f32 or bf16"
                )
            make_fn = (
                make_packed_int8_predict_step
                if self.packed
                else make_int8_predict_step
            )
            fn = make_fn(self.mesh, int8_impl=self.int8_impl)
            placed = replicate_params(
                quantize_params(jax.device_get(variables["params"])),
                self.mesh,
            )
        else:
            raise ValueError(
                f"unknown serving dtype {name!r}; have "
                f"{(DEFAULT_DTYPE, *VARIANT_DTYPES)}"
            )
        sentinel = RecompileSentinel(
            fn,
            max_traces=len(self.buckets),
            name=f"predict_step_{name}",
            registry=registry,
        )
        return _Variant(name, fn, sentinel, placed)

    # -- construction helpers ------------------------------------------------

    @classmethod
    def from_checkpoint(cls, path: str, **kwargs) -> "InferenceEngine":
        """Load either checkpoint surface (``--save-model`` torch/npz file
        or a ``--save-state`` archive) and build the engine around it."""
        from ..utils.checkpoint import load_inference_variables

        return cls(load_inference_variables(path), **kwargs)

    @classmethod
    def from_seed(cls, seed: int = 1, **kwargs) -> "InferenceEngine":
        """Fresh reference-init params (utils/rng stream layout) — the
        no-checkpoint path used by ``--warmup-only`` smoke runs and load
        tests, where serving mechanics matter and weights don't.
        Family-aware: a sharded ``shard_kind`` seeds the model family it
        serves (ViT for vtp, MoE-ViT for ep, the CNN otherwise)."""
        from ..utils.rng import root_key, split_streams

        key = split_streams(root_key(seed))["init"]
        kind = kwargs.get("shard_kind", "dp")
        if kind != "dp":
            from . import sharded as shardlib

            if kind in ("vtp", "ep") and kwargs.get("vit_cfg") is None:
                kwargs["vit_cfg"] = shardlib.default_vit_cfg(kind)
            params = shardlib.seed_params(kind, key, kwargs.get("vit_cfg"))
            return cls({"params": params}, **kwargs)
        return cls({"params": init_params(key)}, **kwargs)

    # -- variant surface ------------------------------------------------------

    @property
    def dtypes(self) -> tuple[str, ...]:
        """Served dtype names, default first."""
        return tuple(self._variants)

    @property
    def default_dtype(self) -> str:
        return DEFAULT_DTYPE

    def variant_verified(self, dtype: str | None) -> bool:
        v = self._variants.get(dtype or DEFAULT_DTYPE)
        return v is not None and v.verified

    @property
    def parity_report(self) -> dict[str, dict]:
        """Per-variant gate results recorded by :meth:`verify_parity`."""
        return {
            v.name: v.parity
            for v in self._variants.values()
            if v.parity is not None
        }

    def _variant_for(self, dtype: str | None) -> _Variant:
        name = dtype or DEFAULT_DTYPE
        v = self._variants.get(name)
        if v is None:
            raise ValueError(
                f"dtype {name!r} is not served; have {list(self._variants)}"
            )
        return v

    # -- lifecycle ------------------------------------------------------------

    def compile_count(self) -> int:
        """Distinct traces of the forward across every variant (== warmed
        buckets x variants once warmup has run in jit mode, 0 in AOT
        mode where executables deserialize; the /metrics ``compiles``
        field).  Version-pinned canary variants SHARE their base
        variant's sentinel (a canary install adds zero traces), so the
        sum deduplicates by sentinel identity instead of double-counting
        a shared budget."""
        seen: set[int] = set()
        total = 0
        for v in self._variants.values():
            if id(v.predict) in seen:
                continue
            seen.add(id(v.predict))
            total += v.predict.trace_count()
        return total

    def _stage(self, staged):
        """Commit a padded host batch to the data-axis sharding (async
        H2D) — the serving leg of the steady-state prefetch discipline.
        Identity when device staging is off or the caller pre-staged."""
        if not self.device_stage or not isinstance(staged, np.ndarray):
            return staged
        return jax.device_put(staged, self._input_sharding)

    def _stage_seg(self, seg):
        """The segment-id leg of :meth:`_stage` (packed mode): commit
        the int32 vector to the same data-axis sharding as the rows
        buffer, so seg values shard row-aligned with their rows."""
        if not self.device_stage or not isinstance(seg, np.ndarray):
            return seg
        return jax.device_put(seg, self._input_sharding)

    def _run_variant(self, v: _Variant, staged, seg=None):
        """Dispatch one bucket-shaped batch on a variant, bypassing the
        verified gate (warmup and the parity gate itself come through
        here; request traffic goes through :meth:`launch`).  Steady
        state is ``Program.call`` — the executable fast path in AOT
        mode, the sentinel-guarded jit wrapper otherwise.

        Packed mode takes the segment-id vector as a third arg;
        ``seg=None`` (warmup sweeps, parity slices, direct calls)
        synthesizes the all-live vector — every row segment 0 — which
        masks nothing, so those paths see exactly the bucketed
        semantics.

        EP dispatches return ``(logp, expert_load)``; the load array is
        stashed on-device and the PREVIOUS dispatch's (already
        materialized by then) is read into the expert-load gauges — the
        one-batch lag keeps ``np.asarray`` off the dispatch hot path."""
        staged = self._stage(staged)
        if self.packed:
            if seg is None:
                seg = np.zeros(len(staged), np.int32)
            seg = self._stage_seg(seg)
            prog = v.programs.get(len(staged))
            if prog is not None:
                out = prog.call(v.variables, staged, seg)
            else:
                out = v.predict(v.variables, staged, seg)
        else:
            prog = v.programs.get(len(staged))
            if prog is not None:
                out = prog.call(v.variables, staged)
            else:
                out = v.predict(v.variables, staged)
        if self.shard_kind == "ep":
            out, load = out
            prev, self._pending_expert_load = self._pending_expert_load, load
            if prev is not None and self.metrics is not None:
                self.metrics.record_expert_load(np.asarray(prev))
        return out

    def flush_expert_load(self) -> None:
        """Materialize the stashed (one-batch-lagged) EP expert-load
        array into the gauges — drain/shutdown hook so the LAST batch's
        routing isn't lost to the lag."""
        prev, self._pending_expert_load = self._pending_expert_load, None
        if prev is not None and self.metrics is not None:
            self.metrics.record_expert_load(np.asarray(prev))

    def _program_for(self, v: _Variant, b: int):
        """The (variant, bucket) rung as a :class:`~..compile.Program`:
        shared jit fn + sentinel budget, canonical
        :func:`~..compile.predict_config` AOT key (concrete device ids
        included — serialized executables pin their compile-time
        devices, so two replicas' same-shape meshes on different
        devices never alias one entry), staged example input."""
        prog = v.programs.get(b)
        if prog is None:
            from ..compile import Program, predict_config

            base_dtype = v.name.split(VERSION_SEP)[0]
            prog = Program(
                f"predict_step[{v.name}][{b}]",
                v.jit_fn,
                sentinel=None if v.aot else v.predict,
                example_args=lambda: (
                    v.variables,
                    self._stage(np.zeros((b, *INPUT_SHAPE), np.float32)),
                    *(
                        (self._stage_seg(np.zeros(b, np.int32)),)
                        if self.packed
                        else ()
                    ),
                ),
                config=predict_config(
                    self.mesh, base_dtype, b,
                    use_bn=self.use_bn,
                    conv_impl=self._conv_impl,
                    device_stage=self.device_stage,
                    packed=self.packed,
                    # Keys a sharded rung's executable apart from every
                    # DP rung (with the mesh-shape/device fields) so a
                    # warm start never deserializes the wrong topology.
                    shard_kind=self.shard_kind,
                    # Only the int8 forward has a head impl choice; f32/
                    # bf16 keep the default key so their digests are
                    # impl-independent.
                    int8_impl=(
                        self.int8_impl if base_dtype == "int8" else "dot"
                    ),
                    # A version-pinned variant ("f32@v2") keys the store
                    # under ITS version; the primary keys under the
                    # engine's ("" on the unversioned path — digest
                    # compatibility with the trainer handoff).
                    version=(
                        v.name.split(VERSION_SEP, 1)[1]
                        if VERSION_SEP in v.name else self.version
                    ),
                ),
                store=self._aot_store if v.aot else None,
            )
            v.programs[b] = prog
        return prog

    def _warm_one(self, v: _Variant, b: int) -> None:
        self._program_for(v, b).build()

    def warmup(
        self,
        on_bucket=None,
        parallel: bool = True,
        max_workers: int | None = None,
        sink=None,
        on_rung=None,
    ) -> list[tuple[int, int]]:
        """Compile every (variant, bucket) exactly once; verify the
        second pass hits.

        ``parallel=True`` (the default) fans the full dtype x bucket
        grid out over a :class:`~..compile.CompileService` thread pool:
        XLA compilation releases the GIL and jit's caches are
        thread-safe, so N programs compile in the wall time of the
        slowest one instead of the sum — the startup win the
        fake-compiler structural test pins (tests/test_compile.py).
        Each variant's RecompileSentinel budget is untouched: concurrent
        or not, warmup produces exactly ``len(buckets)`` traces per
        variant, and the serial verification sweep below proves every
        rung is a cache hit afterwards.  With an ``aot_cache``, each
        rung instead loads-or-compiles a serialized executable keyed by
        (dtype, bucket, config) — a warm start is pure deserialize,
        zero traces.

        Returns ``[(bucket, cumulative_trace_count), ...]`` for the
        DEFAULT variant in ladder order (the PR-2 report surface).
        ``on_bucket(bucket, traces)`` fires as each default-variant rung
        finishes; ``on_rung(dtype, bucket, total_compiles)`` fires for
        EVERY rung of every variant — from worker threads in parallel
        mode — so callers can report progress DURING the slow phase.
        ``sink`` (obs event sink) receives the per-rung ``compile``
        spans from the service.
        """
        registry = self.metrics.registry if self.metrics is not None else None
        done: dict[int, int] = {}

        def warm_one(vname: str, b: int) -> None:
            v = self._variants[vname]
            self._warm_one(v, b)
            if vname == DEFAULT_DTYPE:
                traces = self._predict.trace_count()
                done[b] = traces
                if on_bucket is not None:
                    on_bucket(b, traces)
            if on_rung is not None:
                on_rung(vname, b, self.compile_count())

        jobs = [
            (vname, b) for vname in self._variants for b in self.buckets
        ]
        # Even a single job rides the service (a packed engine's
        # collapsed ladder is exactly one rung per variant): the service
        # is where compile spans and the compile_seconds counters are
        # emitted, and a spanless warmup would make the packed rung
        # invisible to perf_report's device-path section.
        if parallel and jobs:
            from ..compile import CompileService

            with CompileService(
                max_workers=min(len(jobs), max_workers or 8),
                registry=registry,
                sink=sink,
            ) as svc:
                for vname, b in jobs:
                    label = (
                        f"predict_step[{b}]"
                        if vname == DEFAULT_DTYPE
                        else f"predict_step[{vname}][{b}]"
                    )
                    svc.submit(label, warm_one, vname, b)
                svc.wait_all()
        else:
            # The opt-in serial fallback (parallel=False): deterministic
            # rung-by-rung compile order for debugging ladder issues.
            for vname, b in jobs:
                warm_one(vname, b)
        report = [(b, done[b]) for b in self.buckets]
        for v in self._variants.values():
            if v.aot:
                missing = [
                    b for b in self.buckets
                    if b not in v.programs or not v.programs[b].built
                ]
                if missing:
                    raise RecompileError(
                        f"AOT warmup left {v.name} buckets {missing} "
                        "without executables"
                    )
                continue
            for b in self.buckets:
                self._run_variant(v, np.zeros((b, *INPUT_SHAPE), np.float32))  # jaxlint: disable=JL010 -- verification sweep, not warmup: every call here MUST be a cache hit (the sentinel raises otherwise), so there is nothing to parallelize
            if v.predict.trace_count() != len(self.buckets):
                raise RecompileError(
                    f"warmup traced {v.predict.trace_count()} executables "
                    f"for {len(self.buckets)} buckets {self.buckets} of "
                    f"variant {v.name!r}; the bucket ladder does not map "
                    "1:1 onto compiled programs"
                )
        # The verification sweep's all-zero batches routed SOMEWHERE;
        # don't let that synthetic load leak into the gauges on the
        # first real dispatch (the one-batch lag would surface it).
        self._pending_expert_load = None
        self.warmed = True
        return report

    # -- parity gates ----------------------------------------------------------

    def verify_parity(
        self,
        tol: dict[str, float] | None = None,
        raise_on_failure: bool = False,
        sink=None,
    ) -> dict[str, dict]:
        """Gate every reduced-precision variant against the f32 forward.

        A fixed, seeded eval slice (raw pixels through the training
        normalize — the distribution the model serves) is dispatched at
        an already-warmed bucket shape on the reference variant and on
        each unverified one; a variant passes iff

        - ``max |logit_variant - logit_f32| <= tol[dtype]``
          (:data:`PARITY_TOL` defaults), AND
        - argmax is identical on EVERY row.

        Passing marks the variant servable; failing leaves it refused
        (``launch``/``submit`` raise).  Zero new traces: the gate rides
        warmed bucket shapes only.  Returns (and records on
        :attr:`parity_report`) one result dict per gated variant; with
        ``raise_on_failure`` a failing gate raises :class:`ParityError`
        naming the numbers.  Note near-untrained weights can
        legitimately fail int8's argmax check — nearly-uniform logits
        put real ties inside the quantization error, and the gate
        refusing to serve that is the gate working.
        """
        pending = [
            v for v in self._variants.values()
            if v.name != DEFAULT_DTYPE and not v.verified
        ]
        results: dict[str, dict] = {}
        if not pending:
            return results
        x, bucket = self._parity_slice()
        ref = np.asarray(self._run_variant(self._variants[DEFAULT_DTYPE], x))
        registry = self.metrics.registry if self.metrics is not None else None
        for v in pending:
            out = np.asarray(self._run_variant(v, x))
            max_diff = float(np.abs(out - ref).max())
            argmax_ok = bool((out.argmax(axis=1) == ref.argmax(axis=1)).all())
            tolerance = float(
                (tol or {}).get(v.name, PARITY_TOL.get(v.name, 0.25))
            )
            passed = argmax_ok and max_diff <= tolerance
            v.verified = passed
            v.parity = {
                "dtype": v.name,
                "rows": int(bucket),
                "max_abs_logit_diff": max_diff,
                "tolerance": tolerance,
                "argmax_identical": argmax_ok,
                "passed": passed,
            }
            results[v.name] = v.parity
            if registry is not None:
                registry.gauge(
                    "serving_variant_verified",
                    help="1 = the dtype variant passed its parity gate "
                    "and may serve; 0 = refused",
                    dtype=v.name,
                ).set(1.0 if passed else 0.0)
            if sink:
                sink.emit("parity_gate", **v.parity)
        if raise_on_failure:
            failed = [r for r in results.values() if not r["passed"]]
            if failed:
                raise ParityError(
                    "parity gate failed: "
                    + "; ".join(
                        f"{r['dtype']} max|dlogit|={r['max_abs_logit_diff']:.4g}"
                        f" (tol {r['tolerance']:g}), argmax_identical="
                        f"{r['argmax_identical']}"
                        for r in failed
                    )
                )
        return results

    def _parity_slice(self) -> tuple[np.ndarray, int]:
        """The fixed, seeded eval slice every gate dispatches (parity
        gates AND the rollout controller's canary-drift probe) — one
        composition so both speak about the same inputs.  Rides a
        warmed bucket shape: zero new traces."""
        from ..data.transforms import normalize

        fits = [b for b in self.buckets if b <= PARITY_ROWS]
        bucket = fits[-1] if fits else self.buckets[0]
        raw = np.random.RandomState(PARITY_SEED).randint(
            0, 256, (bucket, 28, 28)
        ).astype(np.uint8)
        return normalize(raw), bucket

    def verify_sharded_parity(
        self,
        tol: float | None = None,
        raise_on_failure: bool = False,
        sink=None,
    ) -> dict:
        """Gate a sharded replica's forward against the SINGLE-DEVICE
        reference forward of its model family — the topology twin of
        :meth:`verify_parity`, and the gate a sharded default variant
        must pass before :meth:`launch` will serve it.

        The fixed parity slice is dispatched through the sharded
        forward at an already-warmed bucket (zero new traces) and
        through a jitted single-device reference on the HOST param
        tree; the replica passes iff

        - ``max |logp_sharded - logp_reference| <= tol`` (defaults per
          kind, serving/sharded.SHARDED_PARITY_TOL — pp is gated at
          exactly 0.0, bit-identity), AND
        - argmax is identical on EVERY row.

        EP note: the default serving MoE config carries capacity-factor
        headroom, so routing keeps every token and the gate sees
        bit-identical outputs; a config at the capacity edge whose
        groups drop different tokens than the dense reference FAILS
        here, and that refusal is the gate working (docs/SERVING.md).

        No-op ``{}`` on a DP engine.  Returns (and records on
        :attr:`parity_report` under the default variant's name, with
        ``shard_kind`` in the row) the result dict; ``raise_on_failure``
        raises :class:`ParityError` naming the numbers."""
        if self.shard_kind == "dp":
            return {}
        from . import sharded as shardlib

        v = self._variants[DEFAULT_DTYPE]
        x, bucket = self._parity_slice()
        out = np.asarray(self._run_variant(v, x))
        if self._reference_fn is None:
            self._reference_fn = shardlib.reference_fn(
                self.shard_kind, self._vit_cfg
            )
        ref = np.asarray(self._reference_fn(self._host_served, x))
        max_diff = float(np.abs(out - ref).max())
        argmax_ok = bool((out.argmax(axis=1) == ref.argmax(axis=1)).all())
        tolerance = float(
            shardlib.SHARDED_PARITY_TOL[self.shard_kind]
            if tol is None else tol
        )
        passed = argmax_ok and max_diff <= tolerance
        v.verified = passed
        v.parity = {
            "dtype": v.name,
            "shard_kind": self.shard_kind,
            "devices": len(list(self.mesh.devices.flat)),
            "rows": int(bucket),
            "max_abs_logit_diff": max_diff,
            "tolerance": tolerance,
            "argmax_identical": argmax_ok,
            "passed": passed,
        }
        if self.metrics is not None:
            self.metrics.registry.gauge(
                "serving_variant_verified",
                help="1 = the dtype variant passed its parity gate "
                "and may serve; 0 = refused",
                dtype=f"{v.name}/{self.shard_kind}",
            ).set(1.0 if passed else 0.0)
        if sink:
            sink.emit("parity_gate", **v.parity)
        if raise_on_failure and not passed:
            raise ParityError(
                f"sharded parity gate failed: {self.shard_kind} "
                f"max|dlogp|={max_diff:.4g} (tol {tolerance:g}), "
                f"argmax_identical={argmax_ok}"
            )
        return v.parity

    # -- the registry swap surface (serving/registry.py, rollout.py) ----------
    #
    # Weight mutation enters the engine ONLY through these methods (the
    # jaxlint JL022 idiom): every variant's forward reads ``v.variables``
    # exactly once per dispatch (_run_variant), so one Python attribute
    # reassignment per variant is an atomic cutover — a request is served
    # ENTIRELY by old or entirely by new weights, never torn — and the
    # compiled executables are keyed by shape, taking weights as a call
    # argument, so a swap or canary install adds ZERO traces.

    def _prepare_weights(self, variables: dict[str, Any]):
        """Validate + place an incoming variable tree against the served
        tree: same BN-ness, same structure, same leaf shapes — the
        compiled executables are specialized to those avals, and a
        mismatched tree must be refused here, not crash a dispatch."""
        if self.shard_kind != "dp":
            raise ValueError(
                f"weight publish into a sharded ({self.shard_kind}) "
                "replica is not supported: a swap would have to re-place "
                "the tree under the kind's partition specs and re-gate "
                "parity mid-serve; drain the replica and rebuild it on "
                "the new checkpoint instead (docs/SERVING.md)"
            )
        use_bn = "bn1" in variables.get("params", {})
        if use_bn != self.use_bn:
            raise ValueError(
                f"cannot publish a {'BN' if use_bn else 'non-BN'} "
                f"checkpoint into a {'BN' if self.use_bn else 'non-BN'} "
                "engine: the warmed executables are specialized to the "
                "served tree"
            )
        if use_bn and "batch_stats" not in variables:
            variables = dict(variables)
            variables["batch_stats"] = init_variables(
                jax.random.PRNGKey(0), use_bn=True
            )["batch_stats"]
        served = (
            {"params": variables["params"],
             "batch_stats": variables["batch_stats"]}
            if self.use_bn
            else variables["params"]
        )
        new_leaves, new_def = jax.tree_util.tree_flatten(served)
        cur_leaves, cur_def = jax.tree_util.tree_flatten(
            self._variants[DEFAULT_DTYPE].variables
        )
        if new_def != cur_def or [
            np.shape(a) for a in new_leaves
        ] != [np.shape(a) for a in cur_leaves]:
            raise ValueError(
                "published variable tree does not match the served tree "
                "(structure or leaf shapes differ); versions of one "
                "model must share an architecture — register a new "
                "model name for a new architecture instead"
            )
        digest = weights_digest(served)
        placed = replicate_params(served, self.mesh)
        return variables, digest, placed

    def _variant_weights(self, name: str, variables, placed):
        """The per-variant placed tree for a published checkpoint: int8
        re-quantizes from host params (same construction as
        _build_variant); f32 and bf16 share the placed f32 tree."""
        if name.split(VERSION_SEP)[0] != "int8":
            return placed
        from ..models.quant import quantize_params

        return replicate_params(
            quantize_params(jax.device_get(variables["params"])),
            self.mesh,
        )

    def publish_weights(
        self, variables: dict[str, Any], version: str | None = None
    ) -> str:
        """Atomically republish the PRIMARY served weights in place —
        the replica-tier half of a zero-downtime swap (docs/SERVING.md
        swap state machine; the fleet tier rolls per backend).

        Every primary variant's ``variables`` is reassigned (int8
        re-quantized from the new host params); version-pinned canary
        variants keep their own weights.  In-flight batches that read
        the old tree complete on it; the next dispatch reads the new
        one.  Returns the new weights digest — the caller (rollout
        controller) bumps the response-cache generation with it so no
        stale fill survives the cutover."""
        variables, digest, placed = self._prepare_weights(variables)
        cache: dict[str, Any] = {}
        for key, v in self._variants.items():
            if VERSION_SEP in key:
                continue
            base = key.split(VERSION_SEP)[0]
            if base not in cache:
                cache[base] = self._variant_weights(key, variables, placed)
            v.variables = cache[base]
        self._variables = placed
        self.weights_digest = digest
        if version is not None:
            self.version = str(version)
        return digest

    def install_version(
        self,
        version: str,
        variables: dict[str, Any],
        verified: bool | None = None,
    ) -> str:
        """Install VERSION's weights as parallel variants beside the
        primary — the canary mechanism (serving/rollout.py).

        Each base dtype grows a ``{dtype}@{version}`` twin holding the
        new weights but SHARING the base variant's sentinel and Program
        grid (executables are shape-keyed and take weights per call), so
        the install adds zero traces and canary traffic batches
        separately from primary traffic — no batch ever mixes versions.
        ``verified`` overrides the gate state (default: inherit the base
        variant's — the registry manifest records the version's own
        parity verdict and the rollout controller enforces it)."""
        version = str(version)
        if not version or VERSION_SEP in version:
            raise ValueError(
                f"bad version {version!r}: must be non-empty and free of "
                f"{VERSION_SEP!r}"
            )
        variables, digest, placed = self._prepare_weights(variables)
        for name, base in [
            (n, v) for n, v in self._variants.items() if VERSION_SEP not in n
        ]:
            key = f"{name}{VERSION_SEP}{version}"
            nv = _Variant(
                key, base.jit_fn, base.predict,
                self._variant_weights(name, variables, placed),
                verified=base.verified if verified is None else verified,
            )
            nv.programs = base.programs  # shared shape-keyed grid
            nv.aot = base.aot
            self._variants[key] = nv
        return digest

    def remove_version(self, version: str) -> int:
        """Drop VERSION's pinned variants (rollback, or post-promote
        cleanup).  Shared Programs/sentinels stay with their base
        variants; in-flight batches already dispatched on the removed
        variants complete normally (the batcher holds its own
        reference).  Returns the number of variants removed."""
        suffix = VERSION_SEP + str(version)
        removed = [k for k in self._variants if k.endswith(suffix)]
        for key in removed:
            del self._variants[key]
        return len(removed)

    def version_divergence(self, version: str) -> dict:
        """Max |dlogit| + argmax agreement between the primary f32
        forward and VERSION's pinned f32 variant on the fixed parity
        slice — the rollout controller's canary parity-drift probe.
        Zero new traces (warmed bucket shapes only)."""
        key = f"{DEFAULT_DTYPE}{VERSION_SEP}{version}"
        v = self._variants.get(key)
        if v is None:
            raise ValueError(
                f"version {version!r} is not installed; have "
                f"{[k for k in self._variants if VERSION_SEP in k]}"
            )
        x, bucket = self._parity_slice()
        ref = np.asarray(self._run_variant(self._variants[DEFAULT_DTYPE], x))
        out = np.asarray(self._run_variant(v, x))
        return {
            "version": version,
            "rows": int(bucket),
            "max_abs_logit_diff": float(np.abs(out - ref).max()),
            "argmax_identical": bool(
                (out.argmax(axis=1) == ref.argmax(axis=1)).all()
            ),
        }

    # -- serving --------------------------------------------------------------

    def launch(
        self,
        staged: np.ndarray,
        n: int,
        dtype: str | None = None,
        seg_ids: np.ndarray | None = None,
    ):
        """Dispatch one already-bucket-shaped batch WITHOUT reading back.

        ``staged`` must be exactly a warmed bucket shape (the batcher and
        :meth:`predict_logits` stage through a :class:`StagingPool`, so
        jit only ever sees bucket shapes) and carry ``n`` live rows at
        the front.  ``dtype`` selects a served variant (default f32);
        an unverified variant refuses (:class:`UnverifiedVariantError`).
        Returns the on-device ``[bucket, 10]`` log-probs — jax's async
        dispatch means this does NOT wait for the compute, so the caller
        can overlap host work (padding the next batch) with device
        execution and read the result later with ``np.asarray``.

        Packed mode additionally takes ``seg_ids`` — the int32
        ``[capacity]`` segment-id vector (serving/buckets.py
        ``segment_ids``) mapping each live row to its request and
        padding rows to ``-1``; omitted, the whole buffer dispatches as
        one all-live segment.  ``n`` stays the LIVE row count, so
        ``serving_batch_fill_ratio`` measures real rows over
        rows-capacity in both modes (satellite accounting contract,
        serving/metrics.py).
        """
        v = self._variant_for(dtype)
        bucket = len(staged)
        if seg_ids is not None and not self.packed:
            raise ValueError(
                "seg_ids passed to a bucketed engine; packed=True is the "
                "segment-aware path"
            )
        if seg_ids is not None and len(seg_ids) != bucket:
            raise ValueError(
                f"seg_ids length {len(seg_ids)} does not match the "
                f"{bucket}-row staged buffer"
            )
        if bucket not in self.buckets:
            raise ValueError(
                f"staged batch of {bucket} rows is not a warmed bucket "
                f"{self.buckets}; stage through StagingPool/bucket_for"
            )
        if not 1 <= n <= bucket:
            raise ValueError(f"live rows {n} outside [1, {bucket}]")
        if not v.verified:
            raise UnverifiedVariantError(
                f"variant {v.name!r} has not passed its parity gate "
                "(engine.verify_parity); refusing to serve it"
            )
        logits = self._run_variant(v, staged, seg=seg_ids)
        if self.metrics is not None:
            self.metrics.record_batch(n, bucket)
        return logits

    def predict_logits(
        self, x: np.ndarray, dtype: str | None = None
    ) -> np.ndarray:
        """``[n, 28, 28, 1]`` normalized float32 -> ``[n, 10]`` log-probs.

        Pads into the engine's preallocated staging buffers (zero-alloc
        steady state), dispatches, slices padding back off.  ``n`` above
        the top bucket is chunked (direct callers only — the batcher
        never coalesces past the top bucket).  Serial by design: each
        chunk's result is read before the next stages; the overlapped
        path is the pipelined batcher (serving/batcher.py).
        """
        x = np.asarray(x, np.float32)
        if x.ndim != 1 + len(INPUT_SHAPE) or x.shape[1:] != INPUT_SHAPE:
            raise ValueError(
                f"expected [n, {', '.join(map(str, INPUT_SHAPE))}] input, "
                f"got shape {x.shape}"
            )
        n = len(x)
        if n == 0:
            raise ValueError("empty batch")
        top = self.buckets[-1]
        outs = []
        for start in range(0, n, top):
            chunk = x[start : start + top]
            staged, bucket = self._staging.stage([chunk])
            try:
                logits = self.launch(staged, len(chunk), dtype=dtype)
                outs.append(np.asarray(logits)[: len(chunk)])  # jaxlint: disable=JL009 -- serial direct-call path: each chunk is read inline by contract; the overlapped read lives in the batcher's completion worker
            finally:
                self._staging.release(staged, bucket)
        out = outs[0] if len(outs) == 1 else np.concatenate(outs)
        assert out.shape == (n, NUM_CLASSES)
        return out
