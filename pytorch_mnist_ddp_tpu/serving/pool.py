"""EnginePool: one InferenceEngine replica per device, one warm cache.

The scale-out half of the serving subsystem (the other half is the
router, serving/router.py): everything a single-engine deployment does —
bucket-warmed forward, per-dtype variants behind parity gates, the PR-4
pipelined batcher — replicated once per visible device, behind one
admission front.  Per host, aggregate goodput is then bounded by devices
x per-replica throughput instead of by the one dispatch chain a single
process can drive.

Design points:

- **Explicit device pinning.**  Each replica's engine lives on a 1x1
  mesh over exactly one device (parallel/mesh.single_device_mesh), so
  staging (``device_put`` against the replica's data-axis sharding) and
  dispatch land on that device and nowhere else.  The checkpoint is
  loaded ONCE on the host; each engine places its own device copy.
- **One shared ExecutableStore.**  All replicas warm against a single
  AOT cache directory (``aot_cache``), sized for the full replicas x
  dtypes x buckets grid.  Entries are keyed per device (serialized
  executables pin their compile-time device ids — serving/engine.py),
  so replica k's grid is its own set of entries: a COLD pool start
  compiles each replica's grid (concurrently, through each engine's
  compile-service fan-out), and every later start of the same pool
  shape deserializes the whole grid with **zero traces** — the
  warm-pool contract tests/test_scaleout.py pins via the store's
  hit/miss counters.  Sentinel budgets are per replica and unchanged:
  ``len(buckets)`` traces per variant per replica, ever.
- **Elasticity.**  ``drain(name)`` delegates to the router (mark
  unroutable, then the PR-4 ``stop(drain=True)``); the engine stays
  warm, so ``add(name)`` rebuilds only the batcher — re-adding capacity
  costs no compile, no checkpoint reload, no parity re-gate.

The pool deliberately exposes the single-engine surface the server and
loadgen already consume (``buckets``/``dtypes``/``variant_verified``/
``compile_count``/``warmed``/``use_bn``): ``make_server(pool, metrics,
batcher=router)`` is the whole wiring difference between one replica
and eight.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Sequence

from ..parallel.mesh import replica_devices, single_device_mesh
from .buckets import DEFAULT_MAX_BUCKET, pow2_buckets
from .engine import InferenceEngine
from .metrics import ServingMetrics
from .router import Replica, Router

# Replica names are positional and stable across drain/add cycles:
# r0..rN-1, the labels on every per-replica metric family.
def _replica_name(i: int) -> str:
    return f"r{i}"


class EnginePool:
    """Per-device InferenceEngine replicas sharing weights and AOT cache.

    Parameters mirror :class:`~.engine.InferenceEngine` where they mean
    the same thing; ``replicas`` picks the pool size (default: one per
    local device), ``devices`` overrides the assignment explicitly.
    """

    def __init__(
        self,
        variables: dict[str, Any],
        replicas: int | None = None,
        devices: Sequence | None = None,
        buckets: Sequence[int] | None = None,
        max_bucket: int | None = None,
        dtypes: Sequence[str] | None = None,
        aot_cache: str | None = None,
        metrics: ServingMetrics | None = None,
        conv_impl: str = "conv",
        device_stage: bool | None = None,
        compute_dtype=None,
    ):
        assigned = replica_devices(replicas, devices)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        registry = self.metrics.registry
        dtypes = tuple(dtypes or ())
        if buckets is None:
            # Resolve the default ladder ONCE and hand every engine the
            # explicit result: the store sizing below and the engines'
            # rung grids must agree exactly (a drift under-sizes the
            # shared store, and replica N's warmup would prune replica
            # 1's just-written entries).  Min bucket 1 = n_shards on the
            # single-device meshes every replica runs on.
            buckets = pow2_buckets(1, max_bucket or DEFAULT_MAX_BUCKET)
            max_bucket = None
        self._store = None
        if aot_cache:
            from ..compile import ExecutableStore

            # Sized for the WHOLE pool grid (+ headroom for one config
            # change): per-engine sizing would let replica 8's warmup
            # prune replica 1's just-written entries.
            self._store = ExecutableStore(
                aot_cache,
                registry=registry,
                max_entries=(
                    2 * len(assigned) * (1 + len(dtypes)) * len(buckets) + 4
                ),
            )
        self.engines: list[InferenceEngine] = []
        for device in assigned:
            # Per-replica engine construction carries BOTH pool
            # disciplines jaxlint JL012 checks for: an explicit mesh pin
            # (no replica ends up wherever jax defaults) and the shared
            # AOT store (no replica re-compiles what another persisted).
            self.engines.append(
                InferenceEngine(
                    variables,
                    mesh=single_device_mesh(device),
                    buckets=buckets,
                    max_bucket=max_bucket,
                    compute_dtype=compute_dtype,
                    conv_impl=conv_impl,
                    metrics=self.metrics,
                    dtypes=dtypes,
                    aot_cache=self._store,
                    device_stage=device_stage,
                )
            )
        self.devices = list(assigned)
        self.router: Router | None = None
        self._batcher_kwargs: dict = {}
        self._sink = None
        self._add_lock = threading.Lock()

    # -- construction helpers (the engine's surface, pool-shaped) -------------

    @classmethod
    def from_checkpoint(cls, path: str, **kwargs) -> "EnginePool":
        """Load the checkpoint ONCE, place it per replica."""
        from ..utils.checkpoint import load_inference_variables

        return cls(load_inference_variables(path), **kwargs)

    @classmethod
    def from_seed(cls, seed: int = 1, **kwargs) -> "EnginePool":
        from ..models.net import init_params
        from ..utils.rng import root_key, split_streams

        key = split_streams(root_key(seed))["init"]
        return cls({"params": init_params(key)}, **kwargs)

    # -- single-engine-compatible surface --------------------------------------

    @property
    def n_replicas(self) -> int:
        return len(self.engines)

    @property
    def replica_names(self) -> list[str]:
        return [_replica_name(i) for i in range(len(self.engines))]

    @property
    def buckets(self):
        return self.engines[0].buckets

    @property
    def dtypes(self):
        return self.engines[0].dtypes

    @property
    def default_dtype(self):
        return self.engines[0].default_dtype

    @property
    def use_bn(self):
        return self.engines[0].use_bn

    @property
    def warmed(self) -> bool:
        return all(e.warmed for e in self.engines)

    @property
    def parity_report(self) -> dict:
        return self.engines[0].parity_report

    def variant_verified(self, dtype: str | None) -> bool:
        return all(e.variant_verified(dtype) for e in self.engines)

    def compile_count(self) -> int:
        """Distinct traces across every replica and variant (the /metrics
        ``compiles`` field; 0 in AOT mode, where rungs deserialize)."""
        return sum(e.compile_count() for e in self.engines)

    # -- lifecycle --------------------------------------------------------------

    def warmup(
        self, parallel: bool = True, sink=None, on_rung=None
    ) -> None:
        """Warm every replica's full dtype x bucket grid.

        Replicas warm CONCURRENTLY (one thread each, each fanning its
        own rungs over a compile service when ``parallel``): a cold pool
        pays roughly the wall time of one replica's warmup, and a warm
        pool deserializes everything.  ``on_rung(replica, dtype, bucket,
        pool_compiles)`` reports progress across the whole grid.
        """
        self._sink = sink
        if len(self.engines) == 1 or not parallel:
            for i, engine in enumerate(self.engines):
                self._warm_one(i, engine, parallel, sink, on_rung)
            return
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(self.engines)) as pool:
            futures = [
                pool.submit(self._warm_one, i, engine, parallel, sink, on_rung)
                for i, engine in enumerate(self.engines)
            ]
            for f in futures:
                f.result()  # surface the first warmup failure, not hang

    def _warm_one(self, i, engine, parallel, sink, on_rung) -> None:
        name = _replica_name(i)
        engine.warmup(
            parallel=parallel,
            sink=sink,
            on_rung=(
                None if on_rung is None
                else lambda dtype, bucket, _n: on_rung(
                    name, dtype, bucket, self.compile_count()
                )
            ),
        )

    def verify_parity(
        self, tol=None, raise_on_failure: bool = False, sink=None
    ) -> dict[str, dict]:
        """Gate reduced-precision variants on EVERY replica.

        Replicas hold identical weights, but each runs its own compiled
        program on its own device — the gate proves each replica's
        actual executables, not a representative's.  The returned
        per-dtype results are replica 0's when the whole pool passed;
        a variant that fails on ANY replica returns that replica's
        failing result (tagged with ``"replica"``) so non-raising
        callers — the serving CLI's refuse-to-start gate — see the
        pool-wide verdict, not a representative's.
        """
        results: dict[str, dict] = {}
        for i, engine in enumerate(self.engines):
            name = _replica_name(i)
            r = engine.verify_parity(
                tol=tol, raise_on_failure=raise_on_failure,
                sink=sink if i == 0 else None,  # one gate event set, not N
            )
            for dtype, gate in r.items():
                if not gate["passed"]:
                    gate = dict(gate, replica=name)
                if dtype not in results or (
                    not gate["passed"] and results[dtype]["passed"]
                ):
                    results[dtype] = gate
        return results

    # -- batchers + router -------------------------------------------------------

    def start(
        self, router_policy: str = "cost", sink=None, **batcher_kwargs
    ) -> Router:
        """Start one pipelined batcher per replica and build the router.

        ``batcher_kwargs`` (linger, queue depth, timeouts, in-flight
        window...) are remembered so :meth:`add` rebuilds identical
        batchers later.
        """
        if self.router is not None:
            raise RuntimeError("pool already started")
        self._batcher_kwargs = dict(batcher_kwargs)
        self._sink = sink if sink is not None else self._sink
        replicas = []
        for i, engine in enumerate(self.engines):
            name = _replica_name(i)
            batcher = self._make_batcher(name, engine)
            replica = Replica(name, batcher, engine=engine)
            # The completion worker feeds the router's cost policy.
            batcher.on_complete = replica.observe_latency
            batcher.start()
            replicas.append(replica)
        self.router = Router(
            replicas,
            policy=router_policy,
            registry=self.metrics.registry,
            sink=self._sink,
            metrics=self.metrics,
        )
        return self.router

    def _make_batcher(self, name: str, engine: InferenceEngine):
        from .batcher import MicroBatcher

        return MicroBatcher(
            engine,
            metrics=self.metrics,
            sink=self._sink,
            replica=name,
            **self._batcher_kwargs,
        )

    # -- elasticity ---------------------------------------------------------------

    def drain(self, name: str) -> float:
        """Gracefully remove one replica under live traffic (router
        ordering: unroutable first, then drain queue + window — nothing
        dropped or duplicated).  The engine stays warm for :meth:`add`."""
        if self.router is None:
            raise RuntimeError("pool not started")
        return self.router.drain(name)

    def add(self, name: str | None = None) -> str:
        """Re-add a drained replica (or the next drained one) under live
        traffic.  Only the batcher is rebuilt: the engine kept its warmed
        executables and parity state, so new capacity is routable in
        milliseconds — the warm-elasticity contract."""
        if self.router is None:
            raise RuntimeError("pool not started")
        # Serialized: two concurrent add() calls racing to the same
        # drained replica would each build AND start a batcher, and the
        # attach() loser's worker threads would be orphaned unstoppable.
        with self._add_lock:
            candidates = [
                r for r in self.router.replicas
                if r.state == "drained" and (name is None or r.name == name)
            ]
            if not candidates:
                raise RuntimeError(
                    f"no drained replica "
                    f"{'named ' + name if name else 'available'}"
                )
            replica = candidates[0]
            if replica.engine is None:
                # Registered via Router.attach's new-replica path, which
                # carries no engine to rebuild a batcher around.
                raise RuntimeError(
                    f"replica {replica.name!r} has no engine; re-add it "
                    f"with router.attach(name, batcher)"
                )
            t0 = time.perf_counter()
            batcher = self._make_batcher(replica.name, replica.engine)
            batcher.on_complete = replica.observe_latency
            batcher.start()
            self.router.attach(replica.name, batcher)
        if self._sink:
            self._sink.emit(
                "replica_add", replica=replica.name,
                duration_s=time.perf_counter() - t0,
            )
        return replica.name

    def stop(self, drain: bool = True) -> None:
        if self.router is not None:
            self.router.stop(drain=drain)
