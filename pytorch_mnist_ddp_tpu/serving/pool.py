"""EnginePool: one InferenceEngine replica per device, one warm cache.

The scale-out half of the serving subsystem (the other half is the
router, serving/router.py): everything a single-engine deployment does —
bucket-warmed forward, per-dtype variants behind parity gates, the PR-4
pipelined batcher — replicated once per visible device, behind one
admission front.  Per host, aggregate goodput is then bounded by devices
x per-replica throughput instead of by the one dispatch chain a single
process can drive.

Design points:

- **Explicit device pinning.**  Each replica's engine lives on a 1x1
  mesh over exactly one device (parallel/mesh.single_device_mesh), so
  staging (``device_put`` against the replica's data-axis sharding) and
  dispatch land on that device and nowhere else.  The checkpoint is
  loaded ONCE on the host; each engine places its own device copy.
- **One shared ExecutableStore.**  All replicas warm against a single
  AOT cache directory (``aot_cache``), sized for the full replicas x
  dtypes x buckets grid.  Entries are keyed per device (serialized
  executables pin their compile-time device ids — serving/engine.py),
  so replica k's grid is its own set of entries: a COLD pool start
  compiles each replica's grid (concurrently, through each engine's
  compile-service fan-out), and every later start of the same pool
  shape deserializes the whole grid with **zero traces** — the
  warm-pool contract tests/test_scaleout.py pins via the store's
  hit/miss counters.  Sentinel budgets are per replica and unchanged:
  ``len(buckets)`` traces per variant per replica, ever.
- **Elasticity.**  ``drain(name)`` delegates to the router (mark
  unroutable, then the PR-4 ``stop(drain=True)``); the engine stays
  warm, so ``add(name)`` rebuilds only the batcher — re-adding capacity
  costs no compile, no checkpoint reload, no parity re-gate.
- **Supervision** (docs/ROBUSTNESS.md).  ``start()`` also runs a
  :class:`ReplicaSupervisor`: a replica that fails consecutive launches,
  trips its circuit breaker, or stalls its completion worker is
  quarantined (batcher aborted, its requests retried on survivors) and
  restarted with exponential backoff + seeded jitter — a *warm* restart,
  because the engine and the shared AOT store never left memory, so
  recovery adds ZERO traces.  A restart budget escalates to permanent
  ejection.

The pool deliberately exposes the single-engine surface the server and
loadgen already consume (``buckets``/``dtypes``/``variant_verified``/
``compile_count``/``warmed``/``use_bn``): ``make_server(pool, metrics,
batcher=router)`` is the whole wiring difference between one replica
and eight.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Sequence

from ..analysis.lockwatch import make_lock
from ..liveness import BackoffLadder
from ..parallel.mesh import (
    parse_replica_shapes,
    plan_replica_meshes,
    replica_devices,
    single_device_mesh,
)
from .buckets import DEFAULT_MAX_BUCKET, packed_capacities, pow2_buckets
from .engine import InferenceEngine
from .faults import fault_point
from .metrics import ServingMetrics
from .router import Replica, Router

# Replica names are positional and stable across drain/add cycles:
# r0..rN-1, the labels on every per-replica metric family.
def _replica_name(i: int) -> str:
    return f"r{i}"


class _ReplicaWatch:
    """Supervisor-side bookkeeping for one replica's restart ladder."""

    __slots__ = (
        "attempts", "restarts", "next_restart_t", "quarantined_at",
        "backoff_s", "recovery_s",
    )

    def __init__(self):
        self.attempts = 0          # restarts since the last healthy spell
        self.restarts = 0          # lifetime restarts (the counter's twin)
        self.next_restart_t: float | None = None
        self.quarantined_at: float | None = None
        self.backoff_s = 0.0
        self.recovery_s: list[float] = []


class ReplicaSupervisor:
    """Watches replica health, quarantines the sick, restarts with
    backoff, ejects the incurable (docs/ROBUSTNESS.md state machine).

    The control-plane half of fault tolerance (the data-plane half is
    the router's per-replica :class:`~.router.CircuitBreaker`): a
    polling thread reads three health signals per active replica —

    - **circuit open** — the breaker tripped on consecutive batch
      failures (the fast path already stopped placement);
    - **launch-failure streak** — ``batcher.consecutive_launch_failures``
      at/above ``failure_threshold`` (covers a replica the breaker has
      not tripped yet, e.g. failures interleaved with successes on
      other dtypes);
    - **completion stall** — the oldest launched-but-unread batch older
      than ``stall_timeout_s`` (a wedged device or hung D2H read; the
      chaos harness's ``hang`` op injects exactly this).

    A sick replica is **quarantined**: circuit forced open, batcher
    aborted (queued + in-flight requests complete with
    ``ReplicaDeadError`` → handlers retry on survivors), then
    **restarted** after an exponential backoff with seeded jitter — the
    restart rebuilds only the batcher around the still-warm engine, so
    a warm restart is pure deserialize/reuse, ZERO new traces (the
    sentinel budget is unchanged; pinned in tests/test_faults.py).  The
    circuit re-admits via half-open trial requests.  ``restart_budget``
    consecutive failed recoveries escalate to permanent **ejection**.

    Decoupled from :class:`EnginePool` on purpose: the supervisor needs
    only a router, a ``make_batcher(replica) -> started MicroBatcher``
    factory, and somewhere to record — so the chaos tests drive it
    against fake engines at interactive speed.
    """

    def __init__(
        self,
        router: Router,
        make_batcher,
        registry=None,
        sink=None,
        interval_s: float = 0.1,
        stall_timeout_s: float = 5.0,
        failure_threshold: int = 3,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 10.0,
        backoff_jitter: float = 0.25,
        restart_budget: int = 3,
        seed: int = 0,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.router = router
        self.make_batcher = make_batcher
        self.interval_s = interval_s
        self.stall_timeout_s = stall_timeout_s
        self.failure_threshold = max(1, failure_threshold)
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.backoff_jitter = backoff_jitter
        self.restart_budget = max(0, restart_budget)
        self._registry = registry
        self._sink = sink
        # Seeded: backoff jitter must not make two chaos runs diverge
        # (liveness.py, the ladder every supervisor climbs).
        self._ladder = BackoffLadder(
            base_s=backoff_base_s, max_s=backoff_max_s,
            jitter=backoff_jitter, seed=seed,
        )
        self._watch: dict[str, _ReplicaWatch] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "ReplicaSupervisor":
        if self._thread is not None:
            raise RuntimeError("supervisor already started")
        self._thread = threading.Thread(
            target=self._run, name="serve-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # The supervisor must outlive any single bad tick (a
                # replica torn down mid-inspection): skipping one beat
                # is recoverable, a dead supervisor is not.
                pass

    # -- the state machine ----------------------------------------------------

    def tick(self, now: float | None = None) -> None:
        """One inspection pass (public so tests can step deterministically
        without the polling thread)."""
        now = now if now is not None else time.perf_counter()
        for replica in list(self.router.replicas):
            watch = self._watch.setdefault(replica.name, _ReplicaWatch())
            if replica.state == "active":
                reason = self._sick_reason(replica)
                if reason is not None:
                    self._quarantine(replica, watch, reason, now)
                elif (
                    watch.attempts
                    and replica.breaker is not None
                    and replica.breaker.state == "closed"
                ):
                    # Healed (a trial passed and traffic flows): the next
                    # incident starts a fresh backoff ladder instead of
                    # inheriting this one's escalation.
                    watch.attempts = 0
            elif (
                replica.state == "quarantined"
                and watch.next_restart_t is not None
                and now >= watch.next_restart_t
            ):
                self._restart(replica, watch, now)

    def _sick_reason(self, replica: Replica) -> str | None:
        if replica.breaker is not None and replica.breaker.state == "open":
            return "circuit_open"
        batcher = replica.batcher
        if (getattr(batcher, "consecutive_launch_failures", 0)
                >= self.failure_threshold):
            return "launch_failures"
        age = getattr(batcher, "oldest_inflight_age", lambda: 0.0)()
        if age > self.stall_timeout_s:
            return "completion_stall"
        return None

    def _backoff(self, attempts: int) -> float:
        """Exponential backoff with seeded jitter for the given rung of
        the ladder (``attempts`` completed restart attempts)."""
        return self._ladder.delay_s(attempts)

    def _quarantine(self, replica, watch, reason, now) -> None:
        if watch.attempts >= self.restart_budget:
            self._eject(replica, watch, reason)
            return
        flushed = self.router.quarantine(replica.name, reason=reason)
        backoff = self._backoff(watch.attempts)
        watch.quarantined_at = now
        watch.next_restart_t = now + backoff
        watch.backoff_s = backoff
        # The router already emitted replica_quarantine; log the
        # schedule here so the backoff ladder is reconstructible.
        if self._sink:
            self._sink.emit(
                "replica_restart_scheduled", replica=replica.name,
                reason=reason, attempt=watch.attempts + 1,
                backoff_s=backoff, flushed=flushed,
            )

    def _restart(self, replica, watch, now) -> None:
        watch.attempts += 1
        with self.router._lock:
            replica.state = "restarting"
        try:
            batcher = self.make_batcher(replica)
        except Exception as e:
            # Engine/batcher rebuild failed outright (not a traffic
            # failure).  The budget applies HERE too: _quarantine's
            # check is only reachable from state "active" (a restart
            # that succeeded and re-sickened), so without this a
            # make_batcher that always raises would cycle
            # quarantined→restarting forever — never ejected, never
            # settled (docs/ROBUSTNESS.md promises ejection after
            # restart_budget consecutive failed recoveries).
            if watch.attempts >= self.restart_budget:
                if self._sink:
                    self._sink.emit(
                        "replica_restart", replica=replica.name,
                        attempt=watch.attempts, outcome="restart_failed",
                        error=f"{type(e).__name__}: {e}",
                    )
                self._eject(replica, watch, "restart_failed")
                return
            with self.router._lock:
                replica.state = "quarantined"
            # attempts was already incremented for this try, so the
            # next wait climbs one rung up the same ladder.
            backoff = self._backoff(watch.attempts)
            watch.next_restart_t = now + backoff
            watch.backoff_s = backoff
            if self._sink:
                self._sink.emit(
                    "replica_restart", replica=replica.name,
                    attempt=watch.attempts, outcome="restart_failed",
                    error=f"{type(e).__name__}: {e}", backoff_s=backoff,
                )
            return
        self.router.attach(replica.name, batcher)
        if replica.breaker is not None:
            replica.breaker.half_open()
        watch.restarts += 1
        watch.next_restart_t = None
        recovery = (
            now - watch.quarantined_at
            if watch.quarantined_at is not None else 0.0
        )
        watch.recovery_s.append(recovery)
        if self._registry is not None:
            self._registry.counter(
                "serving_replica_restarts_total",
                help="supervisor restarts per replica (fresh batcher "
                "around the still-warm engine; zero new traces)",
                replica=replica.name,
            ).inc()
        if self._sink:
            self._sink.emit(
                "replica_restart", replica=replica.name,
                attempt=watch.attempts, backoff_s=watch.backoff_s,
                recovery_s=recovery, outcome="restarted",
            )

    def _eject(self, replica, watch, reason) -> None:
        with self.router._lock:
            replica.state = "ejected"
        if replica.breaker is not None:
            replica.breaker.force_open("ejected")
        # Same teardown quarantine gives a sick replica: queued and
        # in-flight requests complete with ReplicaDeadError so their
        # handlers retry on survivors instead of idling out their full
        # deadline — ejection is permanent, so nobody else will ever
        # flush this batcher (Router.stop skips ejected replicas, and
        # abort makes that stop a no-op anyway).
        flushed = replica.batcher.abort()
        watch.next_restart_t = None
        if self._sink:
            self._sink.emit(
                "replica_eject", replica=replica.name, reason=reason,
                attempts=watch.attempts, flushed=flushed,
            )

    # -- reads ----------------------------------------------------------------

    def stats(self) -> dict:
        """Per-replica restart/recovery accounting plus the pooled
        recovery times — the loadgen chaos report's source."""
        per_replica = {
            name: {
                "restarts": w.restarts,
                "attempts_since_healthy": w.attempts,
                "recovery_s": list(w.recovery_s),
            }
            for name, w in self._watch.items()
        }
        all_recoveries = [
            s for w in self._watch.values() for s in w.recovery_s
        ]
        return {
            "replicas": per_replica,
            "restarts_total": sum(
                w.restarts for w in self._watch.values()
            ),
            "mean_recovery_s": (
                sum(all_recoveries) / len(all_recoveries)
                if all_recoveries else None
            ),
        }


class EnginePool:
    """Per-device InferenceEngine replicas sharing weights and AOT cache.

    Parameters mirror :class:`~.engine.InferenceEngine` where they mean
    the same thing; ``replicas`` picks the pool size (default: one per
    local device), ``devices`` overrides the assignment explicitly.

    ``replica_shapes`` (``"tp4,dp,dp,dp,dp"`` or a parsed list) builds a
    HETEROGENEOUS pool instead: each entry is one replica's shard
    topology (parallel/mesh.SHARD_KINDS), multi-device shapes take
    strictly disjoint consecutive device blocks, and every sharded
    replica is parity-gated against the single-device forward at the end
    of :meth:`warmup` — it cannot serve a request before that gate
    passes.  The ViT families (``vtp``/``ep``) cannot mix with the CNN
    kinds in one pool (one checkpoint, one architecture).  Sharded
    pools serve f32 only (``dtypes`` must stay empty).
    """

    def __init__(
        self,
        variables: dict[str, Any],
        replicas: int | None = None,
        devices: Sequence | None = None,
        buckets: Sequence[int] | None = None,
        max_bucket: int | None = None,
        dtypes: Sequence[str] | None = None,
        aot_cache: str | None = None,
        metrics: ServingMetrics | None = None,
        conv_impl: str = "conv",
        device_stage: bool | None = None,
        compute_dtype=None,
        version: str = "",
        packed: bool = False,
        int8_impl: str = "dot",
        replica_shapes=None,
        vit_cfg=None,
        pp_microbatches: int = 2,
    ):
        plans = None
        if replica_shapes is not None:
            shapes = parse_replica_shapes(replica_shapes)
            if replicas is not None and replicas != len(shapes):
                raise ValueError(
                    f"replicas={replicas} disagrees with the "
                    f"{len(shapes)}-entry replica_shapes plan; pass one "
                    "or the other"
                )
            kinds = {kind for kind, _ in shapes}
            vit_kinds = kinds & {"vtp", "ep"}
            if vit_kinds and kinds - vit_kinds:
                raise ValueError(
                    f"replica plan mixes the ViT families {sorted(vit_kinds)} "
                    f"with CNN kinds {sorted(kinds - vit_kinds)}; one pool "
                    "serves one checkpoint, so every replica must serve "
                    "the same model family"
                )
            if len(vit_kinds) > 1:
                raise ValueError(
                    "replica plan mixes 'vtp' (dense ViT) and 'ep' "
                    "(MoE-ViT); those are different param trees"
                )
            if kinds != {"dp"} and dtypes:
                raise ValueError(
                    f"sharded replica shapes serve f32 only; drop dtypes="
                    f"{tuple(dtypes)} (the parity anchor is the single-"
                    "device f32 forward)"
                )
            plans = plan_replica_meshes(shapes, devices)
            assigned = [plan[2].devices.flat[0] for plan in plans]
        else:
            assigned = replica_devices(replicas, devices)
        self.metrics = metrics if metrics is not None else ServingMetrics()
        registry = self.metrics.registry
        dtypes = tuple(dtypes or ())
        # The ladder's floor: 1 on the classic single-device meshes, but
        # a heterogeneous plan can raise it — an EP replica shards rows
        # over its k-wide data axis (every bucket must divide), and a
        # pipeline replica splits every bucket into its microbatches.
        n_min = 1
        if plans is not None:
            for kind, _k, plan_mesh in plans:
                n_min = max(n_min, plan_mesh.shape["data"])
                if kind == "pp":
                    n_min = max(n_min, int(pp_microbatches))
        if buckets is None:
            # Resolve the default ladder ONCE and hand every engine the
            # explicit result: the store sizing below and the engines'
            # rung grids must agree exactly (a drift under-sizes the
            # shared store, and replica N's warmup would prune replica
            # 1's just-written entries).
            buckets = pow2_buckets(n_min, max_bucket or DEFAULT_MAX_BUCKET)
            max_bucket = None
        self.packed = bool(packed)
        if self.packed:
            # Collapse to the rows-capacity ladder HERE, not per engine:
            # the store sizing below must see the PACKED grid.  Sizing
            # from the pow2 ladder while the engines warm the collapsed
            # one would let the grids drift apart — the exact bug class
            # the post-warmup assert in :meth:`warmup` pins shut.
            # (packed_capacities is idempotent, so the engines' own
            # collapse of this list is a no-op; n_min matches the widest
            # data axis any replica in the plan runs on.)
            buckets = packed_capacities(max(buckets), n_min)
            max_bucket = None
        self._store = None
        if aot_cache:
            from ..compile import ExecutableStore, predict_store_size

            # Sized for the WHOLE pool grid (+ headroom for one config
            # change) through the one shared formula (compile/program.py
            # predict_store_size — the same sizing the single engine and
            # the trainer's serve-prewarm handoff use): per-engine sizing
            # would let replica 8's warmup prune replica 1's just-written
            # entries.  Each engine's rungs are Programs over this store.
            self._store = ExecutableStore(
                aot_cache,
                registry=registry,
                max_entries=predict_store_size(
                    len(assigned), 1 + len(dtypes), len(buckets)
                ),
            )
        self.engines: list[InferenceEngine] = []
        # Per-replica engine construction carries BOTH pool disciplines
        # jaxlint JL012 checks for: an explicit mesh pin (no replica
        # ends up wherever jax defaults) and the shared AOT store (no
        # replica re-compiles what another persisted).  Under a
        # replica-shape plan the mesh is the replica's k-device block
        # (parallel/mesh.plan_replica_meshes); classically it is the
        # 1x1 mesh over the replica's one device.
        if plans is not None:
            replica_meshes = [
                (kind, plan_mesh) for kind, _k, plan_mesh in plans
            ]
        else:
            replica_meshes = [
                ("dp", single_device_mesh(device)) for device in assigned
            ]
        for kind, replica_mesh_ in replica_meshes:
            self.engines.append(
                InferenceEngine(
                    variables,
                    mesh=replica_mesh_,
                    buckets=buckets,
                    max_bucket=max_bucket,
                    compute_dtype=compute_dtype,
                    conv_impl=conv_impl,
                    metrics=self.metrics,
                    dtypes=dtypes,
                    aot_cache=self._store,
                    device_stage=device_stage,
                    version=version,
                    packed=packed,
                    int8_impl=int8_impl,
                    shard_kind=kind,
                    vit_cfg=vit_cfg,
                    pp_microbatches=pp_microbatches,
                )
            )
        self.devices = list(assigned)
        # Topology is scrapeable from the first exposition: one
        # serving_shard_devices{replica=} gauge per replica, plus the
        # expert-load family pre-registered for EP pools (CI greps a
        # short smoke's dump).
        for i, engine in enumerate(self.engines):
            self.metrics.record_shard_devices(
                _replica_name(i), len(list(engine.mesh.devices.flat))
            )
            if engine.shard_kind == "ep" and engine._vit_cfg is not None:
                self.metrics.ensure_expert_load(
                    engine._vit_cfg.num_experts
                )
        self.router: Router | None = None
        self.supervisor: ReplicaSupervisor | None = None
        self._batcher_kwargs: dict = {}
        self._sink = None
        self._add_lock = make_lock("pool.add")

    # -- construction helpers (the engine's surface, pool-shaped) -------------

    @classmethod
    def from_checkpoint(cls, path: str, **kwargs) -> "EnginePool":
        """Load the checkpoint ONCE, place it per replica."""
        from ..utils.checkpoint import load_inference_variables

        return cls(load_inference_variables(path), **kwargs)  # jaxlint: disable=JL022 -- pre-registry CLI surface (--checkpoint without --registry); digest ownership stays with the operator

    @classmethod
    def from_seed(cls, seed: int = 1, **kwargs) -> "EnginePool":
        """Seed a pool for the FAMILY the replica shapes imply: dp/tp/pp
        shapes share one CNN checkpoint, vtp shapes a dense ViT, ep
        shapes a MoE ViT (one checkpoint, one architecture — mixing
        families is refused by the constructor, so the seed only has to
        look at which ViT kind, if any, appears)."""
        from ..utils.rng import root_key, split_streams

        key = split_streams(root_key(seed))["init"]
        raw_shapes = kwargs.get("replica_shapes")
        shapes = parse_replica_shapes(raw_shapes) if raw_shapes else []
        kinds = {kind for kind, _ in shapes}
        if kinds & {"vtp", "ep"}:
            from . import sharded as shardlib

            family = "ep" if "ep" in kinds else "vtp"
            if kwargs.get("vit_cfg") is None:
                kwargs["vit_cfg"] = shardlib.default_vit_cfg(family)
            variables = {
                "params": shardlib.seed_params(family, key, kwargs["vit_cfg"])
            }
        else:
            from ..models.net import init_params

            variables = {"params": init_params(key)}
        return cls(variables, **kwargs)

    # -- single-engine-compatible surface --------------------------------------

    @property
    def n_replicas(self) -> int:
        return len(self.engines)

    @property
    def replica_names(self) -> list[str]:
        return [_replica_name(i) for i in range(len(self.engines))]

    @property
    def weights_digest(self) -> str:
        """The pool serves ONE checkpoint placed per replica, so the
        response cache's model digest (serving/cache.py) is any
        replica's — they hash identically by construction."""
        return self.engines[0].weights_digest

    @property
    def version(self):
        return self.engines[0].version

    @property
    def buckets(self):
        return self.engines[0].buckets

    @property
    def dtypes(self):
        return self.engines[0].dtypes

    @property
    def default_dtype(self):
        return self.engines[0].default_dtype

    @property
    def use_bn(self):
        return self.engines[0].use_bn

    @property
    def warmed(self) -> bool:
        return all(e.warmed for e in self.engines)

    @property
    def parity_report(self) -> dict:
        return self.engines[0].parity_report

    def variant_verified(self, dtype: str | None) -> bool:
        return all(e.variant_verified(dtype) for e in self.engines)

    def compile_count(self) -> int:
        """Distinct traces across every replica and variant (the /metrics
        ``compiles`` field; 0 in AOT mode, where rungs deserialize)."""
        return sum(e.compile_count() for e in self.engines)

    # -- registry/rollout surface (serving/rollout.py) -------------------------
    # Each verb applies to EVERY replica, sequentially: a replica's swap
    # is reference-atomic (engine.publish_weights), so mid-iteration the
    # pool serves a mix of old and new WHOLE trees — each request still
    # lands entirely on one version, never a torn tree; the response
    # cache's generation bump (the controller's job) happens after all
    # replicas flip.

    def publish_weights(self, variables, version: str | None = None) -> str:
        digest = ""
        for engine in self.engines:
            digest = engine.publish_weights(variables, version=version)
        return digest

    def install_version(
        self, version: str, variables, verified: bool | None = None
    ) -> str:
        digest = ""
        for engine in self.engines:
            digest = engine.install_version(
                version, variables, verified=verified
            )
        return digest

    def remove_version(self, version: str) -> int:
        return sum(e.remove_version(version) for e in self.engines)

    def version_divergence(self, version: str) -> dict:
        return self.engines[0].version_divergence(version)

    # -- lifecycle --------------------------------------------------------------

    def warmup(
        self, parallel: bool = True, sink=None, on_rung=None
    ) -> None:
        """Warm every replica's full dtype x bucket grid.

        Replicas warm CONCURRENTLY (one thread each, each fanning its
        own rungs over a compile service when ``parallel``): a cold pool
        pays roughly the wall time of one replica's warmup, and a warm
        pool deserializes everything.  ``on_rung(replica, dtype, bucket,
        pool_compiles)`` reports progress across the whole grid.
        """
        self._sink = sink
        if len(self.engines) == 1 or not parallel:
            for i, engine in enumerate(self.engines):
                self._warm_one(i, engine, parallel, sink, on_rung)
            self._check_store_sizing()
            self._gate_sharded(sink)
            return
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(self.engines)) as pool:
            futures = [
                pool.submit(self._warm_one, i, engine, parallel, sink, on_rung)
                for i, engine in enumerate(self.engines)
            ]
            for f in futures:
                f.result()  # surface the first warmup failure, not hang
        self._check_store_sizing()
        self._gate_sharded(sink)

    def _gate_sharded(self, sink) -> None:
        """Parity-gate every SHARDED replica against the single-device
        reference forward of its family, immediately after warmup — a
        sharded replica cannot take a request before this passes
        (engine.launch refuses unverified variants), and a failing gate
        fails the pool start loudly rather than serving wrong logits
        fast (docs/SERVING.md sharded replicas)."""
        for i, engine in enumerate(self.engines):
            if engine.shard_kind == "dp":
                continue
            engine.verify_sharded_parity(raise_on_failure=True, sink=sink)

    def _check_store_sizing(self) -> None:
        """Post-warmup invariant (PR-19 satellite): the shared store was
        sized from the SAME rung ladder the engines actually warmed.

        ``predict_store_size`` is computed in ``__init__`` from
        ``len(buckets)`` — if that list were the pre-collapse pow2
        ladder while packed engines warm the collapsed capacity ladder
        (or vice versa), the cap and the grid drift: an under-sized cap
        means replica N's warmup silently pruned replica 1's
        just-written entries, and every warm start after that re-misses.
        Warmup is the one moment the whole grid is provably on disk, so
        check it here, loudly, instead of debugging ghost recompiles
        later.
        """
        if self._store is None:
            return
        grid = len(self.engines) * (1 + len(self.dtypes)) * len(self.buckets)
        if grid > self._store.MAX_ENTRIES:
            raise RuntimeError(
                f"AOT store sized for {self._store.MAX_ENTRIES} entries but "
                f"the warmed grid needs {grid} "
                f"({len(self.engines)} replicas x {1 + len(self.dtypes)} "
                f"variants x {len(self.buckets)} rungs) — store sizing and "
                f"engine rung ladder disagree (packed collapse drift?)"
            )
        on_disk = sum(
            1 for f in os.listdir(self._store.directory)
            if f.endswith(".jexec")
        )
        if on_disk > self._store.MAX_ENTRIES:
            raise RuntimeError(
                f"AOT store holds {on_disk} entries over its cap "
                f"{self._store.MAX_ENTRIES} — pruning failed to keep the "
                f"warmed grid bounded"
            )

    def _warm_one(self, i, engine, parallel, sink, on_rung) -> None:
        name = _replica_name(i)
        # Dormant fault point (serving/faults.py): chaos schedules can
        # fail one replica's warmup to prove a cold-start failure
        # surfaces instead of silently serving an unwarmed replica.
        fault_point("warmup", name)
        engine.warmup(
            parallel=parallel,
            sink=sink,
            on_rung=(
                None if on_rung is None
                else lambda dtype, bucket, _n: on_rung(
                    name, dtype, bucket, self.compile_count()
                )
            ),
        )

    def verify_parity(
        self, tol=None, raise_on_failure: bool = False, sink=None
    ) -> dict[str, dict]:
        """Gate reduced-precision variants on EVERY replica.

        Replicas hold identical weights, but each runs its own compiled
        program on its own device — the gate proves each replica's
        actual executables, not a representative's.  The returned
        per-dtype results are replica 0's when the whole pool passed;
        a variant that fails on ANY replica returns that replica's
        failing result (tagged with ``"replica"``) so non-raising
        callers — the serving CLI's refuse-to-start gate — see the
        pool-wide verdict, not a representative's.
        """
        results: dict[str, dict] = {}
        for i, engine in enumerate(self.engines):
            name = _replica_name(i)
            r = engine.verify_parity(
                tol=tol, raise_on_failure=raise_on_failure,
                sink=sink if i == 0 else None,  # one gate event set, not N
            )
            for dtype, gate in r.items():
                if not gate["passed"]:
                    gate = dict(gate, replica=name)
                if dtype not in results or (
                    not gate["passed"] and results[dtype]["passed"]
                ):
                    results[dtype] = gate
        return results

    # -- batchers + router -------------------------------------------------------

    def start(
        self,
        router_policy: str = "cost",
        sink=None,
        supervise: bool = True,
        supervisor_kwargs: dict | None = None,
        hedge: bool = False,
        hedge_delay_ms: float | None = None,
        **batcher_kwargs,
    ) -> Router:
        """Start one pipelined batcher per replica and build the router.

        ``batcher_kwargs`` (linger, queue depth, timeouts, in-flight
        window, QoS weights, deadline-aware close...) are remembered so
        :meth:`add` rebuilds identical batchers later.  ``supervise``
        (default on) also starts the :class:`ReplicaSupervisor` —
        quarantine / backoff-restart / ejection of sick replicas
        (docs/ROBUSTNESS.md); ``supervisor_kwargs`` tunes its
        thresholds.  ``hedge`` enables hedged dispatch
        (:class:`~.router.HedgeManager`): straggler requests re-dispatch
        to a second replica after ``hedge_delay_ms`` (None = each
        class's online p99), first completion wins.
        """
        if self.router is not None:
            raise RuntimeError("pool already started")
        self._batcher_kwargs = dict(batcher_kwargs)
        self._sink = sink if sink is not None else self._sink
        replicas = []
        for i, engine in enumerate(self.engines):
            name = _replica_name(i)
            replica = Replica(name, self._make_batcher(name, engine),
                              engine=engine)
            self._hook_and_start(replica, replica.batcher)
            replicas.append(replica)
        self.router = Router(
            replicas,
            policy=router_policy,
            registry=self.metrics.registry,
            sink=self._sink,
            metrics=self.metrics,
            hedge=hedge,
            hedge_delay_ms=hedge_delay_ms,
        )
        if supervise:
            self.supervisor = ReplicaSupervisor(
                self.router,
                self._restart_batcher,
                registry=self.metrics.registry,
                sink=self._sink,
                **(supervisor_kwargs or {}),
            ).start()
        if self._sink is not None:
            self._sink.emit("pool_topology", replicas={
                _replica_name(i): {
                    "shard_kind": engine.shard_kind,
                    "devices": len(list(engine.mesh.devices.flat)),
                }
                for i, engine in enumerate(self.engines)
            })
        return self.router

    @staticmethod
    def _hook_and_start(replica: Replica, batcher) -> None:
        # The completion worker feeds the router's cost policy AND the
        # circuit breaker's success side; the failure hook feeds its
        # trip side; the expiry hook returns half-open trial tokens
        # held by requests that timed out in queue before dispatch.
        batcher.on_complete = replica.observe_latency
        batcher.on_failure = replica.observe_failure
        batcher.on_expire = replica.observe_expiry
        batcher.start()

    def _restart_batcher(self, replica: Replica):
        """Supervisor restart factory: a fresh batcher around the
        replica's still-warm engine — same construction as :meth:`add`,
        so a restart costs no compile, no checkpoint reload, no parity
        re-gate (the zero-new-traces contract, tests/test_faults.py)."""
        if replica.engine is None:
            raise RuntimeError(
                f"replica {replica.name!r} has no engine to restart around"
            )
        batcher = self._make_batcher(replica.name, replica.engine)
        self._hook_and_start(replica, batcher)
        return batcher

    def _make_batcher(self, name: str, engine: InferenceEngine):
        from .batcher import MicroBatcher

        return MicroBatcher(
            engine,
            metrics=self.metrics,
            sink=self._sink,
            replica=name,
            **self._batcher_kwargs,
        )

    # -- elasticity ---------------------------------------------------------------

    def drain(self, name: str) -> float:
        """Gracefully remove one replica under live traffic (router
        ordering: unroutable first, then drain queue + window — nothing
        dropped or duplicated).  The engine stays warm for :meth:`add`."""
        if self.router is None:
            raise RuntimeError("pool not started")
        return self.router.drain(name)

    def add(self, name: str | None = None) -> str:
        """Re-add a drained replica (or the next drained one) under live
        traffic.  Only the batcher is rebuilt: the engine kept its warmed
        executables and parity state, so new capacity is routable in
        milliseconds — the warm-elasticity contract."""
        if self.router is None:
            raise RuntimeError("pool not started")
        # Serialized: two concurrent add() calls racing to the same
        # drained replica would each build AND start a batcher, and the
        # attach() loser's worker threads would be orphaned unstoppable.
        with self._add_lock:
            candidates = [
                r for r in self.router.replicas
                if r.state == "drained" and (name is None or r.name == name)
            ]
            if not candidates:
                raise RuntimeError(
                    f"no drained replica "
                    f"{'named ' + name if name else 'available'}"
                )
            replica = candidates[0]
            if replica.engine is None:
                # Registered via Router.attach's new-replica path, which
                # carries no engine to rebuild a batcher around.
                raise RuntimeError(
                    f"replica {replica.name!r} has no engine; re-add it "
                    f"with router.attach(name, batcher)"
                )
            t0 = time.perf_counter()
            batcher = self._make_batcher(replica.name, replica.engine)
            self._hook_and_start(replica, batcher)
            self.router.attach(replica.name, batcher)
        if self._sink:
            self._sink.emit(
                "replica_add", replica=replica.name,
                duration_s=time.perf_counter() - t0,
            )
        return replica.name

    def stop(self, drain: bool = True) -> None:
        # Supervisor first: a restart racing the shutdown would attach a
        # fresh batcher to a router that is tearing its replicas down.
        if self.supervisor is not None:
            self.supervisor.stop()
            self.supervisor = None
        if self.router is not None:
            self.router.stop(drain=drain)
        # EP expert-load readback lags one dispatch (the engine stashes
        # the device array and materializes it on the NEXT launch, so a
        # readback never blocks the hot path); flush the stash now that
        # the batchers are quiet, then put the final per-expert picture
        # on the JSONL stream for perf_report's sharded-serving section.
        ep_engines = [e for e in self.engines if e.shard_kind == "ep"]
        for engine in ep_engines:
            engine.flush_expert_load()
        if ep_engines and self._sink is not None:
            loads = self.metrics.expert_load_snapshot()
            vals = list(loads.values())
            mean = sum(vals) / len(vals) if vals else 0.0
            self._sink.emit(
                "expert_load", loads=loads,
                imbalance=(max(vals) / mean) if mean else None,
            )
