"""stdlib-only serving endpoint over ``http.server``.

Endpoints:

- ``POST /predict`` — body ``{"instances": [...]}`` where each instance
  is a flat 784-list or a 28x28 (optionally ...x1) nested list of pixel
  values.  By default instances are RAW pixels (0..255) and the server
  applies the training pipeline's exact ToTensor∘Normalize affine
  (data/transforms.normalize — serving must see the distribution the
  model trained on); send ``"normalized": true`` to submit pre-normalized
  float inputs verbatim.  ``"dtype": "bf16"|"int8"`` selects a
  reduced-precision serving variant (400 when not served, 503 until its
  parity gate passes — docs/SERVING.md).  Response:
  ``{"predictions": [digit, ...]}``, plus per-class ``"log_probs"`` when
  ``"return_log_probs": true``.

  With ``Content-Type: application/x-mnist-f32`` the SAME endpoint
  speaks the binary wire protocol (serving/wire.py): a fixed
  little-endian header plus raw float32 rows, parsed with one zero-copy
  ``np.frombuffer`` — no per-pixel text parsing — and answered with raw
  logits bytes (``application/x-mnist-logits-f32``).  JSON stays the
  default and is byte-identical to the pre-wire server; an unrecognized
  content type falls back to JSON parsing (a ``wire_fallback`` event
  notes it).  ``serving_wire_requests_total{format=}`` /
  ``serving_wire_bytes_total{direction=}`` count both paths.

  ``--response-cache N`` adds a content-addressed response cache with
  single-flight dedup at this admission point (serving/cache.py):
  deterministic inference means identical (weights, dtype, rows) can be
  answered from an N-entry LRU, and concurrent identical requests
  coalesce onto ONE dispatch.  Off by default; when off, no code path
  changes.
- ``GET /metrics`` — the full ServingMetrics snapshot (queue depth,
  occupancy, p50/p95/p99 latency, compile count) as JSON; with
  ``Accept: text/plain`` or ``?format=prom``, the same registry renders
  as Prometheus text exposition (obs/export.py) instead — including the
  ``jax_compiles_total`` counter the engine's RecompileSentinel reports.
- ``GET /healthz`` — liveness (cheap, always 200 once serving) + the
  warmed/dtype/replica summary.
- ``GET /readyz`` — readiness, split from liveness (docs/ROBUSTNESS.md):
  503 when zero replicas are routable (all quarantined/draining/ejected
  or circuit-open), with per-replica state
  (``healthy|draining|drained|quarantined|restarting|ejected``) and
  circuit states in the body — the load-balancer pull signal while the
  supervisor heals replicas.

Status mapping (the backpressure contract, docs/SERVING.md): 400 malformed
input, 503 admission rejected (queue full or draining — retry later),
504 deadline expired, 500 engine failure.

``ThreadingHTTPServer`` gives one handler thread per in-flight request;
handlers only parse, ``submit()`` to the batcher's bounded queue, and
wait — the single batcher worker owns all jax dispatch, so concurrency
here costs no device-side contention.  Every handler connection carries
a socket timeout (``request_timeout_s``, default 30 s): a client that
connects and goes silent is closed (idle keep-alive / absent request
line) or answered 408 (stall mid-body) instead of pinning its thread
forever — a fleet front (serving/fleet.py) multiplies held connections,
so a leak here scales with fan-in.
"""

from __future__ import annotations

import json
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

import numpy as np

from ..data.transforms import normalize
from ..obs.export import render_prometheus
from ..models.net import INPUT_SHAPE
from . import wire
from .batcher import MicroBatcher, RejectedError, RequestTimeout
from .cache import COALESCED, HIT, FlightTimeout, ResponseCache
from .engine import InferenceEngine
from .metrics import ServingMetrics
from .qos import QOS_CLASSES


def decode_instances(body: dict) -> np.ndarray:
    """Request JSON -> model-ready ``[n, 28, 28, 1]`` float32 rows.

    Raises ``ValueError`` (-> 400) on anything malformed; the message is
    returned to the client so a bad integration fails debuggably.
    """
    if not isinstance(body, dict):
        raise ValueError("request body must be a JSON object")
    instances = body.get("instances")
    if instances is None:
        raise ValueError('missing "instances"')
    try:
        x = np.asarray(instances, np.float32)
    except (TypeError, ValueError) as e:
        raise ValueError(f"instances are not a rectangular numeric array: {e}")
    if x.ndim == 1 or x.ndim == 2 and x.shape[1:] == (28,):
        raise ValueError(
            "instances must be a LIST of samples (wrap a single sample in "
            "an outer list)"
        )
    h, w, c = INPUT_SHAPE
    if x.ndim == 2 and x.shape[1] == h * w:
        x = x.reshape(-1, h, w)
    elif x.ndim == 3 and x.shape[1:] == (h, w):
        pass
    elif x.ndim == 4 and x.shape[1:] == INPUT_SHAPE:
        x = x[..., 0]
    else:
        raise ValueError(
            f"each instance must be {h * w} flat, {h}x{w}, or {h}x{w}x{c} "
            f"pixels; got array shape {x.shape}"
        )
    if bool(body.get("normalized", False)):
        return x[..., None]
    return normalize(x)


class ServingHandler(BaseHTTPRequestHandler):
    server_version = "mnist-serve/1"
    protocol_version = "HTTP/1.1"

    # Per-request stdout lines would swamp the metrics surface at serving
    # rates; /metrics is the observability story.
    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass

    def setup(self):
        # Handler-connection socket timeout: without one, a client that
        # connects and then goes silent (dead peer, stalled proxy, a
        # fleet front holding keep-alives) pins this handler thread
        # FOREVER — ThreadingHTTPServer threads block in rfile reads
        # with no deadline, and a fleet multiplies held connections by
        # fan-in.  With the timeout set, an idle keep-alive or a
        # never-sent request line times out in handle_one_request
        # (stdlib closes the connection); a mid-body stall surfaces in
        # do_POST, which answers 408 and closes (below).
        self.timeout = getattr(self.server, "request_timeout_s", 30.0)
        super().setup()

    def _send_json(self, status: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - stdlib casing
        srv: ServingHTTPServer = self.server  # type: ignore[assignment]
        url = urlsplit(self.path)
        if url.path == "/healthz":
            engine = srv.engine
            health = {
                "status": "ok",
                "warmed": engine.warmed,
                "buckets": list(engine.buckets),
                # Which dtype variants may serve right now (a False
                # entry is warmed but refused: parity gate not
                # passed — docs/SERVING.md).
                "dtypes": {
                    name: getattr(
                        engine, "variant_verified", lambda _d: True
                    )(name)
                    for name in getattr(engine, "dtypes", ("f32",))
                },
            }
            # Pool mode: per-replica drain state, so an operator can see
            # a drain as capacity (state != active) rather than guess.
            stats = getattr(srv.batcher, "replica_stats", None)
            if stats is not None:
                health["replicas"] = {
                    name: s["state"] for name, s in stats().items()
                }
            # Registry mode: the active route + any live canary, so an
            # operator reads "what is serving" from the same endpoint
            # that says "is it serving".
            if srv.rollout is not None:
                health["rollout"] = srv.rollout.describe()
            self._send_json(200, health)
        elif url.path == "/readyz":
            # Readiness, split from liveness (docs/ROBUSTNESS.md):
            # /healthz answers "is the process alive" (always 200 once
            # serving); /readyz answers "can a request succeed RIGHT
            # NOW" — 503 when zero replicas are routable (all
            # quarantined/draining/ejected or circuit-blocked), so a
            # load balancer pulls the instance without killing it while
            # the supervisor heals replicas.
            routable = getattr(srv.batcher, "routable_count", None)
            payload: dict = {}
            if routable is not None:
                n = routable()
                ready = n > 0
                payload["routable_replicas"] = n
                stats = srv.batcher.replica_stats()
                payload["replicas"] = {
                    # "active" is router vocabulary; the readiness body
                    # speaks health: healthy|draining|drained|
                    # quarantined|restarting|ejected.
                    name: ("healthy" if s["state"] == "active"
                           else s["state"])
                    for name, s in stats.items()
                }
                payload["circuits"] = {
                    name: s["circuit"] for name, s in stats.items()
                }
            else:
                # Single engine: ready once warmed (admission handles
                # the rest via 503 backpressure).
                ready = bool(srv.engine.warmed)
                payload["warmed"] = srv.engine.warmed
            payload["status"] = "ready" if ready else "unready"
            self._send_json(200 if ready else 503, payload)
        elif url.path == "/metrics":
            # Content negotiation: JSON stays the default (the PR-2
            # surface, nothing breaks); Prometheus text is selected by
            # the scraper convention (Accept: text/plain) or explicitly
            # (?format=prom) for curl-without-headers ergonomics.
            wants_prom = (
                parse_qs(url.query).get("format", [""])[0] == "prom"
                or "text/plain" in self.headers.get("Accept", "")
            )
            if wants_prom:
                body = srv.prometheus().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._send_json(200, srv.snapshot())
        else:
            self._send_json(404, {"error": f"no such path {self.path!r}"})

    def _handle_admin(self, srv) -> None:
        """``POST /admin/{swap,canary,rollback,rollout}`` — the rollout
        control surface (serving/rollout.py; fleet mode forwards these
        per-backend, serving/fleet.py).  503 without a registry; rollout
        state errors map to 400 like any other client error."""
        if srv.rollout is None:
            self._send_json(
                503, {"error": "no model registry configured (--registry)"}
            )
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("admin body must be a JSON object")
        except ValueError as e:
            self._send_json(400, {"error": str(e)})
            return
        try:
            if self.path == "/admin/swap":
                result = srv.rollout.swap(
                    str(body["version"]), model=body.get("model")
                )
            elif self.path == "/admin/canary":
                if "version" in body:
                    result = srv.rollout.start_canary(
                        str(body["version"]), float(body["pct"]),
                        model=body.get("model"),
                    )
                else:
                    result = srv.rollout.set_canary_pct(float(body["pct"]))
            elif self.path == "/admin/rollback":
                result = srv.rollout.rollback(
                    reason=str(body.get("reason", "operator"))
                )
            elif self.path == "/admin/rollout":
                result = srv.rollout.describe()
            else:
                self._send_json(
                    404, {"error": f"no such admin path {self.path!r}"}
                )
                return
        except KeyError as e:
            self._send_json(400, {"error": f"missing admin field {e}"})
            return
        except (TypeError, ValueError) as e:
            # RegistryError/RolloutError subclass ValueError.
            self._send_json(400, {"error": str(e)})
            return
        self._send_json(200, result)

    def do_POST(self):  # noqa: N802 - stdlib casing
        srv: ServingHTTPServer = self.server  # type: ignore[assignment]
        if self.path.startswith("/admin/"):
            self._handle_admin(srv)
            return
        if self.path != "/predict":
            self._send_json(404, {"error": f"no such path {self.path!r}"})
            return
        ctype = (
            (self.headers.get("Content-Type") or "")
            .split(";")[0].strip().lower()
        )
        binary = ctype == wire.WIRE_REQUEST_TYPE
        fmt = "binary" if binary else "json"

        # Every /predict outcome goes out through here so the wire
        # accounting (serving_wire_requests_total{format=} +
        # serving_wire_bytes_total{direction=}) counts each exchange
        # exactly once, whatever status it ends with.
        def reply(status, data, content_type="application/json"):
            if srv.metrics is not None:
                srv.metrics.record_wire(
                    fmt, bytes_in=len(raw), bytes_out=len(data)
                )
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def reply_json(status, payload):
            reply(status, json.dumps(payload).encode())

        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            raw = b""
            reply_json(400, {"error": "malformed Content-Length"})
            return
        try:
            raw = self.rfile.read(length)
        except (TimeoutError, OSError):
            # The client sent headers then went silent mid-body: answer
            # 408 (best effort — the peer may be gone) and drop the
            # connection so the handler thread frees NOW, not never.
            raw = b""
            try:
                reply_json(408, {"error": "request body read timed out"})
            except OSError:
                pass
            self.close_connection = True
            return
        deadline_ms = None
        return_log_probs = False
        model = version = None
        route = None
        t_req = time.perf_counter()
        try:
            if binary:
                # Binary wire path (serving/wire.py): one zero-copy
                # frombuffer view instead of ~784·n parsed text floats;
                # a malformed or truncated message is a WireError ->
                # the same 400 contract as malformed JSON, never a
                # hung handler.
                wreq = wire.decode_request(raw)
                x = wire.to_model_input(wreq)
                dtype = None if wreq.dtype == "f32" else wreq.dtype
                qos = wreq.qos
                deadline_ms = wreq.deadline_ms
                model, version = wreq.model, wreq.version
            else:
                if ctype not in ("", "application/json") and srv.sink:
                    # Fallback rule (docs/SERVING.md): any content type
                    # that is not the binary format parses as JSON (the
                    # default protocol), with an operator breadcrumb —
                    # a silent fallback would hide a client that thinks
                    # it is speaking binary but typo'd the header.
                    srv.sink.emit("wire_fallback", content_type=ctype)
                body = json.loads(raw or b"{}")
                x = decode_instances(body)
                dtype = body.get("dtype")
                return_log_probs = bool(body.get("return_log_probs", False))
                model, version = body.get("model"), body.get("version")
            # Variant selection (docs/SERVING.md): "dtype" picks a
            # reduced-precision serving path.  Unknown names are a
            # client error (400); a known-but-unverified variant is
            # rejected by the batcher below (503 — the parity-gate
            # refusal contract).
            if dtype is not None:
                served = [
                    d
                    for d in getattr(srv.engine, "dtypes", ("f32",))
                    # Version-pinned canary keys ("f32@v2") are minted
                    # by the rollout controller below, never accepted
                    # from the wire — a client naming one directly
                    # would bypass the canary split and its breaker.
                    if "@" not in d
                ]
                if not isinstance(dtype, str) or dtype not in served:
                    raise ValueError(
                        f"unknown dtype {dtype!r}; served dtypes: {served}"
                    )
            # QoS class (docs/SERVING.md tail latency): "qos" selects
            # the scheduling class the weighted admission queue orders
            # by; omitted = interactive (the pre-QoS behavior).  An
            # unknown class is a client error, not backpressure.
            if not binary:
                qos = body.get("qos")
            if qos is not None:
                classes = getattr(srv.batcher, "qos_classes", QOS_CLASSES)
                if not isinstance(qos, str) or qos not in classes:
                    raise ValueError(
                        f"unknown qos {qos!r}; classes: {list(classes)}"
                    )
            # Registry routing (docs/SERVING.md model registry): the
            # "model"/"version" fields resolve through the rollout
            # controller — absent fields take the default route (and,
            # when a canary is live, join its deterministic split);
            # without a registry the fields are a client error, not
            # silently ignored traffic misdirection.
            for field, name in ((model, "model"), (version, "version")):
                if field is not None and not isinstance(field, str):
                    raise ValueError(f'"{name}" must be a string')
            if srv.rollout is not None:
                # Assignment hashes the MODEL-READY rows (the two wire
                # formats normalize to bit-identical inputs), so the
                # canary split is reproducible from the payload alone —
                # across replicas, wire formats, and the loadgen's own
                # offline audit (tools/serve_loadgen.py).
                route = srv.rollout.route(
                    model, version,
                    payload=np.ascontiguousarray(x).data,
                )
            elif model is not None or version is not None:
                raise ValueError(
                    "no model registry is configured on this server; "
                    'omit "model"/"version"'
                )
        except ValueError as e:  # WireError subclasses ValueError
            reply_json(400, {"error": str(e)})
            return

        # Per-route outcome feedback (metrics families + the canary
        # breaker -> auto-rollback); no-op without a registry.
        def observe(ok):
            if route is not None:
                srv.rollout.observe(
                    route, ok, time.perf_counter() - t_req
                )
        # Content-addressed response cache + single-flight
        # (serving/cache.py; off unless --response-cache).  The key
        # hashes the MODEL-READY rows, so identical pixels hit across
        # wire formats; a miss claims the flight and the dispatch below
        # feeds every coalesced waiter through first-wins completion.
        cache = srv.response_cache
        flight = key = None
        base_timeout_s = (
            deadline_ms / 1e3 if deadline_ms
            else getattr(srv.batcher, "timeout_s", 30.0)
        )
        # Canary routes dispatch on the version-pinned variant key
        # ("f32@v2"): the batcher coalesces by key, so no batch mixes
        # versions, and the key joins the cache key below, so a cached
        # canary response can never serve a primary request.
        submit_dtype = dtype
        if route is not None and route.canary:
            submit_dtype = route.dtype_key(
                dtype or getattr(srv.engine, "default_dtype", "f32")
            )
        if cache is not None:
            # memoryview, not tobytes(): blake2b hashes the contiguous
            # rows in place — no payload-sized copy on the path whose
            # whole point is deleting per-request host work.
            key = cache.key(
                np.ascontiguousarray(x).data,
                dtype=submit_dtype
                or getattr(srv.engine, "default_dtype", "f32"),
            )
            outcome, val = cache.claim(key)
            if outcome == HIT:
                observe(True)
                self._reply_logits(reply, reply_json, val,
                                   binary, return_log_probs)
                return
            if outcome == COALESCED:
                # Join the claimant's in-flight dispatch on THIS
                # request's own deadline budget (plus the same grace
                # result() allows a launched batch).
                try:
                    logits = val.result(base_timeout_s + 1.0)
                except RejectedError as e:
                    observe(False)
                    reply_json(503, {"error": str(e)})
                    return
                except (RequestTimeout, FlightTimeout) as e:
                    observe(False)
                    reply_json(504, {"error": str(e)})
                    return
                except BaseException as e:
                    # BaseException included: the error is the
                    # CLAIMANT's, re-raised by the flight — whatever
                    # killed that thread, this joiner still owes its
                    # client one HTTP outcome, never a torn connection.
                    observe(False)
                    reply_json(
                        500, {"error": f"{type(e).__name__}: {e}"}
                    )
                    return
                observe(True)
                self._reply_logits(reply, reply_json, logits,
                                   binary, return_log_probs)
                return
            flight = val  # MISS: this request owns the dispatch
        try:
            # Pool mode only: a drain race OR a replica death can flush
            # an already-admitted request back out with RejectedError /
            # ReplicaDeadError AFTER submit() returned (batcher stop()'s
            # post-join flush; the supervisor's abort).  The flushed
            # work never produced a response, so resubmitting cannot
            # duplicate one — the router places each retry on a
            # surviving replica, and every retry runs on the REMAINING
            # deadline budget.  Budget: one attempt per replica (a
            # failure can cascade across the pool exactly once), so a
            # request never outlives a pool-wide outage by spinning.  A
            # single engine that flushes is shutting down outright:
            # nothing to retry onto, and its flush accounting (PR 4) is
            # already client-visible.
            pool_replicas = getattr(srv.batcher, "replicas", None)
            attempts = 1 + len(pool_replicas) if pool_replicas else 1
            t0 = time.perf_counter()
            for attempt in range(attempts):
                # The retry runs on the REMAINING budget of the original
                # admission (router.timeout_s = min over replicas), not a
                # fresh full deadline — the drain race must not double
                # the client's worst-case latency.  Attempt 0 carries the
                # binary header's per-request deadline override when one
                # was sent (None = the server default).
                remaining_ms = (
                    deadline_ms if attempt == 0 else max(
                        0.0,
                        1e3 * (
                            base_timeout_s
                            - (time.perf_counter() - t0)
                        ),
                    )
                )
                request = srv.batcher.submit(
                    x, dtype=submit_dtype, qos=qos, timeout_ms=remaining_ms
                )
                if attempt:
                    # The retry tally (serving_request_retries_total +
                    # request_retry events): transparent resubmissions
                    # are an operator signal even when no client error
                    # surfaces (docs/ROBUSTNESS.md).  Counted AFTER the
                    # submit so a resubmission rejected at admission
                    # (nothing ever placed, the client sees the 503)
                    # doesn't inflate the tally.
                    note_retry = getattr(srv.batcher, "record_retry", None)
                    if note_retry is not None:
                        note_retry()
                try:
                    logits = request.result()
                    break
                except RejectedError:
                    if attempt + 1 < attempts:
                        continue
                    # Pool-mode flushes don't count themselves (the
                    # retry may succeed); a result()-raised rejection
                    # surviving the retry IS the client outcome, and
                    # no submit-side counter fired for it.
                    if attempts > 1 and srv.metrics is not None:
                        srv.metrics.record_rejected()
                    raise
        # A claimed flight resolves on EVERY exit path: a success fills
        # the cache and wakes coalesced waiters with the value; any
        # failure — rejection, expiry, a chaos-killed dispatch — wakes
        # them with the error and caches NOTHING (the never-a-stale-fill
        # rule, serving/cache.py).
        except RejectedError as e:
            if flight is not None:
                cache.fail(key, flight, e)
            observe(False)
            reply_json(503, {"error": str(e)})
            return
        except RequestTimeout as e:
            if flight is not None:
                cache.fail(key, flight, e)
            observe(False)
            reply_json(504, {"error": str(e)})
            return
        except Exception as e:  # engine failure propagated by the worker
            if flight is not None:
                cache.fail(key, flight, e)
            observe(False)
            reply_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        except BaseException as e:
            # A non-Exception (thread teardown, interrupt) must still
            # resolve the claim: a leaked flight would coalesce every
            # future identical request onto a dispatch that never
            # resolves — a permanent per-payload outage.
            if flight is not None:
                cache.fail(key, flight, e)
            raise
        if flight is not None:
            cache.complete(key, flight, np.asarray(logits))
        observe(True)
        self._reply_logits(reply, reply_json, logits, binary, return_log_probs)

    @staticmethod
    def _reply_logits(reply, reply_json, logits, binary, return_log_probs):
        """One computed-or-cached ``[n, classes]`` logits block -> the
        client's 200, on whichever wire the REQUEST arrived (cached
        logits serve both formats bit-identically)."""
        if binary:
            reply(200, wire.encode_response(logits), wire.WIRE_RESPONSE_TYPE)
            return
        payload: dict = {
            "predictions": [int(p) for p in logits.argmax(axis=1)]
        }
        if return_log_probs:
            payload["log_probs"] = [[float(v) for v in row] for row in logits]
        reply_json(200, payload)


class ServingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the serving objects for its handlers."""

    daemon_threads = True

    def __init__(
        self,
        address: tuple[str, int],
        engine: InferenceEngine,
        batcher: MicroBatcher,
        metrics: ServingMetrics,
        request_timeout_s: float = 30.0,
        response_cache: ResponseCache | None = None,
        sink=None,
        rollout=None,
    ):
        super().__init__(address, ServingHandler)
        self.engine = engine
        self.batcher = batcher
        self.metrics = metrics
        # Registry mode (serving/rollout.py): the route resolver + swap/
        # canary/rollback control surface; None = no registry, and the
        # request path is byte-identical to the pre-registry server.
        self.rollout = rollout
        # Handler-connection socket timeout (ServingHandler.setup): an
        # idle or half-dead client frees its thread within this bound.
        self.request_timeout_s = request_timeout_s
        # Host hot path (docs/SERVING.md): the admission-point response
        # cache (None = tier off) and the event sink for cache_hit /
        # wire_fallback breadcrumbs.
        self.response_cache = response_cache
        self.sink = sink
        # Both wire formats scrapeable from the first exposition (the
        # CI grep contract): the server speaks binary unconditionally.
        if metrics is not None:
            metrics.ensure_wire()

    def snapshot(self) -> dict:
        # Pool mode: the router exposes the same depth/inflight surface
        # as a single batcher (aggregated over active replicas) plus a
        # per-replica stats block the JSON payload carries verbatim.
        stats = getattr(self.batcher, "replica_stats", None)
        return self.metrics.snapshot(
            queue_depth=self.batcher.depth(),
            compiles=self.engine.compile_count(),
            buckets=self.engine.buckets,
            inflight=self.batcher.inflight(),
            max_inflight=self.batcher.max_inflight,
            linger_ms=self.batcher.current_linger_ms,
            replicas=stats() if stats is not None else None,
        )

    def prometheus(self) -> str:
        # snapshot() first: it mirrors the batcher/engine-owned values
        # (queue depth, uptime, occupancy) into registry gauges, so the
        # exposition is as current as the JSON surface.
        self.snapshot()
        return render_prometheus(self.metrics.registry)


def make_server(
    engine: InferenceEngine,
    metrics: ServingMetrics,
    host: str = "127.0.0.1",
    port: int = 0,
    batcher=None,
    request_timeout_s: float = 30.0,
    response_cache: int | ResponseCache | None = None,
    sink=None,
    rollout=None,
    **batcher_kwargs,
) -> ServingHTTPServer:
    """Wire engine + metrics + a started batcher into a ready-to-run
    server (port 0 = OS-assigned, for tests and the in-process loadgen;
    the bound port is ``server.server_address[1]``).

    ``batcher`` injects an already-started admission front instead —
    the replica pool's Router (serving/router.py), whose submit/depth/
    inflight surface is batcher-compatible; ``engine`` is then the
    EnginePool (same buckets/dtypes/compile_count surface).

    ``response_cache`` enables the admission-point response cache
    (serving/cache.py): an int is an entry capacity (the CLI's
    ``--response-cache N``), keyed on the engine's weights digest; a
    pre-built :class:`ResponseCache` is used as-is (tests drive the
    invalidation hook through it)."""
    if isinstance(response_cache, int):
        response_cache = ResponseCache(
            response_cache,
            model_digest=getattr(engine, "weights_digest", ""),
            metrics=metrics, sink=sink, scope="server",
        )
    if rollout is not None and rollout.cache is None:
        # The swap path owes the cache a generation bump; hand the
        # controller the cache built here (None stays None: no cache,
        # nothing to invalidate).
        rollout.cache = response_cache
    if batcher is None:
        batcher = MicroBatcher(
            engine, metrics=metrics, sink=sink, **batcher_kwargs
        ).start()
    elif batcher_kwargs:
        raise ValueError(
            "pass batcher kwargs to the pool's start(), not make_server, "
            "when injecting a router"
        )
    return ServingHTTPServer(
        (host, port), engine, batcher, metrics,
        request_timeout_s=request_timeout_s,
        response_cache=response_cache, sink=sink, rollout=rollout,
    )
