"""Expert parallelism: MoE experts sharded over the mesh, all_to_all routing.

The reference has no MoE (SURVEY.md §2c "EP: No") — beyond-parity
capability completing the framework's parallelism matrix (dp/tp/pp/sp/ep).

Layout: the expert dim of every stacked expert weight (models/moe.py,
``[E, ...]``) is sharded over the existing ``data`` mesh axis — the
standard "EP rides the DP axis" deployment, no third axis needed.  Each
device routes its LOCAL tokens (switch top-1, per-shard capacity), then:

  1. ``all_to_all`` #1: the scatter-form dispatch packs ``[E, C, d]``
     expert inputs, device-major over E, and the exchange delivers
     ``[E/S, S*C, d]`` — every device now holds every token routed to
     ITS experts;
  2. the batched expert FFN runs on local expert weights (E/S matmul
     pairs on the MXU);
  3. ``all_to_all`` #2 returns outputs to the token owners, and the slot
     gather scatters them back (weighted by gate prob).

Capacity is per routing group (the per-device token shard), so the drop
pattern matches what a real multi-chip MoE sees; with enough capacity no
token drops and the output is bit-comparable to the dense oracle —
that's the parity pin in tests/test_moe.py.

Gradients: expert-sharded params stay local (their grads are produced on
the owning device from the gathered tokens; the backward of all_to_all is
the reverse all_to_all), replicated params get the VMA-inserted psum —
both arrive as the data-axis SUM of local-mean grads, so everything is
divided by the data degree, exactly like parallel/tp.py / sp.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.moe import (
    MoeOut,
    capacity_for,
    expert_ffn,
    gather_from_slots,
    route,
    scatter_to_slots,
)
from ..models.vit import ViTConfig, vit_moe_forward
from .mesh import DATA_AXIS, place_tree
from ..utils.jax_compat import shard_map

AUX_LOSS_WEIGHT = 0.01  # standard Switch-style weighting of the balance loss


def _check_expert_divisibility(cfg: ViTConfig, mesh: Mesh) -> None:
    num = mesh.shape[DATA_AXIS]
    if cfg.num_experts <= 0:
        raise ValueError("expert parallelism needs cfg.num_experts > 0")
    if cfg.num_experts % num:
        raise ValueError(
            f"num_experts={cfg.num_experts} not divisible by the expert "
            f"axis ({num})"
        )


def moe_mlp_ep(
    mp: dict, x: jax.Array, cfg: ViTConfig, axis_name: str = DATA_AXIS
) -> MoeOut:
    """The expert-parallel MoE MLP, inside shard_map.

    ``x`` is the local token shard ``[b_local, t, d]``; ``mp`` holds the
    FULL gate (replicated) but only the LOCAL slice of each expert stack
    (``[E/S, ...]``, sharded by ep_param_specs).  Routing math is
    models/moe.py's scatter form (same route / scatter_to_slots /
    gather_from_slots / expert_ffn); only the two all_to_all hops are new.
    """
    out, _ = _moe_mlp_ep_with_load(mp, x, cfg, axis_name)
    return out


def _moe_mlp_ep_with_load(
    mp: dict, x: jax.Array, cfg: ViTConfig, axis_name: str = DATA_AXIS
) -> tuple[MoeOut, jax.Array]:
    """:func:`moe_mlp_ep` plus the per-expert KEPT-token counts of this
    shard's routing group, ``f32[E]`` — the raw material of the serving
    layer's expert load-balance metrics (``serving_expert_load``).
    Counts are local (callers psum over ``axis_name``); dropped tokens
    (over capacity) land in the dummy slot and count for no expert, so
    the counts measure tokens actually SERVED by each expert."""
    b, t, d = x.shape
    flat = x.reshape(b * t, d)
    cap = capacity_for(b * t, cfg)
    slot, kept, gate_prob, aux = route(mp["gate"], flat, cfg, cap)
    # kept slots are e*cap + pos; the dummy drop slot E*cap maps to index
    # E, which one_hot zeroes — exactly the "dropped counts nowhere" rule.
    load = jax.nn.one_hot(
        slot // cap, cfg.num_experts, dtype=jnp.float32
    ).sum(axis=0)

    # Pack per-expert inputs (scatter form — no [G, E, C] tensor), device-
    # major over the E dim (the global expert order IS device-major
    # because the stacks are sharded on dim 0).
    xin = scatter_to_slots(flat, slot, kept, cfg, cap)     # [E, C, d]
    # Exchange #1: chunk e-block j -> device j; receive source-major.
    xin = jax.lax.all_to_all(
        xin, axis_name, split_axis=0, concat_axis=1, tiled=True
    )                                                      # [E/S, S*C, d]
    out = expert_ffn(mp, xin)                              # [E/S, S*C, d]
    # Exchange #2: return outputs to their token owners.
    out = jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=0, tiled=True
    )                                                      # [E, C, d]
    y = gather_from_slots(out, slot, kept, gate_prob)
    # The local aux is this shard's load-balance term; psum-mean it so
    # every device carries the same scalar (and the grad contribution is
    # the global mean's, matching the dense oracle's single-group form).
    aux = jax.lax.pmean(aux, axis_name)
    return MoeOut(y.reshape(b, t, d).astype(x.dtype), aux), load


def ep_param_specs(cfg: ViTConfig) -> dict:
    """PartitionSpecs for the MoE-ViT param tree: expert stacks sharded on
    their leading E dim over the data axis, everything else replicated."""
    moe = {
        "gate": {"kernel": P(), "bias": P()},
        "w_in": P(DATA_AXIS),
        "b_in": P(DATA_AXIS),
        "w_out": P(DATA_AXIS),
        "b_out": P(DATA_AXIS),
    }
    dense2 = {"kernel": P(), "bias": P()}
    ln = {"scale": P(), "bias": P()}
    return {
        "embed": dict(dense2),
        "pos_embed": P(),
        "head": dict(dense2),
        "ln_f": dict(ln),
        "blocks": {
            str(i): {
                "ln1": dict(ln),
                "qkv": dict(dense2),
                "proj": dict(dense2),
                "ln2": dict(ln),
                "moe": moe,
            }
            for i in range(cfg.depth)
        },
    }


def ep_state_specs(cfg: ViTConfig):
    """Specs for the full TrainState: Adadelta accumulators shard exactly
    like their params.  ONE definition, used by both the placement helper
    and the jitted step's in/out specs — they can never drift apart."""
    from ..ops.adadelta import AdadeltaState
    from .ddp import TrainState

    ps = ep_param_specs(cfg)
    return TrainState(
        params=ps, opt=AdadeltaState(square_avg=ps, acc_delta=ps), step=P()
    )


def shard_ep_state(state, mesh: Mesh, cfg: ViTConfig):
    """Place a host TrainState (MoE-ViT params + Adadelta accumulators)
    onto the mesh with expert shardings (mesh.place_tree recipe)."""
    return place_tree(state, ep_state_specs(cfg), mesh)


def make_ep_train_step(
    mesh: Mesh,
    cfg: ViTConfig,
    rho: float = 0.9,
    eps: float = 1e-6,
    aux_weight: float = AUX_LOSS_WEIGHT,
    use_flash: bool = False,
):
    """Build the jitted expert-parallel MoE-ViT train step.

    ``step_fn(state, x, y, w, lr) -> (state, losses)``: ``state`` sharded
    per ``shard_ep_state``, ``x/y/w`` over ``data``; the objective is
    ``nll + aux_weight * balance_loss``, ``losses`` reports the nll part
    (one local loss per data shard, the reference's logging semantic).
    """
    from ..ops.adadelta import adadelta_update
    from ..ops.loss import nll_loss
    from .ddp import TrainState

    _check_expert_divisibility(cfg, mesh)
    num_data = mesh.shape[DATA_AXIS]
    state_specs = ep_state_specs(cfg)
    from ..ops.pallas_attention import select_attention

    attention_fn = select_attention(use_flash)

    def local_step(state: TrainState, x, y, w, lr):
        def loss_fn(params):
            logp, aux = vit_moe_forward(
                params, x, cfg,
                attention_fn=attention_fn,
                moe_fn=lambda mp, h: moe_mlp_ep(mp, h, cfg),
            )
            nll = nll_loss(logp, y, w, reduction="mean")
            return nll + aux_weight * aux, nll

        (_, nll), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        grads = jax.tree.map(lambda g: g / num_data, grads)
        params, opt = adadelta_update(
            state.params, grads, state.opt, lr, rho, eps
        )
        return TrainState(params, opt, state.step + 1), nll[None]

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_specs, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=(state_specs, P(DATA_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_ep_predict_step(mesh: Mesh, cfg: ViTConfig, use_flash: bool = False):
    """Build the jitted expert-parallel forward for the serving path.

    ``predict_fn(params, x) -> (log_probs, expert_load)``: ``params``
    sharded per ``ep_param_specs`` (expert stacks split over ``data``),
    ``x``/``log_probs`` sharded by rows over ``data`` (the serving batch
    rides the same axis the experts do — "EP rides DP"), and
    ``expert_load`` a replicated ``f32[E]`` of kept-token counts per
    expert summed over every block and every shard — the expert
    imbalance signal the serving metrics export
    (``serving_expert_load{expert=}``).

    Capacity is per routing group (each device's row shard), so the
    drop pattern differs from the single-device dense forward's one big
    group: with headroom (``cfg.capacity_factor`` >= ~2 at serving
    loads) no token drops and parity is tight; at the capacity edge a
    token kept by one grouping may drop in the other — the documented
    EP parity tolerance (docs/SERVING.md)."""
    _check_expert_divisibility(cfg, mesh)
    if cfg.remat:
        # The load taps below are collected across block_fn calls; under
        # jax.checkpoint those values are region-local tracers and may
        # not escape.  Forward-only serving gains nothing from remat.
        raise ValueError("the EP serving forward does not support cfg.remat")
    from ..ops.pallas_attention import select_attention

    attention_fn = select_attention(use_flash)

    def local_predict(params, x):
        loads: list[jax.Array] = []

        def moe_fn(mp, h):
            out, load = _moe_mlp_ep_with_load(mp, h, cfg)
            loads.append(load)
            return out

        logp, _ = vit_moe_forward(
            params, x, cfg, attention_fn=attention_fn, moe_fn=moe_fn
        )
        # One [E] count vector per block (the trace calls moe_fn once per
        # block); the serving signal is the total over blocks and shards.
        load = jax.lax.psum(sum(loads), DATA_AXIS)
        return logp, load

    sharded = shard_map(
        local_predict,
        mesh=mesh,
        in_specs=(ep_param_specs(cfg), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS), P()),
    )
    return jax.jit(sharded)


def make_ep_eval_step(mesh: Mesh, cfg: ViTConfig, use_flash: bool = False):
    """Jitted EP eval step: expert-parallel forward + the psum'd
    (loss_sum, correct) totals every eval path in the framework shares."""
    from ..ops.loss import nll_loss

    _check_expert_divisibility(cfg, mesh)
    from ..ops.pallas_attention import select_attention

    attention_fn = select_attention(use_flash)

    def local_eval(params, x, y, w):
        logp, _ = vit_moe_forward(
            params, x, cfg, attention_fn=attention_fn,
            moe_fn=lambda mp, h: moe_mlp_ep(mp, h, cfg),
        )
        loss_sum = nll_loss(logp, y, w, reduction="sum")
        correct = ((jnp.argmax(logp, axis=1) == y) * w).sum()
        return jax.lax.psum(jnp.stack([loss_sum, correct]), DATA_AXIS)

    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(ep_param_specs(cfg), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(),
    )
    return jax.jit(sharded)
