"""Supervising launcher CLI (replaces ``torch.distributed.launch``;
SURVEY.md N4, elastic runtime ISSUE 10).

The reference is launched as ``python -m torch.distributed.launch
--nproc_per_node=4 mnist_ddp.py --batch-size 200 --epochs 20`` (reference
README.md:42), which forks one process per GPU and sets
``RANK``/``WORLD_SIZE``/``LOCAL_RANK``.  On TPU the idiomatic topology is
ONE process per host driving all local chips (SPMD), so this launcher:

- single host: sets ``NPROC_PER_NODE=N`` and runs the script in one child
  process; ``init_distributed_mode`` builds an N-device mesh.  On the CPU
  backend it forces N virtual host devices via
  ``--xla_force_host_platform_device_count`` so the same command line
  exercises real sharding on a laptop/CI (SURVEY.md §4).
- multi host (``--nnodes``/``--node_rank``/``--master_addr``/
  ``--master_port``): exports the reference's env contract
  (``RANK``/``WORLD_SIZE``/``MASTER_ADDR``/``MASTER_PORT``) with
  rank = node_rank — one process per node.
- ``--nprocs N``: a multi-RANK gang on THIS host — N processes form an
  N-process world via the rendezvous (each driving ``--nproc_per_node``
  local devices; 1 virtual CPU device each on ``--backend cpu``), which
  is how one box exercises the real multi-controller path (and how the
  distributed chaos harness kills a real rank, tools/train_chaos.py
  ``--distributed``).

Unlike the PR-9-era ``subprocess.call``, every child is SUPERVISED
(parallel/elastic.py GangSupervisor):

- SIGTERM/SIGINT to the launcher forward to every rank's process group,
  so the trainer's ``--preempt-grace-s`` emergency save fires through
  the launcher, and the child's conventional ``128+signum`` exit code
  propagates back out.
- liveness + per-rank heartbeat files detect a dead or hung rank; the
  survivors get a bounded-grace SIGTERM (then SIGKILL) and the gang is
  restarted from the latest coordinated ``--save-state`` archive under
  a seeded exponential-backoff ``--restart-budget`` (escalating to one
  diagnostic + exit 69 when exhausted).  Restarted children see
  ``ELASTIC_RESTART_COUNT`` and resume via the trainer's elastic
  contract; ``--chaos`` clauses are stripped from restarted commands
  (the injected failure describes incarnation 0 only).
- ``--rdzv-timeout-s``/``--rdzv-attempts`` export the bounded-rendezvous
  contract to ``init_distributed_mode`` (parallel/distributed.py), so a
  missing peer fails with a pointed diagnostic instead of hanging.

Usage: ``python -m pytorch_mnist_ddp_tpu.parallel.launch
--nproc_per_node=4 [--backend cpu] mnist_ddp.py ...script args...``
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from .elastic import (
    ENV_HEARTBEAT_FILE,
    ENV_RDZV_ATTEMPTS,
    ENV_RDZV_TIMEOUT_S,
    ENV_RESTART_COUNT,
    ENV_TELEMETRY_DIR,
    GangSupervisor,
    heartbeat_path,
    strip_chaos_args,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU-native distributed launcher")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="devices each process drives on this host")
    p.add_argument("--nprocs", type=int, default=1, metavar="N",
                   help="rank PROCESSES to spawn on this host (an N-process "
                        "world formed via the rendezvous; each drives "
                        "--nproc_per_node local devices)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master_addr", type=str, default="127.0.0.1")
    p.add_argument("--master_port", type=str, default="29500")
    p.add_argument("--backend", type=str, default=None,
                   help="force a JAX platform (e.g. cpu for virtual devices)")
    # Supervision (parallel/elastic.py; docs/ROBUSTNESS.md).
    p.add_argument("--restart-budget", type=int, default=0, metavar="K",
                   help="gang restarts from the latest coordinated archive "
                        "before escalating to one diagnostic + exit 69 "
                        "(default: 0 — no restarts; signals still forward "
                        "and the child's exit code still propagates)")
    p.add_argument("--grace-s", type=float, default=10.0, metavar="S",
                   help="SIGTERM-to-SIGKILL window when stopping survivors "
                        "of a dead rank (budget the trainer's emergency "
                        "save inside it; default: 10)")
    p.add_argument("--backoff-base-s", type=float, default=0.5)
    p.add_argument("--backoff-max-s", type=float, default=30.0)
    p.add_argument("--backoff-seed", type=int, default=0,
                   help="seed for restart-backoff jitter (deterministic "
                        "chaos schedules)")
    p.add_argument("--heartbeat-timeout-s", type=float, default=0.0,
                   metavar="S",
                   help="treat a rank as HUNG when its step-boundary "
                        "heartbeat file goes silent for S seconds (0 = "
                        "liveness only; budget the first step's compile)")
    p.add_argument("--rdzv-timeout-s", type=float, default=60.0, metavar="S",
                   help="total rendezvous budget exported to the children: "
                        "jax.distributed.initialize fails (with a pointed "
                        "diagnostic) instead of hanging past it")
    p.add_argument("--rdzv-attempts", type=int, default=2, metavar="K",
                   help="bounded rendezvous attempts within the budget "
                        "(retry/backoff between them)")
    p.add_argument("--telemetry-dir", type=str, default=None, metavar="DIR",
                   help="launcher telemetry: launch_restarts_total/"
                        "rank_deaths_total/rank_heartbeat_age_seconds in "
                        "DIR/launcher.prom plus rank_death/gang_restart "
                        "JSONL events (DIR is also exported to children "
                        "for their rendezvous events)")
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def _child_env(args, rank: int, restart_count: int, hb_dir: str | None) -> dict:
    env = dict(os.environ)
    env["NPROC_PER_NODE"] = str(args.nproc_per_node)
    multi_rank = args.nprocs > 1 or args.nnodes > 1
    if multi_rank:
        if args.nnodes > 1:
            # One process per node: rank = node_rank (reference contract).
            env["RANK"] = str(args.node_rank)
            env["WORLD_SIZE"] = str(args.nnodes)
        else:
            env["RANK"] = str(rank)
            env["WORLD_SIZE"] = str(args.nprocs)
        env["LOCAL_RANK"] = "0"
        env["MASTER_ADDR"] = args.master_addr
        env["MASTER_PORT"] = args.master_port
        env[ENV_RDZV_TIMEOUT_S] = str(args.rdzv_timeout_s)
        env[ENV_RDZV_ATTEMPTS] = str(args.rdzv_attempts)
    if args.backend:
        env["JAX_PLATFORMS"] = args.backend
        if args.backend == "cpu":
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.nproc_per_node}"
            ).strip()
            # Keep the axon sitecustomize from re-registering the TPU in
            # the child when a CPU run was explicitly requested.
            env.pop("PALLAS_AXON_POOL_IPS", None)
    if hb_dir is not None:
        env[ENV_HEARTBEAT_FILE] = heartbeat_path(hb_dir, rank)
    if args.telemetry_dir:
        env[ENV_TELEMETRY_DIR] = args.telemetry_dir
    env[ENV_RESTART_COUNT] = str(restart_count)
    return env


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.nprocs > 1 and args.nnodes > 1:
        # Every local child would get RANK=node_rank — duplicate process
        # ids wedging the rendezvous until the timeout on EVERY
        # incarnation, burning the restart budget on a flag mistake.
        parser.error(
            "--nprocs (multi-rank gang on one host) and --nnodes "
            "(one process per node) cannot combine: per-node multi-rank "
            "worlds need distinct RANK assignment the env contract "
            "does not carry; launch one --nprocs gang per node with "
            "hand-assigned rank ranges, or drop one of the flags"
        )

    registry = sink = None
    if args.telemetry_dir:
        from ..obs import EventSink, Registry

        registry = Registry()
        sink = EventSink(args.telemetry_dir, filename="events-launcher.jsonl")

    hb_dir = None
    if args.heartbeat_timeout_s > 0:
        import tempfile

        hb_dir = (
            args.telemetry_dir
            if args.telemetry_dir
            else tempfile.mkdtemp(prefix="elastic_hb_")
        )

    def spawn(rank: int, restart_count: int) -> subprocess.Popen:
        script_args = list(args.script_args)
        if restart_count > 0:
            # Restarts run CLEAN: the chaos schedule describes
            # incarnation 0 — re-arming it would just re-kill the rank.
            script_args = strip_chaos_args(script_args)
        cmd = [sys.executable, args.script, *script_args]
        return subprocess.Popen(
            cmd,
            env=_child_env(args, rank, restart_count, hb_dir),
            # Own session per rank: the supervisor signals the whole
            # process group (grace kill reaches grandchildren too).
            start_new_session=True,
        )

    supervisor = GangSupervisor(
        spawn,
        args.nprocs,
        restart_budget=args.restart_budget,
        backoff_base_s=args.backoff_base_s,
        backoff_max_s=args.backoff_max_s,
        seed=args.backoff_seed,
        grace_s=args.grace_s,
        heartbeat_dir=hb_dir,
        heartbeat_timeout_s=args.heartbeat_timeout_s,
        # Transparent single-child mode: no budget, one rank — the
        # child's own exit code passes through (the 128+signum pin).
        propagate_exit=(args.nprocs == 1 and args.restart_budget == 0),
        registry=registry,
        sink=sink,
    )
    supervisor.install_signals()
    try:
        code = supervisor.run()
    finally:
        supervisor.uninstall_signals()
        if sink is not None:
            sink.close()
        if registry is not None:
            from ..obs import write_prometheus

            write_prometheus(
                registry, os.path.join(args.telemetry_dir, "launcher.prom")
            )
    return code


if __name__ == "__main__":
    raise SystemExit(main())
