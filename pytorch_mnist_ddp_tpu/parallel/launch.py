"""Launcher CLI (replaces ``torch.distributed.launch``; SURVEY.md N4).

The reference is launched as ``python -m torch.distributed.launch
--nproc_per_node=4 mnist_ddp.py --batch-size 200 --epochs 20`` (reference
README.md:42), which forks one process per GPU and sets
``RANK``/``WORLD_SIZE``/``LOCAL_RANK``.  On TPU the idiomatic topology is
ONE process per host driving all local chips (SPMD), so this launcher:

- single host: sets ``NPROC_PER_NODE=N`` and runs the script in one child
  process; ``init_distributed_mode`` builds an N-device mesh.  On the CPU
  backend it forces N virtual host devices via
  ``--xla_force_host_platform_device_count`` so the same command line
  exercises real sharding on a laptop/CI (SURVEY.md §4).
- multi host (``--nnodes``/``--node_rank``/``--master_addr``/
  ``--master_port``): exports the reference's env contract
  (``RANK``/``WORLD_SIZE``/``MASTER_ADDR``/``MASTER_PORT``) with
  rank = node_rank — one process per node.

Usage: ``python -m pytorch_mnist_ddp_tpu.parallel.launch
--nproc_per_node=4 [--backend cpu] mnist_ddp.py ...script args...``
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description="TPU-native distributed launcher")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="devices to use on this host")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master_addr", type=str, default="127.0.0.1")
    p.add_argument("--master_port", type=str, default="29500")
    p.add_argument("--backend", type=str, default=None,
                   help="force a JAX platform (e.g. cpu for virtual devices)")
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    env = dict(os.environ)
    env["NPROC_PER_NODE"] = str(args.nproc_per_node)
    if args.nnodes > 1:
        env["RANK"] = str(args.node_rank)
        env["WORLD_SIZE"] = str(args.nnodes)
        env["LOCAL_RANK"] = "0"
        env["MASTER_ADDR"] = args.master_addr
        env["MASTER_PORT"] = args.master_port
    if args.backend:
        env["JAX_PLATFORMS"] = args.backend
        if args.backend == "cpu":
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.nproc_per_node}"
            ).strip()
            # Keep the axon sitecustomize from re-registering the TPU in
            # the child when a CPU run was explicitly requested.
            env.pop("PALLAS_AXON_POOL_IPS", None)

    cmd = [sys.executable, args.script, *args.script_args]
    return subprocess.call(cmd, env=env)


if __name__ == "__main__":
    raise SystemExit(main())
