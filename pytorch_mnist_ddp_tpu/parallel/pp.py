"""Pipeline parallelism for the reference CNN (SURVEY.md §2c).

The reference has no pipeline parallelism (single ``Net.forward``); this
module gives the reserved mesh axis a GPipe-style **stage** decomposition
of the reference CNN:

- **stage 0**: conv1 -> relu -> conv2 -> relu -> maxpool -> dropout(.25)
  -> flatten
- **stage 1**: fc1 -> relu -> dropout(.5) -> fc2 -> log_softmax ->
  weighted NLL

The microbatched ppermute schedule and its hand-written ``custom_vjp``
backward live in parallel/pipeline.py (shared with the ViT pipeline,
parallel/pp_vit.py); this module supplies the CNN's two stage bodies and
the train-step wrapper.

Params stay replicated in HBM (1.2M params; duplication is noise at this
scale) but the *work* is stage-partitioned, and the gradient psum over
the stage axis is exactly the sync a stage-sharded layout would need.

Parity with the DP step is exact (dropout off) and pinned by
tests/test_pp.py; dropout uses per-microbatch folded keys (mask geometry
differs from DP's per-shard masks, as with TP's per-shard masks).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.net import DROPOUT1_RATE, DROPOUT2_RATE, raw_conv_stack
from ..ops.adadelta import adadelta_update
from ..ops.loss import nll_loss
from .ddp import TrainState
from .mesh import DATA_AXIS
from .pipeline import NUM_STAGES, STAGE_AXIS, make_pipeline_loss
from ..utils.jax_compat import shard_map

_FLAT = 9216  # stage-boundary activation width (64 * 12 * 12)


def _stage0_fwd(
    params: dict, x: jax.Array, key: jax.Array, train: bool,
    compute_dtype: jnp.dtype = jnp.float32,
) -> jax.Array:
    """convs + pool (+ dropout1 when training) + flatten:
    [n, 28, 28, 1] -> [n, 9216].  With bf16 the stage-boundary activation
    (the per-tick ppermute payload) travels at half width — the pipeline
    engine discovers its dtype via eval_shape (parallel/pipeline.py)."""
    x = raw_conv_stack(params, x, compute_dtype)
    if train:
        keep = 1.0 - DROPOUT1_RATE
        x = x * jax.random.bernoulli(key, keep, x.shape) / keep
    return x.reshape(x.shape[0], -1)


def _stage1_loss_sum(
    params: dict, act: jax.Array, y: jax.Array, w: jax.Array,
    key: jax.Array, train: bool,
    compute_dtype: jnp.dtype = jnp.float32,
) -> jax.Array:
    """dense head (+ dropout2 when training) + weighted NLL SUM."""
    h = jax.nn.relu(
        act @ params["fc1"]["kernel"].astype(compute_dtype)
        + params["fc1"]["bias"].astype(compute_dtype)
    )
    if train:
        keep = 1.0 - DROPOUT2_RATE
        h = h * jax.random.bernoulli(key, keep, h.shape) / keep
    logits = h @ params["fc2"]["kernel"].astype(compute_dtype) \
        + params["fc2"]["bias"].astype(compute_dtype)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return nll_loss(logp, y, w, reduction="sum")


def _stage1_logp(
    params: dict, act: jax.Array, compute_dtype: jnp.dtype = jnp.float32,
) -> jax.Array:
    """The dense head as the serving stage body: fc1 -> relu -> fc2 ->
    log_softmax, per-row log-probs instead of the training stage's
    weighted NLL sum.  Same op sequence (and therefore the same numerics)
    as the eval-mode DP forward's tail."""
    h = jax.nn.relu(
        act @ params["fc1"]["kernel"].astype(compute_dtype)
        + params["fc1"]["bias"].astype(compute_dtype)
    )
    logits = h @ params["fc2"]["kernel"].astype(compute_dtype) \
        + params["fc2"]["bias"].astype(compute_dtype)
    return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)


def _mb_keys(key: jax.Array, j: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-microbatch dropout keys, identical in forward and backward so
    rematerialized masks replay exactly."""
    kmb = jax.random.fold_in(key, j)
    return jax.random.fold_in(kmb, 1), jax.random.fold_in(kmb, 2)


def make_pp_predict_step(
    mesh: Mesh,
    num_micro: int = 2,
    compute_dtype: jnp.dtype = jnp.float32,
):
    """Build the jitted forward-only pipeline step for the serving path.

    ``predict_fn(params, x) -> log_probs`` with ``params`` replicated and
    ``x``/the output sharded over ``data`` (size 1 on a pure-pipeline
    serving replica).  The schedule is the forward half of
    parallel/pipeline.py: ``num_micro`` microbatches flow through the
    2-stage ring over ``num_micro + 1`` ticks, each device running only
    its own stage's FLOPs (``lax.cond`` activity predicate around a
    ``lax.switch`` on the stage index, one ``ppermute`` hop per tick).
    Microbatch ``j``'s rows materialize on the stage-1 device at tick
    ``j + 1``; the idle stage contributes zeros, so ONE stage-axis psum
    of the collected per-tick rows hands every device the full
    ``[n, 10]`` — no backward, no stash, no custom_vjp.

    Batch sizes must divide by ``num_micro`` (the serving bucket ladder
    is pow2, so any pow2 ``num_micro`` composes)."""
    if mesh.shape[STAGE_AXIS] != NUM_STAGES:
        raise ValueError(
            f"pipeline needs a {NUM_STAGES}-wide '{STAGE_AXIS}' axis, got "
            f"{mesh.shape[STAGE_AXIS]}"
        )
    if num_micro < 1:
        raise ValueError(f"num_micro must be >= 1, got {num_micro}")

    def local_predict(params, x):
        n = x.shape[0]
        if n % num_micro:
            raise ValueError(
                f"batch {n} not divisible by {num_micro} microbatches"
            )
        mb = n // num_micro
        x_mbs = x.reshape(num_micro, mb, *x.shape[1:])
        stage = jax.lax.axis_index(STAGE_AXIS)
        key = jax.random.PRNGKey(0)  # train=False: never consumed
        zero_act = jnp.zeros((mb, _FLAT), jnp.dtype(compute_dtype))
        zero_logp = jnp.zeros((mb, 10), jnp.float32)
        ring = [(i, (i + 1) % NUM_STAGES) for i in range(NUM_STAGES)]
        ticks = num_micro + NUM_STAGES - 1

        def tick(carry, t):
            in_flight = carry
            j = t - stage
            active = jnp.logical_and(j >= 0, j < num_micro)
            jc = jnp.clip(j, 0, num_micro - 1)
            x_mb = jax.lax.dynamic_index_in_dim(x_mbs, jc, keepdims=False)

            def run_stage0():
                act = _stage0_fwd(params, x_mb, key, False, compute_dtype)
                return act, zero_logp

            def run_stage1():
                return zero_act, _stage1_logp(params, in_flight, compute_dtype)

            out, logp = jax.lax.cond(
                active,
                lambda: jax.lax.switch(stage, [run_stage0, run_stage1]),
                lambda: (zero_act, zero_logp),
            )
            moved = jax.lax.ppermute(out, STAGE_AXIS, ring)
            return moved, logp

        _, logps = jax.lax.scan(tick, zero_act, jnp.arange(ticks))
        # Stage 1 emits microbatch j's rows at tick j+1; every other
        # tick/stage contributed zeros, so the stage psum IS the gather.
        rows = jax.lax.psum(logps[1:], STAGE_AXIS)
        return rows.reshape(n, 10)

    sharded = shard_map(
        local_predict,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_pp_train_step(
    mesh: Mesh,
    num_micro: int = 2,
    rho: float = 0.9,
    eps: float = 1e-6,
    dropout: bool = True,
    compute_dtype: jnp.dtype = jnp.float32,
):
    """Build the jitted (data x stage) pipelined train step.

    ``step_fn(state, x, y, w, dropout_key, lr) -> (state, losses)`` — the
    same signature as the DP/TP steps so the trainer can route ``--pp``
    through the common epoch loop.  ``state`` is replicated (P()
    everywhere), ``x/y/w`` are sharded over ``data``, ``losses`` is one
    local mean loss per data shard.  The stage axis must have size
    ``NUM_STAGES`` (2).
    """
    if mesh.shape[STAGE_AXIS] != NUM_STAGES:
        raise ValueError(
            f"pipeline needs a {NUM_STAGES}-wide '{STAGE_AXIS}' axis, got "
            f"{mesh.shape[STAGE_AXIS]}"
        )

    def stage0(params, x_mb, key, j):
        k0, _ = _mb_keys(key, j)
        return _stage0_fwd(params, x_mb, k0, dropout, compute_dtype)

    def stage1(params, act, y_mb, w_mb, key, j):
        _, k1 = _mb_keys(key, j)
        return _stage1_loss_sum(
            params, act, y_mb, w_mb, k1, dropout, compute_dtype
        )

    pipeline_loss = make_pipeline_loss(stage0, stage1, num_micro)

    def local_step(state: TrainState, x, y, w, dropout_key, lr):
        n = x.shape[0]
        if n % num_micro:
            raise ValueError(
                f"shard batch {n} not divisible by {num_micro} microbatches"
            )
        mb = n // num_micro
        key = jax.random.fold_in(dropout_key, state.step)
        key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
        x_mbs = x.reshape(num_micro, mb, *x.shape[1:])
        y_mbs = y.reshape(num_micro, mb)
        w_mbs = w.reshape(num_micro, mb)
        denom = jnp.maximum(w.sum(), 1.0)

        def loss_fn(params):
            return pipeline_loss(params, x_mbs, y_mbs, w_mbs, key) / denom

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        # custom bwd psums over the stage axis; the DP mean over data is
        # explicit here (check_vma=False: nothing is auto-inserted).
        grads = jax.lax.pmean(grads, DATA_AXIS)
        params, opt = adadelta_update(state.params, grads, state.opt, lr, rho, eps)
        return TrainState(params, opt, state.step + 1), loss[None]

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        out_specs=(P(), P(DATA_AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))
