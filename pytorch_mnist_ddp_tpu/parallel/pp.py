"""Pipeline parallelism over the mesh's second axis (SURVEY.md §2c).

The reference has no pipeline parallelism (single ``Net.forward``); this
module gives the reserved mesh axis a GPipe-style **stage** decomposition
of the reference CNN:

- **stage 0**: conv1 -> relu -> conv2 -> relu -> maxpool -> dropout(.25)
  -> flatten
- **stage 1**: fc1 -> relu -> dropout(.5) -> fc2 -> log_softmax ->
  weighted NLL

The per-data-shard batch is split into ``num_micro`` microbatches.  Both
passes are explicit schedules driven by ``lax.scan``, with one
``lax.ppermute`` hop per tick (the ICI neighbor link):

- **forward** (``num_micro + 1`` ticks): stage 0 runs microbatch ``t``
  while stage 1 consumes the activation sent at ``t - 1`` and accumulates
  the loss; arriving activations are stashed for the backward pass.
- **backward** (``num_micro + 1`` ticks, reverse order): stage 1 re-runs
  its microbatch body under ``jax.vjp`` (rematerialization — same folded
  dropout keys, so masks replay exactly), accumulates its param grads,
  and ppermutes the activation cotangent back; stage 1's ppermute partner
  consumes it one tick later for the conv backward.

Each device executes ONLY its own stage's FLOPs: stage selection is a
runtime ``lax.cond`` on the device's stage-axis index — the idiomatic
SPMD form.  Transposing such a ``cond`` nested in this scan+ppermute
SIGABRTs the XLA:CPU runtime (jaxlib in this image), which is why the
round-1 version burned 2x masked FLOPs instead; the fix here is
``jax.custom_vjp``: the backward schedule is hand-written primal-style
code, so autodiff never transposes anything.  This also makes the
pipeline's collective pattern fully explicit — the only cross-device
traffic is the per-tick activation/cotangent ppermute and one stage-axis
``psum`` of the (disjoint) per-stage grad trees.

Params stay replicated in HBM (1.2M params; duplication is noise at this
scale) but the *work* is stage-partitioned, and the gradient psum over
the stage axis is exactly the sync a stage-sharded layout would need.

Parity with the DP step is exact (dropout off) and pinned by
tests/test_pp.py; dropout uses per-microbatch folded keys (mask geometry
differs from DP's per-shard masks, as with TP's per-shard masks).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.net import DROPOUT1_RATE, DROPOUT2_RATE, raw_conv_stack
from ..ops.adadelta import adadelta_update
from ..ops.loss import nll_loss
from .ddp import TrainState
from .mesh import DATA_AXIS, MODEL_AXIS

STAGE_AXIS = MODEL_AXIS  # the reserved second mesh axis doubles as stages
NUM_STAGES = 2
_FLAT = 9216  # stage-boundary activation width (64 * 12 * 12)


def _float0_zeros(v: jax.Array):
    """Cotangent for a non-differentiable (integer) primal."""
    return np.zeros(v.shape, jax.dtypes.float0)


def _stage0_fwd(params: dict, x: jax.Array, key: jax.Array, train: bool) -> jax.Array:
    """convs + pool (+ dropout1 when training) + flatten:
    [n, 28, 28, 1] -> [n, 9216]."""
    x = raw_conv_stack(params, x)
    if train:
        keep = 1.0 - DROPOUT1_RATE
        x = x * jax.random.bernoulli(key, keep, x.shape) / keep
    return x.reshape(x.shape[0], -1)


def _stage1_loss_sum(
    params: dict, act: jax.Array, y: jax.Array, w: jax.Array,
    key: jax.Array, train: bool,
) -> jax.Array:
    """dense head (+ dropout2 when training) + weighted NLL SUM."""
    h = jax.nn.relu(act @ params["fc1"]["kernel"] + params["fc1"]["bias"])
    if train:
        keep = 1.0 - DROPOUT2_RATE
        h = h * jax.random.bernoulli(key, keep, h.shape) / keep
    logits = h @ params["fc2"]["kernel"] + params["fc2"]["bias"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return nll_loss(logp, y, w, reduction="sum")


def _mb_keys(key: jax.Array, j: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-microbatch dropout keys, identical in forward and backward so
    rematerialized masks replay exactly."""
    kmb = jax.random.fold_in(key, j)
    return jax.random.fold_in(kmb, 1), jax.random.fold_in(kmb, 2)


def make_pp_train_step(
    mesh: Mesh,
    num_micro: int = 2,
    rho: float = 0.9,
    eps: float = 1e-6,
    dropout: bool = True,
):
    """Build the jitted (data x stage) pipelined train step.

    ``step_fn(state, x, y, w, dropout_key, lr) -> (state, losses)`` — the
    same signature as the DP/TP steps so the trainer can route ``--pp``
    through the common epoch loop.  ``state`` is replicated (P()
    everywhere), ``x/y/w`` are sharded over ``data``, ``losses`` is one
    local mean loss per data shard.  The stage axis must have size
    ``NUM_STAGES`` (2).
    """
    if mesh.shape[STAGE_AXIS] != NUM_STAGES:
        raise ValueError(
            f"pipeline needs a {NUM_STAGES}-wide '{STAGE_AXIS}' axis, got "
            f"{mesh.shape[STAGE_AXIS]}"
        )
    if num_micro < 1:
        raise ValueError(f"num_micro must be >= 1, got {num_micro}")
    num_data = mesh.shape[DATA_AXIS]
    ring = [(i, (i + 1) % NUM_STAGES) for i in range(NUM_STAGES)]
    ring_rev = [(dst, src) for src, dst in ring]

    def _pipeline_forward(params, x_mbs, y_mbs, w_mbs, key):
        """The scheduled forward: returns (stage-psum'd loss SUM over this
        data shard, stashed arriving activations [ticks, mb, 9216])."""
        stage = jax.lax.axis_index(STAGE_AXIS)
        mb = x_mbs.shape[1]

        def tick(carry, t):
            in_flight = carry  # activation that arrived at this device

            # stage 0 forwards microbatch t; the activity test lives in the
            # cond PREDICATE, so idle ticks take the zeros branch for free
            # (the cond is never transposed — custom_vjp below — so this
            # costs nothing in AD).
            t0 = jnp.clip(t, 0, num_micro - 1)
            x_mb = jax.lax.dynamic_index_in_dim(x_mbs, t0, keepdims=False)
            k0, _ = _mb_keys(key, t0)
            out = jax.lax.cond(
                jnp.logical_and(stage == 0, t < num_micro),
                lambda: _stage0_fwd(params, x_mb, k0, dropout),
                lambda: jnp.zeros((mb, _FLAT), x_mb.dtype),
            )

            # stage 1 consumes the block sent at tick t-1 (idle at t=0
            # takes the zero branch).
            t1 = jnp.clip(t - 1, 0, num_micro - 1)
            y_mb = jax.lax.dynamic_index_in_dim(y_mbs, t1, keepdims=False)
            w_mb = jax.lax.dynamic_index_in_dim(w_mbs, t1, keepdims=False)
            _, k1 = _mb_keys(key, t1)
            part = jax.lax.cond(
                jnp.logical_and(stage == 1, t >= 1),
                lambda: _stage1_loss_sum(
                    params, in_flight, y_mb, w_mb, k1, dropout
                ),
                lambda: jnp.float32(0.0),
            )

            moved = jax.lax.ppermute(out, STAGE_AXIS, ring)
            return moved, (part, in_flight)

        zero = jnp.zeros((mb, _FLAT), x_mbs.dtype)
        _, (parts, stash) = jax.lax.scan(
            tick, zero, jnp.arange(num_micro + NUM_STAGES - 1)
        )
        return jax.lax.psum(parts.sum(), STAGE_AXIS), stash

    @jax.custom_vjp
    def pipeline_loss(params, x_mbs, y_mbs, w_mbs, key):
        loss_sum, _ = _pipeline_forward(params, x_mbs, y_mbs, w_mbs, key)
        return loss_sum

    def pipeline_loss_fwd(params, x_mbs, y_mbs, w_mbs, key):
        loss_sum, stash = _pipeline_forward(params, x_mbs, y_mbs, w_mbs, key)
        return loss_sum, (params, x_mbs, y_mbs, w_mbs, key, stash)

    def pipeline_loss_bwd(res, g):
        """The reverse schedule, hand-written (never a cond transpose).

        Tick s: stage 1 rematerializes microbatch ``num_micro - 1 - s``
        under ``jax.vjp`` (grads for its params + the activation
        cotangent, scaled by ``g``), ppermutes the cotangent back; stage 0
        consumes it at tick ``s + 1`` for the conv backward.  Param-grad
        trees are disjoint per stage; one stage-axis psum at the end makes
        every device hold the full gradient."""
        params, x_mbs, y_mbs, w_mbs, key, stash = res
        stage = jax.lax.axis_index(STAGE_AXIS)
        mb = x_mbs.shape[1]
        zero_grads = jax.tree.map(jnp.zeros_like, params)

        def tick(carry, s):
            g_act_in, acc = carry
            zero_ga = jnp.zeros((mb, _FLAT), x_mbs.dtype)

            def s1_body():
                # stage 1: microbatch j arrived at forward tick j+1
                j = jnp.clip(num_micro - 1 - s, 0, num_micro - 1)
                act = jax.lax.dynamic_index_in_dim(stash, j + 1, keepdims=False)
                y_mb = jax.lax.dynamic_index_in_dim(y_mbs, j, keepdims=False)
                w_mb = jax.lax.dynamic_index_in_dim(w_mbs, j, keepdims=False)
                _, k1 = _mb_keys(key, j)
                _, vjp = jax.vjp(
                    lambda p, a: _stage1_loss_sum(p, a, y_mb, w_mb, k1, dropout),
                    params, act,
                )
                gp, ga = vjp(g)
                return gp, ga

            def s0_body():
                # stage 0: the cotangent arriving at tick s is for the
                # microbatch stage 1 processed at tick s-1
                j = jnp.clip(num_micro - s, 0, num_micro - 1)
                x_mb = jax.lax.dynamic_index_in_dim(x_mbs, j, keepdims=False)
                k0, _ = _mb_keys(key, j)
                _, vjp = jax.vjp(
                    lambda p: _stage0_fwd(p, x_mb, k0, dropout), params
                )
                gp, = vjp(g_act_in)
                return gp, zero_ga

            def idle():
                return zero_grads, zero_ga

            # Activity in the PREDICATES: each device's idle tick takes the
            # free zeros branch instead of computing-then-masking.
            gp, ga = jax.lax.cond(
                jnp.logical_and(stage == 1, s < num_micro),
                s1_body,
                lambda: jax.lax.cond(
                    jnp.logical_and(stage == 0, s >= 1), s0_body, idle
                ),
            )
            acc = jax.tree.map(jnp.add, acc, gp)
            moved = jax.lax.ppermute(ga, STAGE_AXIS, ring_rev)
            return (moved, acc), None

        zero_act = jnp.zeros((mb, _FLAT), x_mbs.dtype)
        (_, acc), _ = jax.lax.scan(
            tick, (zero_act, zero_grads),
            jnp.arange(num_micro + NUM_STAGES - 1),
        )
        # Disjoint per-stage trees -> full gradient everywhere.
        acc = jax.lax.psum(acc, STAGE_AXIS)
        return (
            acc,
            jnp.zeros_like(x_mbs),
            _float0_zeros(y_mbs),
            jnp.zeros_like(w_mbs),
            _float0_zeros(key),
        )

    pipeline_loss.defvjp(pipeline_loss_fwd, pipeline_loss_bwd)

    def local_step(state: TrainState, x, y, w, dropout_key, lr):
        n = x.shape[0]
        if n % num_micro:
            raise ValueError(
                f"shard batch {n} not divisible by {num_micro} microbatches"
            )
        mb = n // num_micro
        key = jax.random.fold_in(dropout_key, state.step)
        key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
        x_mbs = x.reshape(num_micro, mb, *x.shape[1:])
        y_mbs = y.reshape(num_micro, mb)
        w_mbs = w.reshape(num_micro, mb)
        denom = jnp.maximum(w.sum(), 1.0)

        def loss_fn(params):
            return pipeline_loss(params, x_mbs, y_mbs, w_mbs, key) / denom

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        # custom bwd psums over the stage axis; the DP mean over data is
        # explicit here (check_vma=False: nothing is auto-inserted).
        grads = jax.lax.pmean(grads, DATA_AXIS)
        params, opt = adadelta_update(state.params, grads, state.opt, lr, rho, eps)
        return TrainState(params, opt, state.step + 1), loss[None]

    sharded = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(), P()),
        out_specs=(P(), P(DATA_AXIS)),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))
