"""Pipeline parallelism over the mesh's second axis (SURVEY.md §2c).

The reference has no pipeline parallelism (single ``Net.forward``); this
module is the "beyond parity" counterpart of parallel/tp.py, demonstrating
that the same reserved mesh axis also supports a GPipe-style **stage**
decomposition of the reference CNN:

- **stage 0**: conv1 -> relu -> conv2 -> relu -> maxpool -> flatten
- **stage 1**: fc1 -> relu -> fc2 -> log_softmax -> weighted NLL

The per-data-shard batch is split into ``num_micro`` microbatches; a
``lax.scan`` over ``num_micro + 1`` ticks drives the pipeline, and each
tick moves one activation block stage0 -> stage1 through a single
``lax.ppermute`` hop (the ICI neighbor link).  Stage identity is the
device's index on the stage axis, so both stages run the SAME SPMD program
with a runtime ``lax.cond`` selecting their work — the idiomatic way to
express heterogeneous stages under ``shard_map``.

The backward pipeline is not hand-written: ``jax.grad`` transposes the
scan + ppermute into the reverse schedule automatically, and VMA tracking
(check_vma default) inserts the stage/data-axis gradient reductions for
the replicated params, exactly as in parallel/tp.py.  Params are
replicated over the stage axis (each stage reads only its half; at 1.2M
params the duplication is noise — stage-sharding them is the TP module's
job, composition is future work).

Stage selection is arithmetic masking rather than ``lax.cond``: both
stage bodies are traced on every device and the inactive one is masked
out.  ``cond`` would skip the inactive stage's FLOPs, but transposing a
``cond`` nested in this scan+ppermute aborts the XLA:CPU runtime (hard
SIGABRT, jaxlib in this image), and the test mesh is CPU; at two
heterogeneous stages of this size the redundancy is cheap, and a
production pipeline of N homogeneous layers would stage-shard the params
so the SPMD program needs no branch at all.

Parity with the DP step is exact (dropout off) and pinned by
tests/test_pp.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.net import raw_conv_stack
from ..ops.adadelta import adadelta_update
from ..ops.loss import nll_loss
from .ddp import TrainState
from .mesh import DATA_AXIS, MODEL_AXIS

STAGE_AXIS = MODEL_AXIS  # the reserved second mesh axis doubles as stages
NUM_STAGES = 2
_FLAT = 9216  # stage-boundary activation width (64 * 12 * 12)


def _stage0(params: dict, x: jax.Array) -> jax.Array:
    """convs + pool + flatten: [n, 28, 28, 1] -> [n, 9216]."""
    x = raw_conv_stack(params, x)
    return x.reshape(x.shape[0], -1)


def _stage1_loss_sum(params: dict, act: jax.Array, y: jax.Array, w: jax.Array) -> jax.Array:
    """dense head + weighted NLL SUM over the microbatch."""
    h = jax.nn.relu(act @ params["fc1"]["kernel"] + params["fc1"]["bias"])
    logits = h @ params["fc2"]["kernel"] + params["fc2"]["bias"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return nll_loss(logp, y, w, reduction="sum")


def make_pp_train_step(
    mesh: Mesh,
    num_micro: int = 2,
    rho: float = 0.9,
    eps: float = 1e-6,
):
    """Build the jitted (data x stage) pipelined train step.

    ``step_fn(state, x, y, w, lr) -> (state, losses)``: ``state``
    replicated (P() everywhere), ``x/y/w`` sharded over ``data``,
    ``losses`` one local mean loss per data shard.  The stage axis must
    have size ``NUM_STAGES`` (2).  Dropout is not pipelined here — this
    module demonstrates the schedule; use the DP/TP steps for training
    with dropout.
    """
    if mesh.shape[STAGE_AXIS] != NUM_STAGES:
        raise ValueError(
            f"pipeline needs a {NUM_STAGES}-wide '{STAGE_AXIS}' axis, got "
            f"{mesh.shape[STAGE_AXIS]}"
        )
    num_data = mesh.shape[DATA_AXIS]

    def local_step(state: TrainState, x, y, w, lr):
        n = x.shape[0]
        if n % num_micro:
            raise ValueError(f"shard batch {n} not divisible by {num_micro} microbatches")
        mb = n // num_micro
        stage = jax.lax.axis_index(STAGE_AXIS)

        def loss_fn(params):
            x_mbs = x.reshape(num_micro, mb, *x.shape[1:])
            y_mbs = y.reshape(num_micro, mb)
            w_mbs = w.reshape(num_micro, mb)

            def tick(carry, t):
                in_flight = carry  # activation block arriving at stage 1

                # Stage 0 produces microbatch t (its last tick is idle;
                # non-stage-0 devices produce a masked-out zero block).
                t0 = jnp.clip(t, 0, num_micro - 1)
                feed = jax.lax.dynamic_index_in_dim(x_mbs, t0, keepdims=False)
                on0 = jnp.logical_and(stage == 0, t < num_micro)
                out = jnp.where(on0, _stage0(params, feed), 0.0)

                # Stage 1 consumes the block sent at tick t-1 (idle at
                # t=0); masking the sample weights zeroes both the loss
                # contribution and, through AD, the gradients of the idle
                # evaluations.
                t1 = jnp.clip(t - 1, 0, num_micro - 1)
                y_mb = jax.lax.dynamic_index_in_dim(y_mbs, t1, keepdims=False)
                w_mb = jax.lax.dynamic_index_in_dim(w_mbs, t1, keepdims=False)
                on1 = jnp.logical_and(stage == 1, t >= 1)
                part = _stage1_loss_sum(
                    params, in_flight, y_mb, w_mb * on1.astype(w_mb.dtype)
                )

                # One hop down the pipe: stage0 -> stage1 (stage1's output
                # wraps back but is never consumed).
                moved = jax.lax.ppermute(
                    out, STAGE_AXIS,
                    [(i, (i + 1) % NUM_STAGES) for i in range(NUM_STAGES)],
                )
                return moved, part

            # The carry must enter the scan with the same varying-manual-
            # axes type ppermute's output has (varying over both axes).
            zero = jax.lax.pcast(
                jnp.zeros((mb, _FLAT), x.dtype),
                (DATA_AXIS, STAGE_AXIS),
                to="varying",
            )
            _, parts = jax.lax.scan(
                tick, zero, jnp.arange(num_micro + NUM_STAGES - 1)
            )
            # Weighted-mean loss over the shard, computed on stage 1 and
            # shared to every stage (psum of a stage-1-only value).
            total = jax.lax.psum(parts.sum(), STAGE_AXIS)
            return total / jnp.maximum(w.sum(), 1.0)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        # VMA AD pre-reduces over both axes (params are fully replicated);
        # divide the data-axis SUM of local means down to the DDP mean,
        # exactly as in parallel/tp.py.
        grads = jax.tree.map(lambda g: g / num_data, grads)
        params, opt = adadelta_update(state.params, grads, state.opt, lr, rho, eps)
        return TrainState(params, opt, state.step + 1), loss[None]

    sharded = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=(P(), P(DATA_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))
