"""3-D ViT parallelism: data x sequence x tensor in ONE jitted step.

parallel/sp.py shards tokens (ring attention), parallel/tp_vit.py shards
heads and MLP features (Megatron blocks).  The two factorizations are
orthogonal — SP splits attention's token axis, TP its head axis — so they
compose into a ``(data, seq, model)`` mesh with no new collective kinds:

- batch over ``data`` (grad psum, the DDP story),
- tokens over ``seq``  (k/v ``ppermute`` ring per hop, pool psum),
- heads + MLP features over ``model`` (two row-parallel psums per block).

Each device holds ``T/S`` tokens of ``H/M`` heads and computes its
``[b/D, T/S]`` query block against every k/v block of its own heads as the
ring rotates.  This is the mesh shape real long-context transformer
deployments run (DP for throughput, SP for sequence length, TP for model
width); here it is exercised end-to-end on the 8-virtual-device CPU mesh
and in the driver's multichip dryrun.

Gradient semantics: unchanged — under VMA tracking every param cotangent
arrives psum'd over the axes the param is invariant on, i.e. the SUM over
``data`` of local-mean grads (seq/model reductions are part of the same
transpose); divide by the data degree for DDP mean semantics.  Parity is
pinned by tests/test_sp3.py against the single-device ViT recurrence.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.vit import ViTConfig, dense, layer_norm, patchify, tokens_to_logp
from ..ops.adadelta import adadelta_update
from ..ops.loss import nll_loss
from .ddp import TrainState
from .mesh import DATA_AXIS, MODEL_AXIS, make_nd_mesh
from .sp import (
    SEQ_AXIS,
    _check_token_divisibility,
    ring_attention,
    ring_attention_flash,
)
from ..utils.jax_compat import axis_size, shard_map
from .tp_vit import (
    _check_head_divisibility,
    _tp_block,
    shard_vit_tp_state,
    vit_tp_param_specs,
    vit_tp_state_specs,
)

__all__ = [
    "make_3d_mesh",
    "make_sp3_train_step",
    "make_sp3_eval_step",
    "shard_sp3_state",
]


def make_3d_mesh(
    num_data: int | None = None,
    num_seq: int = 1,
    num_model: int = 1,
    devices=None,
) -> Mesh:
    """Build the ``(data, seq, model)`` mesh via :func:`mesh.make_nd_mesh`:
    ``model`` innermost so the per-block row-parallel psums ride adjacent
    ICI links, the seq ring's every-hop ppermutes the next-nearest, and
    the once-per-step gradient allreduce the longest rings."""
    return make_nd_mesh(
        num_data, [(SEQ_AXIS, num_seq), (MODEL_AXIS, num_model)], devices
    )


def shard_sp3_state(state: TrainState, mesh: Mesh, cfg: ViTConfig):
    """Place a host TrainState onto the 3-D mesh: the TP shardings apply
    verbatim (tokens are an activation axis — params never shard over
    ``seq``, so the specs are tp_vit's with ``seq`` unused)."""
    return shard_vit_tp_state(state, mesh, cfg)


def _sp3_vit_forward(
    params: dict, x: jax.Array, cfg: ViTConfig, use_flash: bool = False
) -> jax.Array:
    """The ViT forward over a (token, head) shard, inside shard_map.

    ``x`` is the local data-shard of images (replicated over seq/model);
    this device embeds its ``T/S`` token slice (sp.py's slicing), projects
    its ``H/M`` heads (tp_vit's column split), rides the seq ring for
    attention, and completes proj/mlp_out with model-axis psums."""
    num_seq = axis_size(SEQ_AXIS)
    heads_local = cfg.heads // axis_size(MODEL_AXIS)
    t_local = cfg.num_tokens // num_seq
    start = jax.lax.axis_index(SEQ_AXIS) * t_local

    dt = jnp.bfloat16 if cfg.bf16 else x.dtype
    patches = jax.lax.dynamic_slice_in_dim(
        patchify(x, cfg), start, t_local, axis=1
    ).astype(dt)
    pos = jax.lax.dynamic_slice_in_dim(
        params["pos_embed"], start, t_local, axis=0
    ).astype(dt)
    tokens = dense(patches, params["embed"]) + pos
    _ring = ring_attention_flash if use_flash else ring_attention
    for i in range(cfg.depth):
        tokens = _tp_block(
            params["blocks"][str(i)], tokens, cfg, heads_local,
            attention_fn=lambda q, k, v: _ring(q, k, v, SEQ_AXIS),
        )
    tokens = layer_norm(tokens, params["ln_f"])
    pooled = (
        jax.lax.psum(tokens.astype(jnp.float32).sum(axis=1), SEQ_AXIS)
        / cfg.num_tokens
    )
    return tokens_to_logp(params, pooled)


def _check(cfg: ViTConfig, mesh: Mesh) -> None:
    _check_token_divisibility(cfg, mesh)
    _check_head_divisibility(cfg, mesh)


def make_sp3_train_step(
    mesh: Mesh, cfg: ViTConfig, rho: float = 0.9, eps: float = 1e-6,
    use_flash: bool = False,
):
    """Build the jitted 3-D (data x seq x model) ViT train step.

    ``step_fn(state, x, y, w, lr) -> (state, losses)`` with ``state``
    sharded per tp_vit's specs, ``x/y/w`` sharded over ``data``, ``losses``
    one local loss per data shard."""
    _check(cfg, mesh)
    num_data = mesh.shape[DATA_AXIS]
    state_specs = vit_tp_state_specs(cfg)

    def local_step(state: TrainState, x, y, w, lr):
        def loss_fn(params):
            logp = _sp3_vit_forward(params, x, cfg, use_flash=use_flash)
            return nll_loss(logp, y, w, reduction="mean")

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        grads = jax.tree.map(lambda g: g / num_data, grads)
        params, opt = adadelta_update(
            state.params, grads, state.opt, lr, rho, eps
        )
        return TrainState(params, opt, state.step + 1), loss[None]

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_specs, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=(state_specs, P(DATA_AXIS)),
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_sp3_eval_step(mesh: Mesh, cfg: ViTConfig, use_flash: bool = False):
    """Jitted 3-D eval step: the (token, head)-sharded forward + the
    psum'd (loss_sum, correct) totals every eval path shares."""
    _check(cfg, mesh)

    def local_eval(params, x, y, w):
        logp = _sp3_vit_forward(params, x, cfg, use_flash=use_flash)
        loss_sum = nll_loss(logp, y, w, reduction="sum")
        correct = ((jnp.argmax(logp, axis=1) == y) * w).sum()
        return jax.lax.psum(jnp.stack([loss_sum, correct]), DATA_AXIS)

    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(
            vit_tp_param_specs(cfg),
            P(DATA_AXIS),
            P(DATA_AXIS),
            P(DATA_AXIS),
        ),
        out_specs=P(),
    )
    return jax.jit(sharded)
